"""Llama-3-style decoder-only transformer, TPU-first.

The flagship model (BASELINE.json config 4: Llama-3-8B FSDP on a v5p-64).
The reference has no transformer at all (its models are MLPs, reference
tests/utils.py:96-120) — this is net-new capability designed for the MXU:

  * bf16 activations, f32 RMSNorm reductions and softmax;
  * GQA attention through the pallas flash kernel (ops/pallas/flash.py);
  * SwiGLU MLP — two fused [D, 2F] projections keep matmuls large;
  * `lax.scan` over layers (one compiled layer body, L-step scan: compile
    time and HBM program size O(1) in depth) with optional
    `jax.checkpoint` rematerialization per layer;
  * sharding by annotation: `param_specs()` returns Megatron-style
    PartitionSpecs (column-split QKV/gate, row-split O/down) on the
    `tensor` axis, token-embedding sharded on `tensor`, everything
    FSDP-shardable on its largest free axis — the strategies compose
    these over the mesh;
"""
from __future__ import annotations

import dataclasses
import functools
from functools import partial
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.core.module import TpuModule
from ray_lightning_tpu.ops.attention import (
    dot_product_attention,
    flash_attention,
)
from ray_lightning_tpu.ops.fused_ce import fused_cross_entropy
from ray_lightning_tpu.ops.ring_attention import ring_attention
from ray_lightning_tpu.ops.ulysses import ulysses_attention
from ray_lightning_tpu.ops.norms import rms_norm
from ray_lightning_tpu.ops.rope import apply_rope, rope_frequencies


# f32-accumulating dense dots (numcheck RLT801's sanctioned
# single-rounding shape; see ops/precision.py for the full contract)
from ray_lightning_tpu.ops.precision import (
    f32_acc_dot_general as _f32_acc_dot_general,
    f32_out_dot_general as _f32_out_dot_general,
)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    hidden_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    remat: bool = True
    #: what the per-layer checkpoint saves: "nothing" (max memory savings,
    #: full recompute in backward), "dots" (save matmul outputs, recompute
    #: only elementwise — the usual best speed/memory point when HBM
    #: allows), "attn_out" (save the named attention residuals — q/k/v,
    #: the kernel output, and its logsumexp — so the backward skips the
    #: QKV projections, RoPE, and the flash forward: the attention share
    #: of the recompute tax, for ~200 MB/layer at B=8 S=2048; everything
    #: else still recomputes). Ignored when remat=False.
    remat_policy: str = "nothing"
    scan_layers: bool = True
    use_flash: bool = True
    #: shard attention over the mesh's `seq` axis — long-context training
    #: where one device cannot hold the full sequence's KV. Takes effect
    #: when the strategy's mesh has seq > 1.
    seq_parallel: bool = False
    #: "ring" (ppermute KV ring, ops/ring_attention.py — O(S/n) memory,
    #: any head count) or "ulysses" (head/sequence all_to_all,
    #: ops/ulysses.py — two collectives, needs heads % seq == 0).
    seq_parallel_mode: str = "ring"
    #: fused chunked cross-entropy (ops/fused_ce.py): training/eval loss
    #: never materializes the [B, S, V] logits — the dominant activation
    #: at V=128256. predict/generate still produce real logits.
    #: None = auto: fused for large vocabularies (>= 64k, where the
    #: materialized logits dominate HBM and may not compile at all),
    #: materialized otherwise (marginally faster, bit-identical to the
    #: historical loss path). Set True/False to force.
    fused_ce: Optional[bool] = None
    #: logits tile height for the fused CE scan (C×V live logits memory)
    ce_chunk_tokens: int = 1024
    #: compute the fused CE's gradients inline in the forward scan
    #: (ops/fused_ce.py _ce_inline) instead of rematerializing each
    #: logits tile in backward — removes the lm_head recompute tax
    #: (~one [C, D]×[D, V] pass per step) for ~D×V f32 extra residual
    #: memory. Only meaningful when the fused path is active.
    ce_inline_bwd: bool = False
    #: >0 enables the GPipe decoder path (ops/pipeline.py) when the mesh
    #: has pipe > 1: the scanned layer stack is stage-split over `pipe`
    #: and this many microbatches flow through per step. Requires
    #: scan_layers (the stacked param layout IS the pipeline's) and
    #: composes with data/fsdp; tensor/seq stay off the pipeline path.
    pipeline_microbatches: int = 0

    def __post_init__(self):
        if self.seq_parallel_mode not in ("ring", "ulysses"):
            raise ValueError(
                f"seq_parallel_mode must be 'ring' or 'ulysses', got "
                f"{self.seq_parallel_mode!r}"
            )
        if self.remat_policy not in ("nothing", "dots", "attn_out"):
            raise ValueError(
                f"remat_policy must be 'nothing', 'dots' or 'attn_out', "
                f"got {self.remat_policy!r}"
            )
        if self.ce_inline_bwd and not (
                self.fused_ce is True
                or (self.fused_ce is None and self.vocab_size >= 2**16)):
            # a silent no-op flag would let a user believe they measured
            # the inline path (and the planner charge for residuals that
            # never exist) — refuse the combination instead
            raise ValueError(
                "ce_inline_bwd requires the fused CE path: set "
                "fused_ce=True (or leave it auto with vocab >= 64k)"
            )
        if self.pipeline_microbatches > 0 and not self.scan_layers:
            raise ValueError(
                "pipeline_microbatches requires scan_layers=True (the "
                "stacked layer layout is what the pipeline stage-splits)"
            )
        if self.pipeline_microbatches > 0 and self.seq_parallel:
            raise ValueError(
                "pipeline_microbatches and seq_parallel are mutually "
                "exclusive (the pipeline path runs attention per stage)"
            )

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        return cls(**{**dict(
            vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, hidden_dim=14336), **kw})

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        """Test/debug config: same code path, laptop-sized."""
        return cls(**{**dict(
            vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            hidden_dim=128, max_seq_len=256, remat=False), **kw})


def _is_prefill_view(paged) -> bool:
    """Is this paged view the PREFILL lane's (chunk-wide queries) or
    the decode lane's (one token per slot)? Import-deferred so the
    training path never pays the serve-op import."""
    from ray_lightning_tpu.ops.attention import PagedPrefillView

    return isinstance(paged, PagedPrefillView)


def _is_flash_remat_opt(params) -> bool:
    """Is this `remat_opt` equation the flash kernel's hoisted fwd rule?

    `optimize_remat=True` rewrites EVERY such custom_vjp into a
    `remat_opt` call, so a policy keyed on the primitive name alone
    would save the residuals of any future optimized-remat custom_vjp
    in the model, not specifically attention's. The flash fwd rule tags
    its residual tuple with checkpoint_name("flash_residuals")
    (ops/pallas/flash.py _flash_fwd_rule) — those `name` equations are
    visible in the hoisted fwd jaxpr carried in the eqn params, which is
    the precise fingerprint."""
    fwd = params.get("fwd_jaxpr")
    jaxpr = getattr(fwd, "jaxpr", None)
    if jaxpr is None:
        return False
    return any(
        eqn.primitive.name == "name"
        and eqn.params.get("name") == "flash_residuals"
        for eqn in jaxpr.eqns)


def _attn_residuals_saveable(prim, *avals, **params) -> bool:
    """Checkpoint policy for remat_policy="attn_out": save the flash
    kernel's VJP residuals (q/k/v/o/lse) plus the block-level attention
    output, recompute everything else.

    Mechanism: the flash custom_vjp is defined with optimize_remat=True
    (ops/pallas/flash.py), which hoists its fwd rule into a `remat_opt`
    call whose outputs ARE the residual tuple — a custom_vjp is
    otherwise opaque to checkpoint policies (its residuals never appear
    in the primal trace; a named-saveable policy alone verifiably saved
    nothing, tests/test_ops.py). Saving the FLASH kernel's remat_opt
    outputs (scoped via `_is_flash_remat_opt` — any other
    optimize_remat custom_vjp keeps its own remat policy) is therefore
    exactly "save the attention residuals". The `name` check covers the
    XLA-reference attention path, whose output is tagged "attn_out" in
    LlamaBlock; the pallas branch deliberately does NOT tag (the kernel
    residuals already include o — tagging would double-save it)."""
    if prim.name == "remat_opt":
        return _is_flash_remat_opt(params)
    return prim.name == "name" and params.get("name") == "attn_out"


def _remat_policy(name: str):
    """Shared checkpoint-policy lookup for the scan and pipeline paths.

    "attn_out" is the point between "nothing" (recompute all) and
    "dots" (save all matmul outputs): it drops the attention share of
    the backward recompute tax — QKV projections, RoPE, and the flash
    forward never re-run — for ~200 MB/layer of saved residuals at
    B=8 S=2048 (the block input is saved by the remat boundary itself
    under every policy)."""
    return {
        "nothing": jax.checkpoint_policies.nothing_saveable,
        "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        "attn_out": _attn_residuals_saveable,
    }[name]


class LlamaBlock(nn.Module):
    cfg: LlamaConfig
    mesh: Optional[Any] = None  # jax.sharding.Mesh (static, hashable)

    @nn.compact
    def __call__(self, x, cos, sin, cache=None, pos=None, pad=None,
                 paged=None):
        """Training/prefill-from-zero when cache is None; with a
        ``cache=(k_cache, v_cache)`` ([B, S_max, Hkv, hd] each) and a
        (traced) ``pos``, runs the KV-cache decode path and returns the
        updated cache as the scan output. ``pad`` ([B] int32, cache path
        only) is the per-row LEFT padding of a ragged batch: RoPE
        positions shift down by ``pad[b]`` (clamped at 0 for the pad
        rows themselves, whose outputs are discarded) and attention
        masks out the pad columns — a left-padded row decodes exactly
        like its unpadded prompt (test-pinned).

        ``paged`` (an `ops.attention.PagedDecodeView`, serving engine
        only) switches the cache path to the block-paged pool: ``cache``
        is then ONE layer's shared pool ``([n_blocks, P, Hkv, hd])``
        pair, S must be 1 (one decode token per slot), ``pos`` is a
        per-slot [B] vector of cache positions, the new K/V token is
        scattered straight into the pool at the view's (already
        scratch-redirected) write index, and attention consumes the
        pool through the per-slot block tables — fused on the pallas
        path, dense-gathered on the XLA reference path
        (ops.attention.paged_attention). A `PagedPrefillView` instead
        selects the chunked PREFILL twin: S is the chunk width, ``pos``
        the group's shared scalar write offset, the whole chunk's K/V
        is scattered through ``write_block/write_offset`` and
        `ops.attention.paged_prefill` attends causally through the
        tables. ``paged=None`` lowers the identical historical
        program."""
        cfg = self.cfg
        d, hd = cfg.dim, cfg.head_dim
        dense = partial(nn.Dense, use_bias=False, dtype=cfg.dtype,
                        param_dtype=jnp.float32,
                        dot_general=_f32_acc_dot_general)

        attn_norm_w = self.param("attn_norm", nn.initializers.ones, (d,))
        h = rms_norm(x, attn_norm_w, cfg.norm_eps)
        # fused QKV projection: one [D, (H + 2*Hkv) * hd] matmul
        n_q, n_kv = cfg.n_heads, cfg.n_kv_heads
        qkv = dense((n_q + 2 * n_kv) * hd, name="wqkv")(h)
        q, k, v = jnp.split(
            qkv, [n_q * hd, (n_q + n_kv) * hd], axis=-1)
        B, S = x.shape[0], x.shape[1]
        q = q.reshape(B, S, n_q, hd)
        k = k.reshape(B, S, n_kv, hd)
        v = v.reshape(B, S, n_kv, hd)
        if cache is None:
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            pallas_path = False
            if (cfg.seq_parallel and self.mesh is not None
                    and self.mesh.shape.get("seq", 1) > 1):
                # manual island: sequence sharded over `seq`; everything
                # else stays compiler-sharded.
                if cfg.seq_parallel_mode == "ulysses":
                    attn = ulysses_attention(
                        q, k, v, self.mesh, causal=True,
                        use_pallas=None if cfg.use_flash else False)
                else:
                    attn = ring_attention(q, k, v, self.mesh, causal=True)
            else:
                # use_flash=True -> auto (pallas on TPU, XLA fallback
                # elsewhere); False -> always the XLA reference path.
                from ray_lightning_tpu.ops.attention import flash_uses_pallas

                pallas_path = flash_uses_pallas(
                    q.shape, k.shape, None if cfg.use_flash else False)
                attn = flash_attention(
                    q, k, v, causal=True,
                    use_pallas=None if cfg.use_flash else False)
            # name the attention output for remat_policy="attn_out" —
            # the save point the XLA-reference (and seq-parallel island)
            # paths offer. The pallas branch is deliberately NOT named:
            # its full VJP residual set (incl. o) is already saved
            # through the kernel's own remat_opt hoist, and naming the
            # output again would keep a second [B, S, H·hd] residual per
            # layer beyond what parallel/plan.py accounts. Under other
            # policies the name is inert. flash_uses_pallas is the SAME
            # predicate the dispatch uses, so the annotation cannot
            # drift from the path actually taken.
            if not pallas_path:
                from jax.ad_checkpoint import checkpoint_name

                attn = checkpoint_name(attn, "attn_out")
            new_cache = None
        elif paged is not None and _is_prefill_view(paged):
            # paged PREFILL (serve/engine.py fused prefill lane): a
            # CH-token chunk per head-group row against the SHARED
            # block pool — the per-group dense cache copy never exists
            # on this path. ``pos`` is the group's shared scalar write
            # offset (chunk token j sits at cache position pos + j);
            # ``pad`` is the per-row left pad of the right-aligned
            # group (None on the single-slot lane).
            positions = jnp.broadcast_to(
                (pos + jnp.arange(S))[None, :], (B, S))
            if pad is not None:
                positions = jnp.maximum(positions - pad[:, None], 0)
            q = apply_rope(q, cos, sin, positions=positions)
            k = apply_rope(k, cos, sin, positions=positions)
            pk, pv = cache  # [n_blocks, P, Hkv, hd] — one layer's pool
            # write-then-attend, the decode fused lane's ordering: the
            # whole chunk's K/V is scattered into OWNED pool blocks
            # (vacant group rows arrive scratch-redirected — block 0 is
            # masked garbage by contract) BEFORE attention, so each
            # query's causal window covers the in-chunk prefix too.
            pk = pk.at[paged.write_block, paged.write_offset].set(
                k.astype(pk.dtype))
            pv = pv.at[paged.write_block, paged.write_offset].set(
                v.astype(pv.dtype))
            from ray_lightning_tpu.ops.attention import paged_prefill

            # the view's STATIC use_pallas (the serve engine's
            # build-time decision) pins the dispatch; absent that,
            # fall back to the flash-style ambient policy
            up = (paged.use_pallas if paged.use_pallas is not None
                  else (None if cfg.use_flash else False))
            attn = paged_prefill(q, pk, pv, paged.tables, pos, pad=pad,
                                 use_pallas=up)
            new_cache = (pk, pv)
        elif paged is not None:
            # paged decode (serve/engine.py fused lane): one token per
            # slot against the SHARED block pool — no per-slot dense
            # cache copy exists on the kernel path. ``pos`` is a [B]
            # vector (per-slot cache position); its RoPE position is
            # pos - pad for a left-pad-prefilled slot.
            assert S == 1, "the paged cache path decodes one token/slot"
            positions = pos[:, None] + jnp.arange(S)[None, :]
            if pad is not None:
                positions = jnp.maximum(positions - pad[:, None], 0)
            q = apply_rope(q, cos, sin, positions=positions)
            k = apply_rope(k, cos, sin, positions=positions)
            pk, pv = cache  # [n_blocks, P, Hkv, hd] — one layer's pool
            # write-then-attend, exactly the dense cache path's
            # dynamic_update_slice ordering: the token's own K/V is
            # visible to its query. Idle/prefilling slots arrive
            # scratch-redirected (write_block 0) — duplicate scratch
            # writes race, but scratch is masked garbage by contract.
            pk = pk.at[paged.write_block, paged.write_offset].set(
                k[:, 0].astype(pk.dtype))
            pv = pv.at[paged.write_block, paged.write_offset].set(
                v[:, 0].astype(pv.dtype))
            from ray_lightning_tpu.ops.attention import paged_attention

            # the view's STATIC use_pallas (the serve engine's
            # build-time decision) pins the dispatch; absent that,
            # fall back to the flash-style ambient policy
            up = (paged.use_pallas if paged.use_pallas is not None
                  else (None if cfg.use_flash else False))
            attn = paged_attention(
                q[:, 0], pk, pv, paged.tables, paged.lengths, pad=pad,
                use_pallas=up)[:, None]
            new_cache = (pk, pv)
        else:
            positions = pos + jnp.arange(S)
            if pad is not None:
                # left-padded ragged batch: row b's first real token
                # sits at column pad[b] but is RoPE position 0; clamp
                # keeps the (discarded) pad rows' table reads in range
                positions = jnp.maximum(
                    positions[None, :] - pad[:, None], 0)
            q = apply_rope(q, cos, sin, positions=positions)
            k = apply_rope(k, cos, sin, positions=positions)
            ck, cv = cache  # [B, S_max, Hkv, hd]
            ck = jax.lax.dynamic_update_slice_in_dim(
                ck, k.astype(ck.dtype), pos, axis=1)
            cv = jax.lax.dynamic_update_slice_in_dim(
                cv, v.astype(cv.dtype), pos, axis=1)
            if (S > 1 and isinstance(pos, int) and pos == 0
                    and pad is None):
                # prefill from empty context: plain causal attention over
                # the chunk itself (flash path — never materialize the
                # [S, S_max] masked score matrix against the zero tail).
                attn = flash_attention(
                    q, k, v, causal=True,
                    use_pallas=None if cfg.use_flash else False)
            else:
                # single-token decode (or mid-sequence chunk, or a
                # left-padded prefill): masked reference SDPA over the
                # cache — S is tiny here.
                kv_pos = jnp.arange(ck.shape[1])[None, None, None, :]
                q_pos = (pos + jnp.arange(S))[None, None, :, None]
                mask = kv_pos <= q_pos
                if pad is not None:
                    # pad columns are not context for anyone
                    mask = mask & (kv_pos >= pad[:, None, None, None])
                attn = dot_product_attention(
                    q, ck, cv, causal=False, mask=mask)
            new_cache = (ck, cv)
        attn = attn.reshape(B, S, n_q * hd)
        x = x + dense(d, name="wo")(attn)

        mlp_norm_w = self.param("mlp_norm", nn.initializers.ones, (d,))
        h = rms_norm(x, mlp_norm_w, cfg.norm_eps)
        # fused gate+up: one [D, 2F] matmul
        gate_up = dense(2 * cfg.hidden_dim, name="w_gate_up")(h)
        gate, up = jnp.split(gate_up, 2, axis=-1)
        x = x + dense(d, name="w_down")(nn.silu(gate) * up)
        return x, new_cache  # (carry, ys) pair so nn.scan drives the block


class Llama(nn.Module):
    """Flax core model: token ids [B, S] -> logits [B, S, V]."""

    cfg: LlamaConfig
    mesh: Optional[Any] = None  # set by the strategy for seq/tensor islands

    @nn.compact
    def __call__(self, tokens: jnp.ndarray, cache=None, pos=None,
                 pad=None, paged=None, last_only: bool = False,
                 return_hidden: bool = False):
        """Training/eval: ``model(tokens) -> logits``. Decoding:
        ``model(tokens, cache=(k, v), pos=p) -> (logits, new_cache)``
        with cache leaves stacked over layers ([L, B, S_max, Hkv, hd];
        see `init_cache`) and ``p`` the write offset (python 0 for a
        fresh prefill, traced thereafter). ``last_only`` projects only
        the final position through the lm_head (prefill wants one row of
        logits, not [S, vocab]). ``return_hidden`` skips the lm_head and
        returns the final-norm'd [B, S, D] states — the fused-CE loss
        path projects them chunk-wise (ops/fused_ce.py). ``paged``
        (serving engine) switches the cache path to the block-paged
        pool — cache leaves are then [L, n_blocks, P, Hkv, hd] and
        ``pos`` is a per-slot vector; see `LlamaBlock.__call__`."""
        cfg = self.cfg
        # take from the f32 table and round the (token-sized) result,
        # rather than dtype=cfg.dtype (which rounds the TABLE before the
        # take): gather commutes with rounding so the forward is
        # bitwise identical, but the backward now upcasts per-token
        # cotangents BEFORE the vocab-sized scatter-add, so the
        # embedding grad accumulates — and reduce-scatters — in f32
        # (numcheck RLT804) instead of bf16
        embed = nn.Embed(
            cfg.vocab_size, cfg.dim, dtype=jnp.float32,
            param_dtype=jnp.float32, name="tok_embed",
        )
        x = embed(tokens).astype(cfg.dtype)
        cos, sin = rope_frequencies(
            cfg.head_dim, cfg.max_seq_len, cfg.rope_theta, dtype=jnp.float32
        )
        if cache is None:
            cos, sin = cos[: tokens.shape[1]], sin[: tokens.shape[1]]

        block = LlamaBlock
        if cfg.remat and cache is None:
            block = nn.remat(block, policy=_remat_policy(cfg.remat_policy))
        new_cache = None
        if cfg.scan_layers:
            # one compiled block, scanned over a stacked-params layer axis
            scan = partial(
                nn.scan,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=cfg.n_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )
            if cache is None:
                x, _ = scan(block, in_axes=nn.broadcast)(
                    cfg, self.mesh, name="layers")(x, cos, sin)
            else:
                # cache rides the scan: in over the layer axis, updated
                # cache collected as the scan output (out_axes=0). The
                # paged view (block tables / lengths / write indices)
                # is layer-invariant, so it broadcasts like pos/pad.
                x, new_cache = scan(
                    block,
                    in_axes=(nn.broadcast, nn.broadcast, 0,
                             nn.broadcast, nn.broadcast, nn.broadcast),
                    out_axes=0,
                )(cfg, self.mesh, name="layers")(x, cos, sin, cache,
                                                 pos, pad, paged)
        else:
            caches = []
            for i in range(cfg.n_layers):
                layer_cache = None if cache is None else jax.tree.map(
                    lambda c, i=i: c[i], cache)
                x, c = block(cfg, self.mesh, name=f"layer_{i}")(
                    x, cos, sin, layer_cache, pos, pad, paged)
                caches.append(c)
            if cache is not None:
                new_cache = jax.tree.map(
                    lambda *cs: jnp.stack(cs, axis=0), *caches)

        final_w = self.param("final_norm", nn.initializers.ones, (cfg.dim,))
        if last_only:
            x = x[:, -1:, :]
        x = rms_norm(x, final_w, cfg.norm_eps)
        if return_hidden:
            # lm_head params still exist (init traces the default path);
            # the loss projects these states tile-by-tile instead.
            return x
        if cfg.tie_embeddings:
            logits = embed.attend(x.astype(jnp.float32))
        else:
            # vocab projection at activation dtype (bf16 operands hit
            # the MXU at full rate; ~3% step-time win) with an f32
            # accumulator the logits keep — loss/sampling math runs on
            # the unrounded sum (_f32_out_dot_general).
            logits = nn.Dense(
                cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                param_dtype=jnp.float32, name="lm_head",
                dot_general=_f32_out_dot_general,
            )(x).astype(jnp.float32)
        if cache is None:
            return logits
        return logits, new_cache


def _stacked(spec: P, stacked: bool) -> P:
    """Prepend the scan layer axis to a per-layer spec. The layer axis
    carries `pipe` — on meshes without pipeline parallelism the strategy
    drops the size-1 axis (Strategy._adapt_spec) and it is replicated as
    before; with pipe > 1 each stage group owns its contiguous block."""
    return P("pipe", *spec) if stacked else spec


#: per-layer tensor-parallel placement (no fsdp, no layer axis). Shared
#: by `llama_param_specs` (which stacks/overlays it) and the overlap
#: schedule's gather target (`LlamaModule._overlapped_hidden`): a
#: double-buffered weight gather un-does exactly the strategy's fsdp
#: overlay — the Megatron `tensor` split stays resident.
_PER_LAYER_SPECS: Dict[str, P] = {
    "wqkv/kernel": P(None, "tensor"),
    "wo/kernel": P("tensor", None),
    "w_gate_up/kernel": P(None, "tensor"),
    "w_down/kernel": P("tensor", None),
    "attn_norm": P(),
    "mlp_norm": P(),
}


def llama_param_specs(cfg: LlamaConfig) -> Dict[str, P]:
    """Megatron-style tensor-parallel placement for every weight.

    Keys are `/`-joined param paths as produced by utils.pytree._path_str.
    Column-parallel (output dim on `tensor`): wqkv, w_gate_up.
    Row-parallel (input dim on `tensor`): wo, w_down.
    Embedding: vocab on `tensor`. Norm gains: replicated (spec P()).
    The strategies overlay `fsdp` on whatever axis is still free.
    """
    st = cfg.scan_layers
    specs: Dict[str, P] = {
        "tok_embed/embedding": P("tensor", None),
        "final_norm": P(),
    }
    if not cfg.tie_embeddings:
        specs["lm_head/kernel"] = P(None, "tensor")
    per_layer = _PER_LAYER_SPECS
    if st:
        for k, v in per_layer.items():
            specs[f"layers/{k}"] = _stacked(v, True)
    else:
        for i in range(cfg.n_layers):
            for k, v in per_layer.items():
                specs[f"layer_{i}/{k}"] = v
    return specs


def cross_entropy_loss(
    logits: jnp.ndarray, targets: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Token-level CE in f32; `mask` (0/1) excludes padding."""
    losses = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets
    )
    if mask is not None:
        return (losses * mask).sum() / jnp.maximum(mask.sum(), 1)
    return losses.mean()


def init_cache(cfg: LlamaConfig, batch: int, max_len: int):
    """Zeroed KV cache, leaves [n_layers, B, max_len, Hkv, head_dim]
    (layer axis matches the scan's in/out axes)."""
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))


@functools.lru_cache(maxsize=32)
def _compiled_generate(model: Llama, B: int, S0: int, max_new_tokens: int,
                       temperature: float, top_k: Optional[int],
                       cache_len: int, padded: bool):
    """Build-and-jit once per (model, shape, sampling) key so repeated
    generate() calls hit XLA's compile cache instead of retracing a
    fresh closure every time. The KV cache is an ARGUMENT, donated:
    the caller's `init_cache` buffer is consumed in place, so the
    decode holds one cache in HBM, never an input copy next to the
    updated one (the second-full-cache failure mode this signature
    retires)."""
    cfg = model.cfg

    def sample(logits, rng):
        if temperature == 0.0:
            return logits.argmax(-1).astype(jnp.int32)
        logits = logits / temperature
        if top_k is not None:
            kth = jax.lax.top_k(logits, top_k)[0][:, -1][:, None]
            logits = jnp.where(logits >= kth, logits, -jnp.inf)
        return jax.random.categorical(rng, logits).astype(jnp.int32)

    def run(params, prompt, rng, cache, pad):
        logits, cache = model.apply({"params": params}, prompt,
                                    cache=cache, pos=0, pad=pad,
                                    last_only=True)
        last = logits[:, -1, :]
        out = jnp.zeros((B, max_new_tokens), jnp.int32)

        def body(t, carry):
            last, cache, out, rng = carry
            rng, sub = jax.random.split(rng)
            tok = sample(last, sub)
            out = jax.lax.dynamic_update_slice_in_dim(
                out, tok[:, None], t, axis=1)
            logits, cache = model.apply({"params": params}, tok[:, None],
                                        cache=cache, pos=S0 + t, pad=pad)
            return (logits[:, 0, :], cache, out, rng)

        _, cache, out, _ = jax.lax.fori_loop(
            0, max_new_tokens, body, (last, cache, out, rng))
        # the final cache is RETURNED so the donated input has an
        # output to alias — donation with no matching output is a
        # silent no-op (plus a UserWarning per compile); the caller
        # drops it, the buffer is simply reused in place
        return out, cache

    if not padded:
        # the pad argument must not appear in the unpadded program at
        # all (bitwise pin vs the historical path)
        def run_nopad(params, prompt, rng, cache):
            return run(params, prompt, rng, cache, None)

        return jax.jit(run_nopad, donate_argnums=(3,))
    return jax.jit(run, donate_argnums=(3,))


def generate(
    model: Llama,
    params,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    seed: int = 0,
    cache_len: Optional[int] = None,
    prompt_lengths=None,
) -> jnp.ndarray:
    """Autoregressive decoding with a KV cache, one compiled program:
    flash-attention prefill over the prompt (one row of lm_head logits),
    then a `lax.fori_loop` of single-token steps (each an in-place
    `dynamic_update_slice` into the DONATED cache — static shapes
    throughout, no per-token recompilation, one cache's HBM; repeated
    calls reuse the compiled program).

    Greedy when temperature == 0; otherwise temperature (+ optional
    top-k) sampling. ``cache_len`` sizes the KV cache explicitly (any
    length >= prompt + max_new_tokens — no rounding is imposed);
    default is exactly prompt + max_new_tokens. ``prompt_lengths``
    ([B] ints) declares a LEFT-padded ragged batch: row b's real prompt
    is its last ``prompt_lengths[b]`` columns, and each row decodes
    exactly as its unpadded prompt would (test-pinned). Returns
    [B, max_new_tokens] int32.
    """
    B, S0 = prompt.shape
    explicit_cache_len = cache_len is not None
    if cache_len is None:
        cache_len = S0 + max_new_tokens
    if cache_len < S0 + max_new_tokens:
        raise ValueError(
            f"cache_len ({cache_len}) is smaller than prompt ({S0}) + "
            f"max_new_tokens ({max_new_tokens})"
        )
    if cache_len > model.cfg.max_seq_len:
        what = (f"cache_len ({cache_len})" if explicit_cache_len else
                f"prompt ({S0}) + max_new_tokens ({max_new_tokens})")
        raise ValueError(
            f"{what} exceeds max_seq_len ({model.cfg.max_seq_len})"
        )
    pad = None
    if prompt_lengths is not None:
        lengths = np.asarray(prompt_lengths, np.int32)
        if lengths.shape != (B,):
            raise ValueError(
                f"prompt_lengths must have shape ({B},), got "
                f"{lengths.shape}")
        if (lengths < 1).any() or (lengths > S0).any():
            # a length beyond the prompt width would produce a NEGATIVE
            # pad — RoPE positions silently shift up and every decode
            # is wrong with no error
            raise ValueError(
                f"prompt_lengths must be within [1, {S0}] (the padded "
                f"prompt width), got {lengths.tolist()}")
        pad = jnp.asarray(S0 - lengths)
    run = _compiled_generate(model, B, S0, max_new_tokens,
                             float(temperature), top_k, int(cache_len),
                             pad is not None)
    cache = init_cache(model.cfg, B, cache_len)
    if pad is None:
        out, _ = run(params, prompt, jax.random.key(seed), cache)
    else:
        out, _ = run(params, prompt, jax.random.key(seed), cache, pad)
    return out


class LlamaModule(TpuModule):
    """TpuModule wrapper: next-token prediction on {"tokens": [B, S+1]}
    (or {"inputs","targets"} pairs)."""

    def __init__(self, cfg: Optional[LlamaConfig] = None,
                 lr: float = 3e-4, weight_decay: float = 0.1,
                 warmup_steps: int = 100, total_steps: int = 10000,
                 mu_dtype: Optional[Any] = None,
                 **cfg_overrides):
        """``mu_dtype``: storage dtype for Adam's first moment (e.g.
        ``jnp.bfloat16``; default None = the params' f32). Halves the
        mu buffer — ~1/4 of optimizer HBM — which on a memory-capped
        chip buys batch instead; the variance (nu) always stays f32.
        The planner charges the real dtype automatically (it eval_shapes
        this optimizer), as do checkpoints (orbax saves the tree as-is)."""
        super().__init__()
        if cfg is None:
            cfg = LlamaConfig(**cfg_overrides)
        elif cfg_overrides:
            cfg = dataclasses.replace(cfg, **cfg_overrides)
        self.cfg = cfg
        self.lr = lr
        self.weight_decay = weight_decay
        self.warmup_steps = warmup_steps
        self.total_steps = total_steps
        self.mu_dtype = mu_dtype
        self.save_hyperparameters(
            cfg=cfg, lr=lr, weight_decay=weight_decay,
            warmup_steps=warmup_steps, total_steps=total_steps,
            mu_dtype=mu_dtype,
        )

    def configure_model(self):
        # `self.mesh` is bound by Strategy.setup before the model builds,
        # so seq/tensor manual islands (ring attention) see the live mesh.
        return Llama(self.cfg, mesh=self.mesh)

    def configure_optimizers(self):
        sched = optax.warmup_cosine_decay_schedule(
            0.0, self.lr, self.warmup_steps, max(self.total_steps, 2),
            end_value=self.lr * 0.1,
        )
        return optax.adamw(sched, b1=0.9, b2=0.95,
                           weight_decay=self.weight_decay,
                           mu_dtype=self.mu_dtype)

    def param_specs(self, params) -> Dict[str, P]:
        return llama_param_specs(self.cfg)

    def _split(self, batch):
        if "tokens" in batch:
            toks = batch["tokens"]
            return toks[:, :-1], toks[:, 1:], batch.get("mask")
        return batch["inputs"], batch["targets"], batch.get("mask")

    def _use_fused_ce(self) -> bool:
        if self.cfg.fused_ce is not None:
            return self.cfg.fused_ce
        return self.cfg.vocab_size >= 2**16

    def _use_pipeline(self) -> bool:
        return (self.cfg.pipeline_microbatches > 0
                and self.mesh is not None
                and self.mesh.shape.get("pipe", 1) > 1)

    def _use_overlap(self) -> bool:
        """The double-buffered weight-gather schedule is live when the
        strategy asked for it (``FSDP/ShardedMesh(overlap="on")`` sets
        ``self.overlap`` at bind time) AND there is FSDP latency to hide
        (fsdp > 1) on a scanned stack deep enough to pipeline. The
        pipeline path owns its own layer schedule, so they are mutually
        exclusive."""
        return (bool(getattr(self, "overlap", False))
                and self.cfg.scan_layers
                and self.cfg.n_layers >= 2
                and self.mesh is not None
                and self.mesh.shape.get("fsdp", 1) > 1
                and not self._use_pipeline())

    def _pipelined_hidden(self, params, tokens):
        """GPipe decoder path: the SAME stacked `layers` params the scan
        path trains, stage-split over the mesh's `pipe` axis
        (ops/pipeline.py) — embedding / final norm / lm_head run outside
        the pipeline, numerics identical to the scan path."""
        from ray_lightning_tpu.ops.pipeline import gpipe_apply

        cfg = self.cfg
        if any(self.mesh.shape.get(ax, 1) > 1 for ax in ("tensor", "seq")):
            raise ValueError(
                "the pipeline path composes with data/fsdp only; drop "
                "tensor/seq from the mesh or disable "
                "pipeline_microbatches"
            )
        emb = params["tok_embed"]["embedding"]
        x = jnp.take(emb, tokens, axis=0).astype(cfg.dtype)
        cos, sin = rope_frequencies(
            cfg.head_dim, cfg.max_seq_len, cfg.rope_theta, dtype=jnp.float32
        )
        cos, sin = cos[: tokens.shape[1]], sin[: tokens.shape[1]]
        block = LlamaBlock(cfg, None)

        def stage_fn(lp, h, cos, sin):
            return block.apply({"params": lp}, h, cos, sin)[0]

        policy = _remat_policy(cfg.remat_policy)
        h = gpipe_apply(
            stage_fn, params["layers"], x, self.mesh,
            microbatches=cfg.pipeline_microbatches,
            remat=cfg.remat, remat_policy=policy, extra=(cos, sin),
        )
        return rms_norm(h, params["final_norm"], cfg.norm_eps)

    def _gathered_layer_shardings(self):
        """NamedShardings for ONE layer's weights with the fsdp overlay
        undone: the module's own per-layer tensor placement
        (`_PER_LAYER_SPECS`) over the live mesh. This is the double
        buffer's layout — gathered over `fsdp`, still `tensor`-split."""
        from jax.sharding import NamedSharding

        mesh = self.mesh
        return {path: NamedSharding(mesh, spec)
                for path, spec in _PER_LAYER_SPECS.items()}

    def _overlapped_hidden(self, params, tokens):
        """Double-buffered weight-gather prefetch over the scanned layer
        stack (docs/PERFORMANCE.md "collective overlap"):

          * the scan carry holds layer *i*'s weights ALREADY gathered
            over `fsdp`; each trip first issues layer *i+1*'s gather
            (`with_sharding_constraint` to the gathered layout, stamped
            with the `rlt_overlap_prefetch` fingerprint and pinned
            before the compute by `ops.dispatch.overlap_barrier`), then
            runs layer *i* from the buffer — the gather's latency sits
            under the layer's matmuls instead of on the critical path;
          * the per-layer `custom_vjp` saves only the SHARDED slice and
            the block input as residuals: the backward scan re-gathers
            each layer's weights as it retires it (the remat-the-gather
            discipline — carrying the gathered buffer as a residual
            would stack L full layers of weights in HBM) and its grad
            reduce-scatters are emitted per retired layer by GSPMD;
          * per-layer recompute-from-inputs is inherent to the schedule
            (the custom_vjp IS remat policy "nothing" for the block), so
            `remat_policy` refinements are inert on this path;
          * HBM cost: one extra layer of gathered weights + the in-flight
            gradient — charged by `parallel.plan.llama_overlap_buffer_bytes`.

        Numerics are bitwise-identical to the naive scan (test-pinned):
        gathers move bytes, the per-layer math is the same block, and the
        grad reductions ride the same fsdp ring.
        """
        import jax.tree_util as jtu

        from ray_lightning_tpu.ops.dispatch import (
            fusion_fence, overlap_barrier, prefetch_named,
        )
        from ray_lightning_tpu.utils.pytree import _path_str

        cfg = self.cfg
        emb = params["tok_embed"]["embedding"]
        x = jnp.take(emb, tokens, axis=0).astype(cfg.dtype)
        cos, sin = rope_frequencies(
            cfg.head_dim, cfg.max_seq_len, cfg.rope_theta, dtype=jnp.float32
        )
        cos, sin = cos[: tokens.shape[1]], sin[: tokens.shape[1]]

        from jax.sharding import NamedSharding

        from ray_lightning_tpu.parallel.mesh import dp_axis_names

        layers = params["layers"]
        gshard = self._gathered_layer_shardings()
        block = LlamaBlock(cfg, self.mesh)
        hshard = NamedSharding(
            self.mesh, P(dp_axis_names(self.mesh), None, None))

        def gather(shard):
            return jtu.tree_map_with_path(
                lambda kp, t: jax.lax.with_sharding_constraint(
                    t, gshard[_path_str(kp)]), shard)

        def block_apply(w, h, cos, sin):
            # fence the block region on both ends: the prefetched and
            # serial schedules surround the block with different ops,
            # and XLA fuses across those seams, reassociating the
            # block's bf16/f32 reductions differently per schedule
            # (measured: 1-2 bf16 ulp per layer at small shapes). With
            # barrier-delimited input and output the block is an
            # identical compilation region under every schedule — the
            # overlapped-vs-serial bitwise pin rests on this. The
            # barriers pin FUSION but not PARTITIONING, so the input
            # layouts are pinned too (w to the gathered layout, h to
            # batch-sharded): under the prefetched schedule w arrives as
            # a scan carry, and GSPMD sharding a carry differently than
            # the serial schedule's in-body gather would re-split the
            # block's matmul reductions — a data-dependent last-bit
            # divergence (observed at 1 ulp on CPU-SPMD).
            w, h = fusion_fence((w, h))
            w = gather(w)
            h = jax.lax.with_sharding_constraint(h, hshard)
            return fusion_fence(block.apply({"params": w}, h, cos, sin)[0])

        def _bwd_core(res, g_h):
            from jax.experimental.shard_alike import shard_alike

            shard, h, cos_r, sin_r = res
            w = gather(shard)  # re-gather at retirement (remat the gather)
            _, vjp = jax.vjp(
                lambda w, h: block_apply(w, h, cos_r, sin_r), w, h)
            dw, dh = vjp(g_h)
            # the layer's grad flows through the SHARD argument: GSPMD
            # finishes the partial sums as per-layer reduce-scatters as
            # the backward scan retires the layer. The gathered-carry
            # argument gets zeros so no cotangent rides the prefetch
            # chain (the prologue gather transposes to nothing).
            # shard_alike pins each dw leaf to ITS param shard's layout
            # (the reduce-scatter-at-retirement discipline) — without
            # the pin GSPMD is free to carry dw partially replicated,
            # and the prefetched and serial programs then compile the
            # optimizer's elementwise chain under different layouts
            # (observed: data-dependent 1-ulp drift in the updated
            # params via FMA contraction differences).
            dw = jax.tree.map(lambda s, d: shard_alike(s, d)[1], shard, dw)
            return w, dw, dh, jnp.zeros_like(cos_r), jnp.zeros_like(sin_r)

        def _primal(w, shard, h, cos, sin):
            return block_apply(w, h, cos, sin)

        def _fwd(w, shard, h, cos, sin):
            # residuals: the SHARDED slice + block input, never the
            # gathered buffer (which would stack L×full-layer weights)
            return block_apply(w, h, cos, sin), (shard, h, cos, sin)

        def _bwd(res, g):
            w, dw, dh, dcos, dsin = _bwd_core(res, g)
            return (jax.tree.map(jnp.zeros_like, w), dw, dh, dcos, dsin)

        layer_apply = jax.custom_vjp(_primal)
        layer_apply.defvjp(_fwd, _bwd)

        def _primal_pf(w, w_next, shard, h, cos, sin):
            # pin: the i+1 gather (producing w_next) is ordered before
            # layer i's compute consumes h. The barrier lives INSIDE
            # the custom_vjp so partial-eval never sees the primal-only
            # w chain coupled to the differentiated h at scan-body
            # level — outside, jax's grad-of-scan machinery saves the
            # barrier's known inputs per trip, i.e. stacks a full
            # gathered-layer copy of every weight as residual ys that
            # nothing in the backward consumes (DCE cannot reach them
            # through the custom_vjp call; measured: a phantom
            # full-stack copy, ~26 GiB on llama3-8b v5p-64).
            w_next, h = overlap_barrier((w_next, h))
            return block_apply(w, h, cos, sin), w_next

        def _fwd_pf(w, w_next, shard, h, cos, sin):
            return (_primal_pf(w, w_next, shard, h, cos, sin),
                    (shard, h, cos, sin))

        def _bwd_pf(res, g):
            g_h, _ = g  # the carried buffer's cotangent is dead weight
            w, dw, dh, dcos, dsin = _bwd_core(res, g_h)
            return (jax.tree.map(jnp.zeros_like, w),
                    jax.tree.map(jnp.zeros_like, w), dw, dh, dcos, dsin)

        layer_apply_pf = jax.custom_vjp(_primal_pf)
        layer_apply_pf.defvjp(_fwd_pf, _bwd_pf)

        prefetch = getattr(self, "overlap", False) != "serial"
        if prefetch:
            # stop_gradient: the prologue's cotangent is exactly zero by
            # construction (_bwd returns zeros for the gathered-carry
            # argument), but without the cut the p[0] slice TRANSPOSES
            # to a full-stack pad + add_any of zeros — dead weight XLA
            # must DCE and the HBM model would charge at full size.
            head = jax.tree.map(
                lambda p: jax.lax.stop_gradient(p[0]), layers)
            w = gather(head)  # prologue: layer 0's exposed gather

            def body(carry, xs_i):
                h, w = carry
                shard_i, shard_next = xs_i
                w_next = prefetch_named(gather(shard_next))
                h, w_next = layer_apply_pf(w, w_next, shard_i, h, cos, sin)
                return (h, w_next), None

            # every layer stays INSIDE the one scan — an unrolled
            # epilogue would compile the last layer in a different
            # fusion environment and break bitwise parity with the
            # scanned body (measured: one bf16 ulp per unrolled layer).
            # Trip i therefore prefetches layer (i+1) mod n_layers: the
            # wrap-around trip re-gathers layer 0, which in steady-state
            # training is the NEXT step's prologue warmed up (charged
            # honestly by tracecheck as one extra gather per step).
            # The rolled copy is stop_gradient'd OUTSIDE the scan: the
            # prefetch chain is non-differentiable by design (layer
            # i+1's gradient flows through its own trip's shard_i), and
            # without the cut the scan transpose stacks a full-size
            # zero cotangent for it and adds it through the roll's
            # transpose — real HBM and a GSPMD layout wildcard.
            xs = (layers,
                  jax.tree.map(
                      lambda p: jax.lax.stop_gradient(
                          jnp.concatenate([p[1:], p[:1]], axis=0)),
                      layers))
            (x, _), _ = jax.lax.scan(body, (x, w), xs)
        else:
            # overlap="serial": the ablation control — the SAME explicit
            # gather schedule with the double buffer removed, so the
            # gather blocks at each layer's use. Bitwise-identical math
            # to the prefetched schedule (test-pinned): the only delta
            # between the two programs is where the gather latency sits.
            def body(h, shard_i):
                w = gather(shard_i)
                h = layer_apply(w, shard_i, h, cos, sin)
                return h, None

            x, _ = jax.lax.scan(body, x, layers)
        return rms_norm(x, params["final_norm"], cfg.norm_eps)

    def _loss(self, params, inputs, targets, mask):
        cfg = self.cfg
        use_pipe = self._use_pipeline()
        use_fused = self._use_fused_ce()
        use_overlap = self._use_overlap()
        if not (use_pipe or use_fused or use_overlap):
            return cross_entropy_loss(
                self.apply(params, inputs), targets, mask)
        hidden = (self._pipelined_hidden(params, inputs) if use_pipe
                  else self._overlapped_hidden(params, inputs)
                  if use_overlap
                  else self.apply(params, inputs, return_hidden=True))
        if use_fused:
            if cfg.tie_embeddings:
                w = params["tok_embed"]["embedding"].T
            else:
                w = params["lm_head"]["kernel"]
            return fused_cross_entropy(
                hidden, w, targets, mask,
                chunk_tokens=cfg.ce_chunk_tokens,
                compute_dtype=cfg.dtype,
                inline_backward=cfg.ce_inline_bwd,
            )
        # materialized logits from the pipelined hidden states — the same
        # math the flax head performs: cfg.dtype operands with the f32
        # accumulator kept for the loss (_f32_out_dot_general's
        # contract; a plain cfg.dtype @ here is numcheck's RLT801)
        if cfg.tie_embeddings:
            w = params["tok_embed"]["embedding"].T
        else:
            w = params["lm_head"]["kernel"]
        logits = _f32_out_dot_general(
            hidden.astype(cfg.dtype), w.astype(cfg.dtype),
            (((hidden.ndim - 1,), (0,)), ((), ())))
        return cross_entropy_loss(logits, targets, mask)

    def training_step(self, params, batch, rng):
        inputs, targets, mask = self._split(batch)
        loss = self._loss(params, inputs, targets, mask)
        self.log("train_loss", loss)
        return loss

    def validation_step(self, params, batch):
        inputs, targets, mask = self._split(batch)
        return {"val_loss": self._loss(params, inputs, targets, mask)}

    def predict_step(self, params, batch):
        inputs, _, _ = self._split(batch)
        return self.apply(params, inputs).argmax(-1)

    def init_params(self, rng, batch):
        inputs, _, _ = self._split(batch)
        return self.model.init(rng, inputs)["params"]

    def generate(self, prompt, max_new_tokens: int, **kw) -> jnp.ndarray:
        """KV-cache autoregressive decoding with the trained params."""
        assert self.params is not None, "fit or load a checkpoint first"
        self.setup()
        return generate(self.model, self.params, jnp.asarray(prompt),
                        max_new_tokens, **kw)

