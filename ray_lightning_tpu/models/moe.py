"""Mixture-of-Experts layer with expert parallelism over the mesh.

Beyond-reference capability (the reference has no MoE; SURVEY §2.3 lists
EP as absent) that completes the mesh vocabulary: the `expert` axis
declared in parallel/mesh.py gets a real consumer.

TPU-idiomatic design — static shapes, einsum dispatch (GShard/Switch
style), no ragged tensors:

  * router: top-k gating with normalized weights, f32;
  * fixed expert capacity C = ceil(tokens * capacity_factor * k / E);
    tokens over capacity are dropped (their combine weight is zero) —
    the standard dropless-free formulation that keeps every shape
    static for XLA;
  * dispatch/combine are one-hot einsums; expert FFNs are ONE stacked
    einsum over [E, D, F] weights, so the MXU sees a single big batched
    matmul;
  * expert parallelism = sharding the stacked expert weights (and the
    [E, C, D] dispatched activations) on the `expert` mesh axis —
    `param_specs` returns P("expert", ...) and XLA inserts the
    all-to-alls implied by the dispatch/combine einsums;
  * aux load-balancing loss (Switch §2.2 form) returned alongside the
    output so the caller can add `aux_weight * aux` to the task loss.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from ray_lightning_tpu.core.module import TpuModule


def _fit_group(total: int, target: int) -> int:
    """Largest divisor of `total` that is <= target (linear scan down from
    target; token counts are products of small factors, so the scan is
    short in practice — runs at trace time only)."""
    g = min(total, target)
    while g > 1 and total % g != 0:
        g -= 1
    return max(1, g)


class MoEMLP(nn.Module):
    """Top-k routed SwiGLU expert FFN bank: [B, S, D] -> ([B, S, D], aux)."""

    n_experts: int
    hidden_dim: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16

    #: capacity groups (GShard §3.2): dispatch/combine tensors are
    #: [n_groups, group, E, C] with C ~ group*cf*k/E, so their memory is
    #: O(tokens * group * cf * k) — LINEAR in the token count. Without
    #: grouping C grows with the whole batch and the one-hots are
    #: O(tokens^2). Groups also bound worst-case imbalance locality.
    group_size: int = 1024

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        B, S, D = x.shape
        E, K = self.n_experts, self.top_k
        G = B * S
        gs = _fit_group(G, self.group_size)
        ng = G // gs
        C = max(1, int(np.ceil(gs * self.capacity_factor * K / E)))
        xg = x.reshape(ng, gs, D)

        router = self.param("router", nn.initializers.normal(0.02),
                            (D, E), jnp.float32)
        logits = jnp.einsum("nsd,de->nse", xg.astype(jnp.float32), router)
        probs = jax.nn.softmax(logits, axis=-1)               # [ng, gs, E]

        # top-k selection, normalized combine weights
        top_w, top_e = jax.lax.top_k(probs, K)                # [ng, gs, K]
        top_w = top_w / jnp.maximum(top_w.sum(-1, keepdims=True), 1e-9)

        # position of each (token, choice) in its expert's per-group
        # capacity buffer: running count within the group
        onehot = jax.nn.one_hot(top_e, E, dtype=jnp.float32)  # [ng,gs,K,E]
        flat = onehot.reshape(ng, gs * K, E)
        pos = (jnp.cumsum(flat, axis=1) - flat).reshape(ng, gs, K, E)
        pos = (pos * onehot).sum(-1).astype(jnp.int32)        # [ng, gs, K]
        within = pos < C                                      # capacity fit

        # dispatch / combine [ng, gs, E, C]
        pos_oh = jax.nn.one_hot(pos, C, dtype=jnp.float32)    # [ng,gs,K,C]
        disp = jnp.einsum("nske,nskc->nsec",
                          onehot * within[..., None], pos_oh)
        comb = jnp.einsum("nske,nskc->nsec",
                          onehot * (top_w * within)[..., None], pos_oh)

        w_gate_up = self.param(
            "w_gate_up", nn.initializers.lecun_normal(),
            (E, D, 2 * self.hidden_dim), jnp.float32)
        w_down = self.param(
            "w_down", nn.initializers.lecun_normal(),
            (E, self.hidden_dim, D), jnp.float32)

        # every einsum accumulates in f32 and rounds once at the output
        # (numcheck RLT801's sanctioned shape): operands stay
        # self.dtype for MXU rate, but the group-length dispatch/combine
        # contractions and the D/F-extent expert matmuls never sum in
        # bf16 — on CPU this is bitwise identical to the plain bf16
        # einsum (XLA accumulates in f32 internally either way)
        expert_in = jnp.einsum(
            "nsd,nsec->necd", xg.astype(self.dtype),
            disp.astype(self.dtype),
            preferred_element_type=jnp.float32).astype(self.dtype)
        gate_up = jnp.einsum(
            "necd,edf->necf", expert_in, w_gate_up.astype(self.dtype),
            preferred_element_type=jnp.float32).astype(self.dtype)
        gate, up = jnp.split(gate_up, 2, axis=-1)
        h = nn.silu(gate) * up
        expert_out = jnp.einsum(
            "necf,efd->necd", h, w_down.astype(self.dtype),
            preferred_element_type=jnp.float32).astype(self.dtype)
        y = jnp.einsum(
            "necd,nsec->nsd", expert_out, comb.astype(self.dtype),
            preferred_element_type=jnp.float32).astype(self.dtype)

        # Switch-style load-balance loss: E * sum_e f_e * p_e where f is
        # the RAW router-assignment fraction (no capacity mask — an
        # overloaded expert's fraction must not be clipped exactly when
        # imbalance is worst) and p the mean router probability.
        frac = onehot.sum(2).mean((0, 1))                         # [E]
        mean_p = probs.mean((0, 1))
        aux = E * jnp.sum(frac * mean_p)
        return y.reshape(B, S, D).astype(x.dtype), aux


def moe_param_specs(prefix: str = "") -> Dict[str, P]:
    """Expert-parallel placement: stacked expert weights sharded on the
    `expert` mesh axis; the router is replicated."""
    return {
        f"{prefix}router": P(),
        f"{prefix}w_gate_up": P("expert", None, "tensor"),
        f"{prefix}w_down": P("expert", "tensor", None),
    }


class _MoENet(nn.Module):
    dim: int
    n_experts: int
    hidden_dim: int
    top_k: int
    num_classes: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.dim, dtype=self.dtype,
                     param_dtype=jnp.float32, name="embed")(x)
        h = h[:, None, :]  # [B, 1, D] — MoE over a length-1 sequence
        y, aux = MoEMLP(self.n_experts, self.hidden_dim, self.top_k,
                        dtype=self.dtype, name="moe")(h)
        h = (h + y)[:, 0]
        logits = nn.Dense(self.num_classes, dtype=jnp.float32,
                          param_dtype=jnp.float32, name="head")(h)
        return logits, aux


class MoEClassifierModule(TpuModule):
    """Small expert-parallel classifier: demonstrates the `expert` mesh
    axis end-to-end (router + aux loss + EP sharding) on tabular data."""

    def __init__(self, dim: int = 64, n_experts: int = 4,
                 hidden_dim: int = 128, top_k: int = 2,
                 num_classes: int = 4, lr: float = 1e-3,
                 aux_weight: float = 0.01):
        super().__init__()
        self.save_hyperparameters(
            dim=dim, n_experts=n_experts, hidden_dim=hidden_dim,
            top_k=top_k, num_classes=num_classes, lr=lr,
            aux_weight=aux_weight,
        )
        self.dim = dim
        self.n_experts = n_experts
        self.hidden_dim = hidden_dim
        self.top_k = top_k
        self.num_classes = num_classes
        self.lr = lr
        self.aux_weight = aux_weight

    def configure_model(self):
        return _MoENet(self.dim, self.n_experts, self.hidden_dim,
                       self.top_k, self.num_classes, jnp.float32)

    def configure_optimizers(self):
        return optax.adam(self.lr)

    def param_specs(self, params) -> Dict[str, P]:
        return moe_param_specs("moe/")

    def training_step(self, params, batch, rng):
        logits, aux = self.apply(params, batch["x"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()
        self.log("aux_loss", aux)
        return loss + self.aux_weight * aux

    def validation_step(self, params, batch):
        logits, aux = self.apply(params, batch["x"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()
        acc = (logits.argmax(-1) == batch["y"]).mean()
        return {"val_loss": loss, "val_acc": acc, "val_aux": aux}

    def predict_step(self, params, batch):
        logits, _ = self.apply(params, batch["x"])
        return logits.argmax(-1)
