"""``python -m ray_lightning_tpu supervise`` — run a training job under
the resilience supervisor, from the command line.

Two modes:

  --smoke         the CI fault-injection gate (wired into format.sh),
                  three supervised CPU-SPMD legs on a box with no
                  accelerator:
                    kill      one injected worker kill must auto-resume
                              from the step-cadence checkpoint and
                              converge (kill -> classify -> relaunch ->
                              resume, end to end);
                    guard-nan an injected NaN batch must be SKIPPED
                              in-jit by the trainguard (zero restarts —
                              the process never dies) and still
                              converge;
                    guard-sdc an injected parameter bit-flip on rank 1
                              must be caught by the SDC fingerprint
                              probe within one cadence, rank 1
                              quarantined, and the run must roll back
                              to a blessed checkpoint and converge.
                  ``--no-guard`` drops the two guard legs.

  <target>        ``pkg.mod:factory`` where factory() returns a dict with
                  module_factory / trainer_factory / data_factory — the
                  same triple fit_distributed takes. Supervision knobs
                  (--max-restarts, --faults, --checkpoint-dir) apply.

Fault specs (--faults / RLT_FAULTS) are documented in
resilience/faults.py and docs/RESILIENCE.md.
"""
from __future__ import annotations

import argparse
import json
import sys


# ---- smoke job: module-level factories (cloudpickled by reference;
# workers import this module, which is on their path by construction) ----

_SMOKE_CLASSES = 4
_SMOKE_ROWS = 256
_SMOKE_BATCH = 16


def _smoke_module():
    from ray_lightning_tpu.models.mlp import MLPClassifier

    return MLPClassifier(features=(32,), num_classes=_SMOKE_CLASSES, lr=5e-2)


def _smoke_trainer():
    from ray_lightning_tpu import DataParallel, Trainer

    return Trainer(
        strategy=DataParallel(),
        max_epochs=2,
        enable_progress_bar=False,
        enable_checkpointing=False,  # the supervisor adds its own cadence
        seed=0,
        # every step's metrics are host-fetched: the guard legs' escalation
        # check rides the fetch cadence, and a smoke run is tiny anyway
        log_every_n_steps=1,
    )


def _smoke_data():
    import jax
    import numpy as np

    from ray_lightning_tpu import DataLoader

    rng = np.random.default_rng(0)
    centers = rng.normal(size=(_SMOKE_CLASSES, 8)) * 3
    y = rng.integers(0, _SMOKE_CLASSES, size=_SMOKE_ROWS)
    x = (centers[y] + rng.normal(size=(_SMOKE_ROWS, 8)) * 0.1).astype(
        np.float32)
    shard = dict(num_shards=jax.process_count(),
                 shard_index=jax.process_index())
    train = DataLoader({"x": x, "y": y}, batch_size=_SMOKE_BATCH,
                       shuffle=True, **shard)
    val = DataLoader({"x": x, "y": y}, batch_size=_SMOKE_BATCH, **shard)
    return train, val


def add_supervise_parser(sub) -> None:
    p = sub.add_parser(
        "supervise",
        help="run a distributed fit under the resilience supervisor "
             "(restart + resume on transient failures; "
             "docs/RESILIENCE.md)")
    p.add_argument("target", nargs="?", default=None,
                   help="pkg.mod:factory returning {module_factory, "
                        "trainer_factory, data_factory}; omit with "
                        "--smoke")
    p.add_argument("--smoke", action="store_true",
                   help="built-in CPU-SPMD convergence gate: an injected "
                        "worker kill + the trainguard legs (injected NaN "
                        "must skip in-jit; injected bit-flip must "
                        "quarantine) — the format.sh gate")
    p.add_argument("--no-guard", action="store_true",
                   help="with --smoke: drop the two trainguard legs")
    p.add_argument("--processes", type=int, default=2)
    p.add_argument("--devices-per-process", type=int, default=1)
    p.add_argument("--platform", default="cpu",
                   help="jax platform for the workers (cpu for the "
                        "smoke gate; unset/tpu on a pod)")
    p.add_argument("--faults", default=None,
                   help="fault-injection plan, e.g. 'kill:rank=1,step=3' "
                        "(default for --smoke: exactly that)")
    p.add_argument("--max-restarts", type=int, default=2)
    p.add_argument("--save-every", type=int, default=1,
                   help="step-cadence checkpoint interval the resume "
                        "rides on")
    p.add_argument("--checkpoint-dir", default=None,
                   help="supervisor checkpoint dir (default: a temp dir "
                        "for --smoke, ./rlt_logs/supervise otherwise)")
    p.add_argument("--stall-timeout", type=float, default=0.0,
                   help="silent-heartbeat budget in seconds "
                        "(0 disables the stall watchdog)")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="per-attempt wall-clock budget")
    # same SUPPRESS trick as the plan parser: don't clobber a --json
    # given before the subcommand
    p.add_argument("--json", action="store_true", dest="as_json",
                   default=argparse.SUPPRESS)


def _load_target(spec: str):
    import importlib

    mod_name, _, attr = spec.partition(":")
    if not attr:
        raise SystemExit(f"error: target must be pkg.mod:factory, "
                         f"got {spec!r}")
    factory = getattr(importlib.import_module(mod_name), attr)
    job = factory()
    missing = {"module_factory", "trainer_factory", "data_factory"} - set(job)
    if missing:
        raise SystemExit(
            f"error: {spec} returned no {sorted(missing)} "
            "(need module_factory/trainer_factory/data_factory)")
    return job


def _run_supervised_job(job, cfg, args, devices_per_process=None):
    """One supervised fit under the CLI's knobs. Returns
    ``(supervised_or_None, out_fields)``."""
    from ray_lightning_tpu.resilience.supervisor import (
        SupervisedFailure,
        fit_supervised,
    )

    try:
        supervised = fit_supervised(
            job["module_factory"], job["trainer_factory"],
            job["data_factory"], args.processes,
            resilience=cfg,
            platform=args.platform or None,
            num_cpu_devices_per_process=(
                (devices_per_process or args.devices_per_process)
                if args.platform == "cpu" else None),
            return_weights=False,
            timeout=args.timeout,
        )
    except SupervisedFailure as exc:
        return None, {"ok": False, "error": str(exc),
                      "classified": exc.classified.to_dict()}
    metrics = supervised.result.metrics
    acc = metrics.get("ptl/val_accuracy")
    return supervised, {
        "ok": True,
        "restarts": supervised.restarts,
        "preemptions": supervised.preemptions,
        "rollbacks": supervised.rollbacks,
        "quarantined": supervised.quarantined,
        "attempts": supervised.total_attempts,
        "failures": supervised.failures,
        "val_accuracy": (float(acc) if acc is not None else None),
        "metrics": {k: v for k, v in metrics.items()
                    if isinstance(v, (int, float))},
    }


def _smoke_guard_legs(args, base_dir) -> dict:
    """The trainguard legs of the --smoke gate (ISSUE 5): an injected
    NaN batch must be skipped IN-JIT (the process never dies: zero
    restarts) and still converge; an injected parameter bit-flip must be
    caught by the SDC probe, the rank quarantined, and the rolled-back
    run must converge."""
    import os

    from ray_lightning_tpu.resilience.guard import GuardConfig
    from ray_lightning_tpu.resilience.policy import RetryPolicy
    from ray_lightning_tpu.resilience.supervisor import ResilienceConfig

    job = {"module_factory": _smoke_module,
           "trainer_factory": _smoke_trainer,
           "data_factory": _smoke_data}

    def _cfg(name, guard, faults):
        return ResilienceConfig(
            checkpoint_dir=os.path.join(base_dir, name),
            policy=RetryPolicy(max_restarts=args.max_restarts,
                               backoff_base_s=0.5, jitter=0.0),
            save_every_n_steps=args.save_every,
            stall_timeout_s=args.stall_timeout,
            heartbeat_interval_s=1.0,
            guard=guard, faults=faults)

    legs: dict = {}

    # leg 2: nan_loss -> in-jit skip, NO restart, converged
    _, out = _run_supervised_job(
        job, _cfg("guard_nan", GuardConfig(warmup_steps=2),
                  "nan_loss:rank=0,step=3"), args)
    skipped = (out.get("metrics") or {}).get("guard_skipped_steps", 0)
    acc = out.get("val_accuracy")
    ok = (out["ok"] and out.get("attempts") == 1 and skipped
          and skipped >= 1 and acc is not None and acc > 0.8)
    legs["guard_nan"] = {
        "ok": bool(ok), "attempts": out.get("attempts"),
        "guard_skipped_steps": skipped, "val_accuracy": acc}
    if not ok:
        legs["guard_nan"]["error"] = (
            out.get("error")
            or "injected NaN was not skipped in-jit without a restart "
               f"(attempts={out.get('attempts')}, skipped={skipped}, "
               f"acc={acc})")

    # leg 3: bitflip_param on rank 1 -> SDC probe catches it within one
    # cadence, rank 1 quarantined, rollback to a blessed ckpt, converged.
    # 2 devices per process => 4 replicas: the flipped device is outvoted
    # 3:1 and its host rank is attributable.
    _, out = _run_supervised_job(
        job, _cfg("guard_sdc", GuardConfig(sdc_every_n_steps=2),
                  "bitflip_param:rank=1,step=3,device=0"), args,
        devices_per_process=2)
    acc = out.get("val_accuracy")
    ok = (out["ok"] and out.get("rollbacks", 0) >= 1
          and out.get("quarantined") == [1]
          and acc is not None and acc > 0.8)
    legs["guard_sdc"] = {
        "ok": bool(ok), "rollbacks": out.get("rollbacks"),
        "quarantined": out.get("quarantined"), "val_accuracy": acc}
    if not ok:
        legs["guard_sdc"]["error"] = (
            out.get("error")
            or "injected bit-flip was not caught+quarantined "
               f"(rollbacks={out.get('rollbacks')}, "
               f"quarantined={out.get('quarantined')}, acc={acc})")
    return legs


def run_supervise(args) -> int:
    import os
    import tempfile

    from ray_lightning_tpu.resilience.policy import RetryPolicy
    from ray_lightning_tpu.resilience.supervisor import ResilienceConfig

    if not args.smoke and not args.target:
        print("error: pass a pkg.mod:factory target or --smoke",
              file=sys.stderr)
        return 2
    if args.smoke:
        job = {"module_factory": _smoke_module,
               "trainer_factory": _smoke_trainer,
               "data_factory": _smoke_data}
        faults = args.faults if args.faults is not None else (
            f"kill:rank={min(1, args.processes - 1)},step=3")
    else:
        job = _load_target(args.target)
        faults = args.faults

    ckpt_base = args.checkpoint_dir or (
        tempfile.mkdtemp(prefix="rlt_supervise_smoke_") if args.smoke
        else os.path.join(os.getcwd(), "rlt_logs", "supervise"))
    ckpt_dir = os.path.join(ckpt_base, "kill") if args.smoke else ckpt_base
    cfg = ResilienceConfig(
        checkpoint_dir=ckpt_dir,
        policy=RetryPolicy(max_restarts=args.max_restarts,
                           backoff_base_s=0.5 if args.smoke else 2.0),
        save_every_n_steps=args.save_every,
        stall_timeout_s=args.stall_timeout,
        heartbeat_interval_s=1.0 if args.smoke else 5.0,
        faults=faults,
    )
    out: dict = {"checkpoint_dir": ckpt_base, "faults": faults}
    supervised, fields = _run_supervised_job(job, cfg, args)
    out.update(fields)
    if supervised is None:
        print(json.dumps(out) if getattr(args, "as_json", False)
              else f"supervise FAILED: {out.get('error')}",
              file=None if getattr(args, "as_json", False) else sys.stderr)
        return 1
    acc = out.get("val_accuracy")
    if args.smoke:
        # the gate's contract: the kill FIRED (otherwise the run proved
        # nothing) and the resumed run still converged
        recovered = supervised.total_attempts >= 2
        converged = acc is not None and acc > 0.8
        out["ok"] = recovered and converged
        if not recovered:
            out["error"] = ("injected fault never fired — the smoke run "
                            "exercised nothing")
        elif not converged:
            out["error"] = f"resumed run did not converge (acc={acc})"
        if not getattr(args, "no_guard", False):
            legs = _smoke_guard_legs(args, ckpt_base)
            out["guard_legs"] = legs
            if not all(leg["ok"] for leg in legs.values()):
                out["ok"] = False
                out.setdefault("error", "; ".join(
                    f"{name}: {leg.get('error')}"
                    for name, leg in legs.items() if not leg["ok"]))
    if getattr(args, "as_json", False):
        print(json.dumps(out))
    else:
        status = "ok" if out["ok"] else "FAILED"
        print(f"supervise {status}: attempts={out['attempts']} "
              f"restarts={out['restarts']} "
              f"preemptions={out['preemptions']} "
              f"rollbacks={out['rollbacks']} "
              + (f"val_accuracy={acc:.3f}" if acc is not None
                 else ""))
        for f in supervised.failures:
            print(f"  attempt {f['attempt']}: [{f['kind']}/{f['cause']}"
                  + (f" rank {f['rank']}" if f.get("rank") is not None
                     else "") + f"] {f['detail']}")
        for name, leg in (out.get("guard_legs") or {}).items():
            print(f"  {name}: {'ok' if leg['ok'] else 'FAILED'} "
                  + " ".join(f"{k}={v}" for k, v in leg.items()
                             if k not in ("ok",)))
        if not out["ok"]:
            print(f"error: {out.get('error')}", file=sys.stderr)
    return 0 if out["ok"] else 1
