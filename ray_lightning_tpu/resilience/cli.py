"""``python -m ray_lightning_tpu supervise`` — run a training job under
the resilience supervisor, from the command line.

Two modes:

  --smoke         the CI fault-injection gate (wired into format.sh): a
                  supervised CPU-SPMD MNIST-class run with one injected
                  worker kill. It must auto-resume from the step-cadence
                  checkpoint and converge — exit 0 proves the whole
                  kill -> classify -> relaunch -> resume path on a box
                  with no accelerator.

  <target>        ``pkg.mod:factory`` where factory() returns a dict with
                  module_factory / trainer_factory / data_factory — the
                  same triple fit_distributed takes. Supervision knobs
                  (--max-restarts, --faults, --checkpoint-dir) apply.

Fault specs (--faults / RLT_FAULTS) are documented in
resilience/faults.py and docs/RESILIENCE.md.
"""
from __future__ import annotations

import argparse
import json
import sys


# ---- smoke job: module-level factories (cloudpickled by reference;
# workers import this module, which is on their path by construction) ----

_SMOKE_CLASSES = 4
_SMOKE_ROWS = 256
_SMOKE_BATCH = 16


def _smoke_module():
    from ray_lightning_tpu.models.mlp import MLPClassifier

    return MLPClassifier(features=(32,), num_classes=_SMOKE_CLASSES, lr=5e-2)


def _smoke_trainer():
    from ray_lightning_tpu import DataParallel, Trainer

    return Trainer(
        strategy=DataParallel(),
        max_epochs=2,
        enable_progress_bar=False,
        enable_checkpointing=False,  # the supervisor adds its own cadence
        seed=0,
    )


def _smoke_data():
    import jax
    import numpy as np

    from ray_lightning_tpu import DataLoader

    rng = np.random.default_rng(0)
    centers = rng.normal(size=(_SMOKE_CLASSES, 8)) * 3
    y = rng.integers(0, _SMOKE_CLASSES, size=_SMOKE_ROWS)
    x = (centers[y] + rng.normal(size=(_SMOKE_ROWS, 8)) * 0.1).astype(
        np.float32)
    shard = dict(num_shards=jax.process_count(),
                 shard_index=jax.process_index())
    train = DataLoader({"x": x, "y": y}, batch_size=_SMOKE_BATCH,
                       shuffle=True, **shard)
    val = DataLoader({"x": x, "y": y}, batch_size=_SMOKE_BATCH, **shard)
    return train, val


def add_supervise_parser(sub) -> None:
    p = sub.add_parser(
        "supervise",
        help="run a distributed fit under the resilience supervisor "
             "(restart + resume on transient failures; "
             "docs/RESILIENCE.md)")
    p.add_argument("target", nargs="?", default=None,
                   help="pkg.mod:factory returning {module_factory, "
                        "trainer_factory, data_factory}; omit with "
                        "--smoke")
    p.add_argument("--smoke", action="store_true",
                   help="built-in CPU-SPMD convergence gate with one "
                        "injected worker kill (the format.sh gate)")
    p.add_argument("--processes", type=int, default=2)
    p.add_argument("--devices-per-process", type=int, default=1)
    p.add_argument("--platform", default="cpu",
                   help="jax platform for the workers (cpu for the "
                        "smoke gate; unset/tpu on a pod)")
    p.add_argument("--faults", default=None,
                   help="fault-injection plan, e.g. 'kill:rank=1,step=3' "
                        "(default for --smoke: exactly that)")
    p.add_argument("--max-restarts", type=int, default=2)
    p.add_argument("--save-every", type=int, default=1,
                   help="step-cadence checkpoint interval the resume "
                        "rides on")
    p.add_argument("--checkpoint-dir", default=None,
                   help="supervisor checkpoint dir (default: a temp dir "
                        "for --smoke, ./rlt_logs/supervise otherwise)")
    p.add_argument("--stall-timeout", type=float, default=0.0,
                   help="silent-heartbeat budget in seconds "
                        "(0 disables the stall watchdog)")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="per-attempt wall-clock budget")
    # same SUPPRESS trick as the plan parser: don't clobber a --json
    # given before the subcommand
    p.add_argument("--json", action="store_true", dest="as_json",
                   default=argparse.SUPPRESS)


def _load_target(spec: str):
    import importlib

    mod_name, _, attr = spec.partition(":")
    if not attr:
        raise SystemExit(f"error: target must be pkg.mod:factory, "
                         f"got {spec!r}")
    factory = getattr(importlib.import_module(mod_name), attr)
    job = factory()
    missing = {"module_factory", "trainer_factory", "data_factory"} - set(job)
    if missing:
        raise SystemExit(
            f"error: {spec} returned no {sorted(missing)} "
            "(need module_factory/trainer_factory/data_factory)")
    return job


def run_supervise(args) -> int:
    import os
    import tempfile

    from ray_lightning_tpu.resilience.policy import RetryPolicy
    from ray_lightning_tpu.resilience.supervisor import (
        ResilienceConfig,
        SupervisedFailure,
        fit_supervised,
    )

    if not args.smoke and not args.target:
        print("error: pass a pkg.mod:factory target or --smoke",
              file=sys.stderr)
        return 2
    if args.smoke:
        job = {"module_factory": _smoke_module,
               "trainer_factory": _smoke_trainer,
               "data_factory": _smoke_data}
        faults = args.faults if args.faults is not None else (
            f"kill:rank={min(1, args.processes - 1)},step=3")
    else:
        job = _load_target(args.target)
        faults = args.faults

    ckpt_dir = args.checkpoint_dir or (
        tempfile.mkdtemp(prefix="rlt_supervise_smoke_") if args.smoke
        else os.path.join(os.getcwd(), "rlt_logs", "supervise"))
    cfg = ResilienceConfig(
        checkpoint_dir=ckpt_dir,
        policy=RetryPolicy(max_restarts=args.max_restarts,
                           backoff_base_s=0.5 if args.smoke else 2.0),
        save_every_n_steps=args.save_every,
        stall_timeout_s=args.stall_timeout,
        heartbeat_interval_s=1.0 if args.smoke else 5.0,
        faults=faults,
    )
    out: dict = {"checkpoint_dir": ckpt_dir, "faults": faults}
    try:
        supervised = fit_supervised(
            job["module_factory"], job["trainer_factory"],
            job["data_factory"], args.processes,
            resilience=cfg,
            platform=args.platform or None,
            num_cpu_devices_per_process=(
                args.devices_per_process if args.platform == "cpu"
                else None),
            return_weights=False,
            timeout=args.timeout,
        )
    except SupervisedFailure as exc:
        out.update({"ok": False, "error": str(exc),
                    "classified": exc.classified.to_dict()})
        print(json.dumps(out) if getattr(args, "as_json", False)
              else f"supervise FAILED: {exc}",
              file=None if getattr(args, "as_json", False) else sys.stderr)
        return 1
    metrics = supervised.result.metrics
    acc = metrics.get("ptl/val_accuracy")
    out.update({
        "ok": True,
        "restarts": supervised.restarts,
        "preemptions": supervised.preemptions,
        "attempts": supervised.total_attempts,
        "failures": supervised.failures,
        "metrics": {k: v for k, v in metrics.items()
                    if isinstance(v, (int, float))},
    })
    if args.smoke:
        # the gate's contract: the kill FIRED (otherwise the run proved
        # nothing) and the resumed run still converged
        recovered = supervised.total_attempts >= 2
        converged = acc is not None and float(acc) > 0.8
        out["ok"] = recovered and converged
        if not recovered:
            out["error"] = ("injected fault never fired — the smoke run "
                            "exercised nothing")
        elif not converged:
            out["error"] = f"resumed run did not converge (acc={acc})"
    if getattr(args, "as_json", False):
        print(json.dumps(out))
    else:
        status = "ok" if out["ok"] else "FAILED"
        print(f"supervise {status}: attempts={out['attempts']} "
              f"restarts={out['restarts']} "
              f"preemptions={out['preemptions']} "
              + (f"val_accuracy={float(acc):.3f}" if acc is not None
                 else ""))
        for f in supervised.failures:
            print(f"  attempt {f['attempt']}: [{f['kind']}/{f['cause']}"
                  + (f" rank {f['rank']}" if f.get("rank") is not None
                     else "") + f"] {f['detail']}")
        if not out["ok"]:
            print(f"error: {out.get('error')}", file=sys.stderr)
    return 0 if out["ok"] else 1
