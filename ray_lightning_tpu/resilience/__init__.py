"""Resilience: supervised elastic training over the runtime substrate.

The reference delegated every fault to Ray's actor-restart machinery; our
native runtime (runtime/group.py, runtime/fit.py) detects a dead worker
and raises — one SIGTERM'd host on a v5p-64 used to lose the whole run.
This package is the supervision layer between the driver API and the
worker group (docs/RESILIENCE.md):

  * policy.py     — failure taxonomy (RETRYABLE / PREEMPTION / FATAL) +
                    RetryPolicy (capped exponential backoff, restart
                    budget); import-light by design (no jax).
  * supervisor.py — supervise()/fit_supervised(): tear down, re-launch,
                    resume from the latest VALID checkpoint
                    (checkpoint.latest_checkpoint) via the trainer's
                    mid-epoch resume bookkeeping.
  * preempt.py    — SIGTERM/preemption notice -> flag-only handler ->
                    emergency checkpoint + graceful drain at the next
                    batch boundary (the async-signal-safe pattern of
                    bench.py's kill handlers).
  * health.py     — per-worker heartbeats over the existing queue
                    channel + a stall watchdog that distinguishes
                    "compiling" (live channel, no step progress) from
                    "hung" (silent channel).
  * faults.py     — deterministic fault injection (kill worker R at
                    step N, drop the coordinator, corrupt the latest
                    checkpoint, poison a batch, flip a parameter bit on
                    one chip, ...) via RLT_FAULTS, so the whole
                    subsystem is testable on CPU with launch_cpu_spmd.
  * guard.py      — trainguard: in-step numerics guard compiled into
                    the jitted train step (NaN/spike -> in-jit skip, no
                    new host syncs), escalation to CORRUPTION rollbacks
                    from the last blessed checkpoint, and a cadenced
                    per-device parameter-fingerprint probe that catches
                    silent data corruption and quarantines the
                    divergent host.

Surfaces: ``fit_distributed(..., resilience=ResilienceConfig(...))``,
``python -m ray_lightning_tpu supervise``, and sweep trial-level retry
(``sweep.run(..., retry_policy=RetryPolicy(...))``).
"""
from ray_lightning_tpu.resilience.policy import (
    FailureClass,
    FailureKind,
    RetryPolicy,
    StallError,
    classify_failure,
)
from ray_lightning_tpu.resilience.preempt import (
    PreemptedError,
    PreemptionGuard,
    install_preemption_handlers,
    preemption_requested,
    reset_preemption,
)
from ray_lightning_tpu.resilience.health import (
    HEARTBEAT_KIND,
    HealthMonitor,
    HeartbeatCallback,
)
from ray_lightning_tpu.resilience.faults import (
    Fault,
    FaultInjector,
    corrupt_checkpoint,
    parse_faults,
)
from ray_lightning_tpu.resilience.guard import (
    GuardCallback,
    GuardConfig,
    GuardState,
    SDCDetectedError,
    TrainingAnomalyError,
)
from ray_lightning_tpu.resilience.supervisor import (
    ResilienceConfig,
    RestartBudgetExceeded,
    SupervisedFailure,
    SupervisedResult,
    fit_supervised,
    supervise,
)

__all__ = [
    "FailureClass",
    "FailureKind",
    "RetryPolicy",
    "StallError",
    "classify_failure",
    "PreemptedError",
    "PreemptionGuard",
    "install_preemption_handlers",
    "preemption_requested",
    "reset_preemption",
    "HEARTBEAT_KIND",
    "HealthMonitor",
    "HeartbeatCallback",
    "Fault",
    "FaultInjector",
    "corrupt_checkpoint",
    "parse_faults",
    "GuardCallback",
    "GuardConfig",
    "GuardState",
    "SDCDetectedError",
    "TrainingAnomalyError",
    "ResilienceConfig",
    "RestartBudgetExceeded",
    "SupervisedFailure",
    "SupervisedResult",
    "fit_supervised",
    "supervise",
]
