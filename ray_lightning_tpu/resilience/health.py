"""Per-worker heartbeats + a stall watchdog over the existing queue channel.

The runtime's failure detector (group._check_liveness) only sees a worker
that DIED. A worker that is alive-but-wedged — a deadlocked collective, a
hung device tunnel — looks identical to one spending 20 minutes in XLA
compilation, and the reference's answer (Ray actor health checks) is gone.
The distinction this module draws:

  live channel, step advancing     -> healthy
  live channel, step frozen        -> "compiling or slow step": logged
                                      once, NOT killed (big-model compiles
                                      legitimately take tens of minutes;
                                      killing them would re-pay the
                                      compile forever)
  silent channel past the budget   -> hung: StallError (RETRYABLE)

Worker side: ``HeartbeatCallback`` runs a daemon thread that ships a tiny
dict over ``session.put_queue`` — the same side channel tune reports ride,
so no new sockets, and items interleave with results in the driver pump.
Driver side: ``HealthMonitor.consume`` absorbs those items from the pump's
``on_queue_item`` and ``HealthMonitor.check`` runs inside the pump's idle
slices (WorkerGroup.wait's ``watchdog`` hook).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from ray_lightning_tpu.analysis.lockwatch import san_lock
from ray_lightning_tpu.core.callbacks import Callback
from ray_lightning_tpu.resilience.policy import StallError
from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)

#: queue items with this "kind" are heartbeats, consumed by the monitor
#: before user on_queue_item callbacks ever see them
HEARTBEAT_KIND = "rlt.heartbeat"


def make_heartbeat(rank: int, step: int, phase: str = "step",
                   span: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """``phase`` is the worker's CURRENT telemetry span phase (what the
    main thread is inside right now); ``span`` is the last completed
    span's summary — together a silent-channel stall report can say
    "hung in ckpt_stall at step 812" instead of just "hung"."""
    hb = {"kind": HEARTBEAT_KIND, "rank": rank, "step": int(step),
          "phase": phase, "sent_at": time.time()}
    if span:
        hb["span"] = {"phase": span.get("phase"),
                      "dur": span.get("dur"), "step": span.get("step")}
    return hb


def is_heartbeat(item: Any) -> bool:
    return isinstance(item, dict) and item.get("kind") == HEARTBEAT_KIND


class HeartbeatCallback(Callback):
    """Worker-side sender. A plain daemon thread (not the training loop)
    so heartbeats keep flowing while the main thread sits inside a
    compile or a long collective — that is precisely the signal that
    distinguishes "compiling" from "hung"."""

    def __init__(self, interval_s: float = 5.0):
        self.interval_s = interval_s
        self._stop: Optional[threading.Event] = None
        self._trainer = None

    def on_fit_start(self, trainer, module) -> None:
        from ray_lightning_tpu.runtime import session

        if not session.is_session_enabled():
            return  # not inside a runtime worker (e.g. local Trainer.fit)
        self._trainer = trainer
        self._stop = threading.Event()
        rank = session.get_actor_rank()
        stop = self._stop

        def _beat():
            while not stop.wait(self.interval_s):
                try:
                    step = int(self._trainer.global_step)
                    # the telemetry recorder's live phase is the
                    # authoritative answer to "what is this worker
                    # doing"; without one, fall back to the step-counter
                    # heuristic this module used before telemetry existed
                    rec = getattr(self._trainer, "telemetry_recorder",
                                  None)
                    phase = rec.current_phase() if rec is not None \
                        and rec.enabled else ""
                    span = rec.last_span() if rec is not None \
                        and rec.enabled else None
                    if not phase:
                        phase = "step" if step > 0 else "setup"
                    session.put_queue(
                        make_heartbeat(rank, step, phase, span=span))
                except Exception:  # noqa: BLE001 — channel closing during
                    # teardown, or a send racing shutdown; never crash the
                    # worker over telemetry
                    return

        threading.Thread(target=_beat, daemon=True,
                         name=f"rlt-heartbeat-{rank}").start()

    def _shutdown(self) -> None:
        if self._stop is not None:
            self._stop.set()
            self._stop = None

    def on_fit_end(self, trainer, module) -> None:
        self._shutdown()

    def on_exception(self, trainer, module, exc) -> None:
        self._shutdown()


class HealthMonitor:
    """Driver-side staleness tracker.

    ``stall_timeout_s`` — silent-channel budget AFTER a rank's first
    heartbeat (before it, ``startup_grace_s`` applies: spawn + imports +
    jax.distributed rendezvous happen heartbeat-less).
    ``step_stall_note_s`` — live-channel-no-progress threshold for the
    advisory "compiling or slow step" log line.
    """

    def __init__(self, num_workers: int, stall_timeout_s: float = 180.0,
                 startup_grace_s: float = 600.0,
                 step_stall_note_s: float = 120.0):
        self.num_workers = num_workers
        self.stall_timeout_s = stall_timeout_s
        self.startup_grace_s = startup_grace_s
        self.step_stall_note_s = step_stall_note_s
        self._lock = san_lock("resilience.health.monitor")
        self.reset()

    def reset(self) -> None:
        with self._lock:
            now = time.monotonic()
            self._started = now
            self._last_seen: Dict[int, float] = {}
            self._last_step: Dict[int, int] = {}
            self._step_since: Dict[int, float] = {}
            self._last_phase: Dict[int, str] = {}
            self._noted_stall: set = set()

    def consume(self, rank: int, item: Any) -> bool:
        """Absorb ``item`` if it is a heartbeat; True when consumed."""
        if not is_heartbeat(item):
            return False
        now = time.monotonic()
        with self._lock:
            hb_rank = int(item.get("rank", rank))
            step = int(item.get("step", -1))
            self._last_seen[hb_rank] = now
            self._last_phase[hb_rank] = str(item.get("phase", ""))
            if self._last_step.get(hb_rank) != step:
                self._last_step[hb_rank] = step
                self._step_since[hb_rank] = now
                self._noted_stall.discard(hb_rank)
        return True

    def check(self, now: Optional[float] = None) -> None:
        """Raise StallError for a hung rank; log (once per stall episode)
        for a live-but-not-stepping rank. Called from the pump's idle
        slices — must stay cheap."""
        now = time.monotonic() if now is None else now
        with self._lock:
            for rank in range(self.num_workers):
                seen = self._last_seen.get(rank)
                if seen is None:
                    if now - self._started > self.startup_grace_s:
                        raise StallError(
                            rank, now - self._started,
                            "no heartbeat ever arrived (worker never "
                            "reached the fit loop)")
                    continue
                silent = now - seen
                if silent > self.stall_timeout_s:
                    raise StallError(
                        rank, silent,
                        phase=self._last_phase.get(rank, ""),
                        step=self._last_step.get(rank, -1))
                frozen = now - self._step_since.get(rank, now)
                if (frozen > self.step_stall_note_s
                        and rank not in self._noted_stall):
                    self._noted_stall.add(rank)
                    phase = self._last_phase.get(rank, "")
                    if phase == "compile":
                        # not an inference from a frozen counter: the
                        # worker's live compile span says so
                        log.warning(
                            "rank %d: inside an XLA compile for %.0fs "
                            "(telemetry span; heartbeats live, step %d) "
                            "— not killing; big-model compiles "
                            "legitimately take tens of minutes",
                            rank, frozen, self._last_step.get(rank, -1))
                    else:
                        log.warning(
                            "rank %d: heartbeats live but step %d "
                            "unchanged for %.0fs%s — a slow step or a "
                            "wedged phase (not killing; the "
                            "silent-channel budget is %.0fs)",
                            rank, self._last_step.get(rank, -1), frozen,
                            f" (phase {phase!r})" if phase else "",
                            self.stall_timeout_s)

    def snapshot(self) -> Dict[int, Dict[str, Any]]:
        """Telemetry view (tests + CLI): per-rank last-seen age / step /
        reported phase."""
        now = time.monotonic()
        with self._lock:
            return {
                r: {"silent_s": now - self._last_seen[r],
                    "step": self._last_step.get(r, -1),
                    "phase": self._last_phase.get(r, "")}
                for r in self._last_seen
            }
