"""Preemption handling: SIGTERM -> emergency checkpoint -> graceful drain.

Production TPU pods are preempted, not stopped: the platform delivers
SIGTERM (or a metadata preemption notice) and the process has a grace
window to get its state durable. The async-signal-safe pattern here is
the one bench.py's kill handlers established: the handler itself does the
MINIMUM legal work (set a flag, os.write a notice — no allocation, no
locks, no jax), and the training loop acts on the flag at the next batch
boundary, where a collective-consistent checkpoint is possible.

Why not checkpoint inside the handler? A signal can land mid-collective:
calling into jax from the handler could deadlock every rank. All ranks
receive the platform's SIGTERM (a pod preemption is host-level), so all
ranks observe their own flag at the same batch boundary and the
emergency ``save_checkpoint`` below is a valid collective.

The drain raises ``PreemptedError`` after the save; the driver's
supervisor classifies it PREEMPTION and resumes from the emergency
checkpoint on fresh capacity.
"""
from __future__ import annotations

import os
import signal
import time
from typing import Optional, Sequence

from ray_lightning_tpu.core.callbacks import Callback
from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)

#: flag state mutated ONLY by the signal handler (single attribute
#: assignments — atomic w.r.t. the interpreter, safe in a handler)
_STATE = {"signame": None, "at": None}


class PreemptedError(RuntimeError):
    """Raised by the drain path after the emergency checkpoint landed.
    The NAME is part of the protocol: it travels to the driver inside the
    worker traceback and policy.classify_failure keys on it."""

    def __init__(self, signame: str, checkpoint_path: Optional[str]):
        self.signame = signame
        self.checkpoint_path = checkpoint_path
        where = (f"; emergency checkpoint at {checkpoint_path}"
                 if checkpoint_path else "; no emergency checkpoint")
        super().__init__(
            f"training drained after preemption notice ({signame}){where}")


def _handler(signum, frame):  # noqa: ARG001 — signal handler shape
    try:
        name = signal.Signals(signum).name
    except ValueError:
        name = f"signal {signum}"
    _STATE["signame"] = name
    _STATE["at"] = time.monotonic()
    # os.write, not print/logging: allocation-free and re-entrant
    # (the bench.py kill-handler discipline)
    os.write(2, f"# preemption notice: {name}\n".encode())


def install_preemption_handlers(
    signals: Sequence[int] = (signal.SIGTERM,),
) -> None:
    """Install the flag-only handlers. Idempotent; a non-main thread or
    an exotic host that refuses leaves the previous disposition."""
    for sig in signals:
        try:
            signal.signal(sig, _handler)
        except (ValueError, OSError):
            log.warning("could not install preemption handler for %s", sig)


def preemption_requested() -> Optional[str]:
    """Signal name when a preemption notice arrived, else None."""
    return _STATE["signame"]


def reset_preemption() -> None:
    _STATE["signame"] = None
    _STATE["at"] = None


class PreemptionGuard(Callback):
    """Batch-boundary drain: on a pending preemption notice, write an
    emergency checkpoint (blocking — it must be durable before the grace
    period expires) and unwind with PreemptedError.

    ``grace_s`` is advisory bookkeeping: the guard logs how much of the
    platform's window the save consumed, so an operator can see when the
    grace budget is too tight for the model size.
    """

    def __init__(self, dirpath: str, grace_s: float = 30.0,
                 install: bool = True,
                 signals: Sequence[int] = (signal.SIGTERM,)):
        self.dirpath = dirpath
        self.grace_s = grace_s
        self._install = install
        self._signals = tuple(signals)

    def on_fit_start(self, trainer, module) -> None:
        if self._install:
            install_preemption_handlers(self._signals)

    def _drain(self, trainer) -> None:
        signame = preemption_requested()
        if signame is None:
            return
        started = _STATE["at"] or time.monotonic()
        path = os.path.join(
            self.dirpath, f"preempt-step={trainer.global_step}")
        ckpt: Optional[str] = None
        # Drain in-flight ASYNC saves first: their checkpoints may be the
        # resume fallback if the emergency save below doesn't finish
        # inside the grace window, so they must be finalized (meta +
        # digest published) — and a failed one must be invalidated, not
        # allowed to fail the emergency save itself.
        try:
            from ray_lightning_tpu.checkpoint import wait_for_checkpoints

            wait_for_checkpoints()
        except Exception:  # noqa: BLE001 — the torn write stays
            # unfinalized (invalid, skipped on resume); keep draining
            log.exception("in-flight async checkpoint failed during "
                          "preemption drain; it will be skipped on resume")
        try:
            # block=True: an async write could still be streaming when
            # the platform pulls the plug — durability beats latency here
            ckpt = trainer.save_checkpoint(path, block=True)
        except Exception:  # noqa: BLE001 — drain anyway; resume falls
            # back to the previous periodic checkpoint
            log.exception("emergency checkpoint failed; draining without")
        used = time.monotonic() - started
        log.warning(
            "preemption drain: %s at step %d, emergency save took %.1fs "
            "of the %.0fs grace window", signame, trainer.global_step,
            used, self.grace_s)
        raise PreemptedError(signame, ckpt)

    def on_train_batch_end(self, trainer, module, metrics, batch_idx) -> None:
        self._drain(trainer)

    def on_train_epoch_end(self, trainer, module) -> None:
        self._drain(trainer)
