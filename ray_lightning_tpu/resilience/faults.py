"""Deterministic fault injection — the test harness the subsystem is
built against (TorchTitan-style: every recovery path must be provable on
CPU, no pod required).

A fault plan is a spec string (env ``RLT_FAULTS`` or
``ResilienceConfig.faults``), semicolon-separated::

    kill:rank=1,step=3            SIGKILL the worker (a vanished host)
    preempt:rank=0,step=2         SIGTERM self (a preemption notice;
                                  rank 0 = "drop the coordinator" when
                                  combined with kill)
    raise:rank=0,step=2           raise RuntimeError (a FATAL user bug)
    exit:rank=1,step=3,rc=7       os._exit(rc) (a crashed runtime)
    hang:rank=1,step=3,secs=600   stop stepping AND stop heartbeating
                                  (exercises the stall watchdog)
    corrupt_latest:rank=0,step=3,dir=/ckpts
                                  flip bytes in the newest checkpoint's
                                  state (latest_checkpoint must skip it)
    nan_loss:rank=0,step=3,count=1
                                  poison the batch about to become step
                                  3 (NaN into its float leaves' local
                                  shards) so the loss goes NaN for
                                  ``count`` consecutive steps — the
                                  trainguard must skip them in-jit
    grad_blowup:rank=0,step=3,scale=1e18,count=1
                                  scale the batch's float leaves so the
                                  loss/grad blow up (spike/overflow)
    bitflip_param:rank=1,step=3,bit=12,leaf=0,element=0,device=0
                                  flip ONE mantissa bit of one param
                                  element in ONE local device's replica
                                  on the matching rank — a silent data
                                  corruption only the trainguard's SDC
                                  fingerprint probe can see

``rank=*`` matches every rank. Each fault fires ONCE per plan across
restarts: a marker file is written under ``RLT_FAULT_STATE_DIR`` BEFORE
the fault fires (crash-safe ordering — a kill cannot lose the marker),
so the restarted run sails past the step that killed its predecessor.
Without a state dir, once-ness is per-process only.
"""
from __future__ import annotations

import dataclasses
import os
import signal
import time
from typing import Dict, List, Optional

from ray_lightning_tpu.core.callbacks import Callback
from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)

FAULTS_ENV = "RLT_FAULTS"
FAULT_STATE_ENV = "RLT_FAULT_STATE_DIR"

_KINDS = ("kill", "preempt", "raise", "exit", "hang", "corrupt_latest",
          "nan_loss", "grad_blowup", "bitflip_param")

#: kinds that poison the BATCH before the step dispatches (they ride the
#: trainer's on_train_batch_start replacement seam); all other kinds
#: fire at the batch-end boundary. ``step=k`` for these means "the batch
#: that would become global step k" — so the anomaly lands exactly at
#: step k, mirroring the batch-end kinds' step semantics.
_BATCH_START_KINDS = ("nan_loss", "grad_blowup")


@dataclasses.dataclass(frozen=True)
class Fault:
    kind: str
    rank: Optional[int]          # None = every rank ("*")
    step: int                    # fires when global_step >= step
    args: Dict[str, str] = dataclasses.field(default_factory=dict)
    index: int = 0               # position in the plan (the marker key)

    def marker(self, rank: int) -> str:
        # per-RANK once-ness: a rank=* fault (e.g. the all-hosts SIGTERM
        # of a pod preemption) must fire on EVERY matching rank — a
        # shared marker would let the first rank to reach the step
        # suppress the others, leaving one rank draining through a
        # collective emergency save the rest never joined (observed as a
        # gloo EnforceNotMet -> SIGABRT)
        return f"fault-{self.index}-{self.kind}-step{self.step}-r{rank}"

    def matches(self, rank: int, step: int) -> bool:
        return (self.rank is None or self.rank == rank) and step >= self.step


def parse_faults(spec: Optional[str]) -> List[Fault]:
    """Parse a plan spec; raises ValueError with the offending clause so
    a typo'd injection fails the run loudly instead of silently testing
    nothing."""
    faults: List[Fault] = []
    for i, clause in enumerate(c.strip() for c in (spec or "").split(";")):
        if not clause:
            continue
        kind, _, rest = clause.partition(":")
        kind = kind.strip()
        if kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in {clause!r} "
                f"(known: {', '.join(_KINDS)})")
        args: Dict[str, str] = {}
        for pair in filter(None, (p.strip() for p in rest.split(","))):
            k, sep, v = pair.partition("=")
            if not sep:
                raise ValueError(f"malformed fault arg {pair!r} in {clause!r}")
            args[k.strip()] = v.strip()
        rank_s = args.pop("rank", "*")
        rank = None if rank_s == "*" else int(rank_s)
        step = int(args.pop("step", "1"))
        faults.append(Fault(kind, rank, step, args, index=i))
    return faults


def corrupt_checkpoint(path: str) -> bool:
    """Flip bytes mid-way through the largest file under ``path`` —
    a torn/garbled write the checksum in meta.json must catch. Returns
    True when something was corrupted."""
    biggest, size = None, -1
    for root, _, files in os.walk(path):
        for f in files:
            if f == "meta.json":
                continue  # corrupt STATE, keep the completeness marker —
                # the checkpoint must look finished-but-damaged
            p = os.path.join(root, f)
            try:
                s = os.path.getsize(p)
            except OSError:
                continue
            if s > size:
                biggest, size = p, s
    if biggest is None or size <= 0:
        return False
    with open(biggest, "r+b") as fh:
        fh.seek(size // 2)
        chunk = fh.read(64) or b"\x00"
        fh.seek(size // 2)
        fh.write(bytes(b ^ 0xFF for b in chunk))
    log.warning("fault injection: corrupted %s (%d bytes at offset %d)",
                biggest, len(chunk), size // 2)
    return True


def _mutate_local_shards(arr, fn, only_device=None):
    """Rebuild a (possibly multi-process) jax.Array from THIS process's
    addressable shards with ``fn(numpy_copy) -> mutated?`` applied — to
    every local shard, or to ``only_device``'s alone. Other processes
    keep their original arrays untouched: the replicas genuinely
    diverge, which is exactly what a hardware fault does. No collective
    ops run (an SPMD-inconsistent computation on a global array would
    deadlock the other ranks)."""
    import jax
    import numpy as np

    shards = getattr(arr, "addressable_shards", None)
    if not shards:
        return arr
    bufs, changed = [], False
    for s in shards:
        data = np.array(s.data)  # host copy
        if (only_device is None or s.device == only_device) and fn(data):
            changed = True
        bufs.append(jax.device_put(data, s.device))
    if not changed:
        return arr
    return jax.make_array_from_single_device_arrays(
        arr.shape, arr.sharding, bufs)


def _poison_batch(batch, kind: str, scale: float):
    """nan_loss / grad_blowup batch poisoning: every float leaf's local
    shards get a NaN in element 0 (nan_loss) or a blow-up scale
    (grad_blowup). The loss is a global reduction, so a single poisoned
    rank poisons the step identically on every rank — the skip decision
    the guard compiles in stays SPMD-consistent."""
    import jax
    import numpy as np

    def mutate(data):
        if not np.issubdtype(data.dtype, np.floating) or data.size == 0:
            return False
        if kind == "nan_loss":
            data.reshape(-1)[0] = np.nan
        else:
            np.multiply(data, data.dtype.type(scale), out=data)
        return True

    return jax.tree.map(lambda x: _mutate_local_shards(x, mutate), batch)


def _bitflip_param_tree(params, leaf_idx: int, element: int, bit: int,
                        device):
    """Flip one mantissa bit of one element in ONE device's local
    replica of the ``leaf_idx``-th float param leaf. Returns the new
    tree (shared-structure except the flipped leaf)."""
    import jax
    import numpy as np

    flat, treedef = jax.tree_util.tree_flatten(params)
    float_positions = [i for i, x in enumerate(flat)
                       if np.issubdtype(x.dtype, np.floating)]
    if not float_positions:
        return params
    pos = float_positions[leaf_idx % len(float_positions)]

    def flip(data):
        itemsize = data.dtype.itemsize
        view_dtype = {2: np.uint16, 4: np.uint32, 8: np.uint64}.get(
            itemsize)
        if view_dtype is None or data.size == 0:
            return False
        view = data.view(view_dtype).reshape(-1)
        view[element % view.size] ^= view_dtype(1 << (bit % (8 * itemsize)))
        return True

    flat[pos] = _mutate_local_shards(flat[pos], flip, only_device=device)
    return jax.tree_util.tree_unflatten(treedef, flat)


class FaultInjector(Callback):
    """Fires plan faults at batch boundaries on the matching rank (the
    batch-poisoning kinds fire at batch START, through the trainer's
    batch replacement seam)."""

    def __init__(self, faults: List[Fault],
                 state_dir: Optional[str] = None):
        self.faults = faults
        self.state_dir = state_dir
        self._fired_local: set = set()
        #: remaining poison budget per fired batch-start fault (the
        #: ``count=N`` arg poisons N consecutive batches within the run
        #: that fired it; the once-marker still spans restarts)
        self._active: Dict[str, int] = {}

    # -- once-ness ---------------------------------------------------------
    def _already_fired(self, fault: Fault, rank: int) -> bool:
        marker = fault.marker(rank)
        if marker in self._fired_local:
            return True
        if self.state_dir:
            return os.path.exists(os.path.join(self.state_dir, marker))
        return False

    def _mark_fired(self, fault: Fault, rank: int) -> None:
        # marker BEFORE the fault fires: a kill must not re-fire on resume
        marker = fault.marker(rank)
        self._fired_local.add(marker)
        if self.state_dir:
            os.makedirs(self.state_dir, exist_ok=True)
            with open(os.path.join(self.state_dir, marker), "w") as f:
                f.write(str(time.time()))

    # -- firing ------------------------------------------------------------
    def _rank(self) -> int:
        from ray_lightning_tpu.runtime import session

        if session.is_session_enabled():
            return session.get_actor_rank()
        return 0

    def _fire(self, fault: Fault, trainer) -> None:
        log.warning("fault injection: firing %s (rank=%s step>=%d) at "
                    "global_step=%d", fault.kind, fault.rank, fault.step,
                    trainer.global_step)
        if fault.kind == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        elif fault.kind == "exit":
            os._exit(int(fault.args.get("rc", "1")))
        elif fault.kind == "preempt":
            # deliver a real SIGTERM: the flag-only handler + the
            # PreemptionGuard drain are both on the tested path
            os.kill(os.getpid(), signal.SIGTERM)
        elif fault.kind == "raise":
            raise RuntimeError(
                f"injected fatal failure at step {trainer.global_step} "
                f"(fault plan #{fault.index})")
        elif fault.kind == "hang":
            time.sleep(float(fault.args.get("secs", "600")))
        elif fault.kind == "bitflip_param":
            self._fire_bitflip(fault, trainer)
        elif fault.kind == "corrupt_latest":
            target = fault.args.get("dir")
            if not target:
                raise ValueError("corrupt_latest fault needs dir=<ckpt dir>")
            newest = _newest_checkpoint_dir(target)
            if newest is not None:
                corrupt_checkpoint(newest)

    def _fire_bitflip(self, fault: Fault, trainer) -> None:
        """Silent data corruption: one mantissa bit of one param element
        flips in ONE local device's replica — invisible to every check
        except a cross-replica fingerprint comparison."""
        import jax

        state = getattr(trainer, "state", None)
        if state is None or state.params is None:
            return
        local = jax.local_devices()
        device = local[int(fault.args.get("device", "0")) % len(local)]
        params = _bitflip_param_tree(
            state.params,
            leaf_idx=int(fault.args.get("leaf", "0")),
            element=int(fault.args.get("element", "0")),
            bit=int(fault.args.get("bit", "12")),
            device=device)
        trainer.state = state.replace(params=params)

    def on_train_batch_start(self, trainer, module, batch, batch_idx):
        rank = self._rank()
        out = batch
        for fault in self.faults:
            if fault.kind not in _BATCH_START_KINDS:
                continue
            key = fault.marker(rank)
            remaining = self._active.get(key)
            if remaining is None:
                # step=k poisons the batch that becomes global step k
                if not fault.matches(rank, trainer.global_step + 1):
                    continue
                if self._already_fired(fault, rank):
                    continue
                self._mark_fired(fault, rank)
                remaining = int(fault.args.get("count", "1"))
            if remaining <= 0:
                continue
            self._active[key] = remaining - 1
            log.warning(
                "fault injection: poisoning batch for %s (rank=%s "
                "step>=%d, %d more) at global_step=%d", fault.kind,
                fault.rank, fault.step, remaining - 1,
                trainer.global_step)
            out = _poison_batch(
                out, fault.kind,
                scale=float(fault.args.get("scale", "1e18")))
        return out if out is not batch else None

    def on_train_batch_end(self, trainer, module, metrics, batch_idx) -> None:
        rank = self._rank()
        for fault in self.faults:
            if fault.kind in _BATCH_START_KINDS:
                continue
            if not fault.matches(rank, trainer.global_step):
                continue
            if self._already_fired(fault, rank):
                continue
            self._mark_fired(fault, rank)
            self._fire(fault, trainer)


def _newest_checkpoint_dir(root: str) -> Optional[str]:
    """Newest checkpoint SUBDIR by mtime — deliberately NOT
    latest_checkpoint(): the injector wants the newest dir regardless of
    validity; the validity filter is the code under test."""
    try:
        subdirs = [os.path.join(root, d) for d in os.listdir(root)
                   if os.path.isdir(os.path.join(root, d))]
    except OSError:
        return None
    return max(subdirs, key=os.path.getmtime, default=None)


def faults_from_env() -> List[Fault]:
    return parse_faults(os.environ.get(FAULTS_ENV))


def maybe_install_faults(trainer) -> Optional[FaultInjector]:
    """Attach a FaultInjector built from the environment (no-op without
    RLT_FAULTS). Called by the supervisor's worker-side trainer wrapper;
    usable directly by any test harness."""
    faults = faults_from_env()
    if not faults:
        return None
    injector = FaultInjector(faults, os.environ.get(FAULT_STATE_ENV))
    trainer.callbacks.append(injector)
    return injector
