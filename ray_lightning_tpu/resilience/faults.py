"""Deterministic fault injection — the test harness the subsystem is
built against (TorchTitan-style: every recovery path must be provable on
CPU, no pod required).

A fault plan is a spec string (env ``RLT_FAULTS`` or
``ResilienceConfig.faults``), semicolon-separated::

    kill:rank=1,step=3            SIGKILL the worker (a vanished host)
    preempt:rank=0,step=2         SIGTERM self (a preemption notice;
                                  rank 0 = "drop the coordinator" when
                                  combined with kill)
    raise:rank=0,step=2           raise RuntimeError (a FATAL user bug)
    exit:rank=1,step=3,rc=7       os._exit(rc) (a crashed runtime)
    hang:rank=1,step=3,secs=600   stop stepping AND stop heartbeating
                                  (exercises the stall watchdog)
    corrupt_latest:rank=0,step=3,dir=/ckpts
                                  flip bytes in the newest checkpoint's
                                  state (latest_checkpoint must skip it)

``rank=*`` matches every rank. Each fault fires ONCE per plan across
restarts: a marker file is written under ``RLT_FAULT_STATE_DIR`` BEFORE
the fault fires (crash-safe ordering — a kill cannot lose the marker),
so the restarted run sails past the step that killed its predecessor.
Without a state dir, once-ness is per-process only.
"""
from __future__ import annotations

import dataclasses
import os
import signal
import time
from typing import Dict, List, Optional

from ray_lightning_tpu.core.callbacks import Callback
from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)

FAULTS_ENV = "RLT_FAULTS"
FAULT_STATE_ENV = "RLT_FAULT_STATE_DIR"

_KINDS = ("kill", "preempt", "raise", "exit", "hang", "corrupt_latest")


@dataclasses.dataclass(frozen=True)
class Fault:
    kind: str
    rank: Optional[int]          # None = every rank ("*")
    step: int                    # fires when global_step >= step
    args: Dict[str, str] = dataclasses.field(default_factory=dict)
    index: int = 0               # position in the plan (the marker key)

    def marker(self, rank: int) -> str:
        # per-RANK once-ness: a rank=* fault (e.g. the all-hosts SIGTERM
        # of a pod preemption) must fire on EVERY matching rank — a
        # shared marker would let the first rank to reach the step
        # suppress the others, leaving one rank draining through a
        # collective emergency save the rest never joined (observed as a
        # gloo EnforceNotMet -> SIGABRT)
        return f"fault-{self.index}-{self.kind}-step{self.step}-r{rank}"

    def matches(self, rank: int, step: int) -> bool:
        return (self.rank is None or self.rank == rank) and step >= self.step


def parse_faults(spec: Optional[str]) -> List[Fault]:
    """Parse a plan spec; raises ValueError with the offending clause so
    a typo'd injection fails the run loudly instead of silently testing
    nothing."""
    faults: List[Fault] = []
    for i, clause in enumerate(c.strip() for c in (spec or "").split(";")):
        if not clause:
            continue
        kind, _, rest = clause.partition(":")
        kind = kind.strip()
        if kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in {clause!r} "
                f"(known: {', '.join(_KINDS)})")
        args: Dict[str, str] = {}
        for pair in filter(None, (p.strip() for p in rest.split(","))):
            k, sep, v = pair.partition("=")
            if not sep:
                raise ValueError(f"malformed fault arg {pair!r} in {clause!r}")
            args[k.strip()] = v.strip()
        rank_s = args.pop("rank", "*")
        rank = None if rank_s == "*" else int(rank_s)
        step = int(args.pop("step", "1"))
        faults.append(Fault(kind, rank, step, args, index=i))
    return faults


def corrupt_checkpoint(path: str) -> bool:
    """Flip bytes mid-way through the largest file under ``path`` —
    a torn/garbled write the checksum in meta.json must catch. Returns
    True when something was corrupted."""
    biggest, size = None, -1
    for root, _, files in os.walk(path):
        for f in files:
            if f == "meta.json":
                continue  # corrupt STATE, keep the completeness marker —
                # the checkpoint must look finished-but-damaged
            p = os.path.join(root, f)
            try:
                s = os.path.getsize(p)
            except OSError:
                continue
            if s > size:
                biggest, size = p, s
    if biggest is None or size <= 0:
        return False
    with open(biggest, "r+b") as fh:
        fh.seek(size // 2)
        chunk = fh.read(64) or b"\x00"
        fh.seek(size // 2)
        fh.write(bytes(b ^ 0xFF for b in chunk))
    log.warning("fault injection: corrupted %s (%d bytes at offset %d)",
                biggest, len(chunk), size // 2)
    return True


class FaultInjector(Callback):
    """Fires plan faults at batch boundaries on the matching rank."""

    def __init__(self, faults: List[Fault],
                 state_dir: Optional[str] = None):
        self.faults = faults
        self.state_dir = state_dir
        self._fired_local: set = set()

    # -- once-ness ---------------------------------------------------------
    def _already_fired(self, fault: Fault, rank: int) -> bool:
        marker = fault.marker(rank)
        if marker in self._fired_local:
            return True
        if self.state_dir:
            return os.path.exists(os.path.join(self.state_dir, marker))
        return False

    def _mark_fired(self, fault: Fault, rank: int) -> None:
        # marker BEFORE the fault fires: a kill must not re-fire on resume
        marker = fault.marker(rank)
        self._fired_local.add(marker)
        if self.state_dir:
            os.makedirs(self.state_dir, exist_ok=True)
            with open(os.path.join(self.state_dir, marker), "w") as f:
                f.write(str(time.time()))

    # -- firing ------------------------------------------------------------
    def _rank(self) -> int:
        from ray_lightning_tpu.runtime import session

        if session.is_session_enabled():
            return session.get_actor_rank()
        return 0

    def _fire(self, fault: Fault, trainer) -> None:
        log.warning("fault injection: firing %s (rank=%s step>=%d) at "
                    "global_step=%d", fault.kind, fault.rank, fault.step,
                    trainer.global_step)
        if fault.kind == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        elif fault.kind == "exit":
            os._exit(int(fault.args.get("rc", "1")))
        elif fault.kind == "preempt":
            # deliver a real SIGTERM: the flag-only handler + the
            # PreemptionGuard drain are both on the tested path
            os.kill(os.getpid(), signal.SIGTERM)
        elif fault.kind == "raise":
            raise RuntimeError(
                f"injected fatal failure at step {trainer.global_step} "
                f"(fault plan #{fault.index})")
        elif fault.kind == "hang":
            time.sleep(float(fault.args.get("secs", "600")))
        elif fault.kind == "corrupt_latest":
            target = fault.args.get("dir")
            if not target:
                raise ValueError("corrupt_latest fault needs dir=<ckpt dir>")
            newest = _newest_checkpoint_dir(target)
            if newest is not None:
                corrupt_checkpoint(newest)

    def on_train_batch_end(self, trainer, module, metrics, batch_idx) -> None:
        rank = self._rank()
        for fault in self.faults:
            if not fault.matches(rank, trainer.global_step):
                continue
            if self._already_fired(fault, rank):
                continue
            self._mark_fired(fault, rank)
            self._fire(fault, trainer)


def _newest_checkpoint_dir(root: str) -> Optional[str]:
    """Newest checkpoint SUBDIR by mtime — deliberately NOT
    latest_checkpoint(): the injector wants the newest dir regardless of
    validity; the validity filter is the code under test."""
    try:
        subdirs = [os.path.join(root, d) for d in os.listdir(root)
                   if os.path.isdir(os.path.join(root, d))]
    except OSError:
        return None
    return max(subdirs, key=os.path.getmtime, default=None)


def faults_from_env() -> List[Fault]:
    return parse_faults(os.environ.get(FAULTS_ENV))


def maybe_install_faults(trainer) -> Optional[FaultInjector]:
    """Attach a FaultInjector built from the environment (no-op without
    RLT_FAULTS). Called by the supervisor's worker-side trainer wrapper;
    usable directly by any test harness."""
    faults = faults_from_env()
    if not faults:
        return None
    injector = FaultInjector(faults, os.environ.get(FAULT_STATE_ENV))
    trainer.callbacks.append(injector)
    return injector
