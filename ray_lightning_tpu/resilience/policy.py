"""Failure taxonomy + retry policy — the supervisor's decision core.

Classification answers ONE question: is restarting the worker group and
resuming from the latest valid checkpoint going to help? Four answers:

  RETRYABLE  — infrastructure flaked (backend unavailable, a worker
               process vanished with a nonzero rc, a stall/timeout, a
               dropped coordinator). The SAME job on the SAME data is
               expected to succeed; restart within the budget.
  PREEMPTION — the platform is reclaiming capacity (SIGTERM on a
               worker, our PreemptedError drain). Also restartable, but
               counted separately: a preemption storm is capacity
               pressure, not a bug, and operators read the two numbers
               differently.
  CORRUPTION — the trainguard (resilience/guard.py) escalated: a run of
               anomalous steps (NaN/spike streak) or a silent-data-
               corruption verdict from the replica fingerprint probe.
               Restartable, but NOT from the latest checkpoint — the
               supervisor rolls back to the last *blessed* checkpoint,
               advances the data order past the poisoned window, and
               quarantines the divergent rank if one was named. Drawn
               from its own (small) ``max_rollbacks`` budget.
  FATAL      — a deterministic Python exception in user/model code (a
               shape error, an assert). Restarting replays the same
               failure N more times and burns the budget; fail fast
               with the classified cause.

This module is import-light BY DESIGN (stdlib only, no jax, no package
imports): bench.py classifies mid-run backend losses with it before any
backend exists, and runtime modules can import it without cycles.
``WorkerError`` is therefore matched structurally (class name + the
rank/cause attributes runtime/group.py attaches), not by isinstance.
"""
from __future__ import annotations

import dataclasses
import random
from typing import Optional


class FailureKind:
    RETRYABLE = "retryable"
    PREEMPTION = "preemption"
    CORRUPTION = "corruption"
    FATAL = "fatal"


class StallError(RuntimeError):
    """A worker's heartbeat channel went silent past the stall budget
    (health.HealthMonitor) — the process is hung, not compiling.

    ``phase``/``step`` come from the last heartbeat's telemetry payload
    (the worker's current span phase): the report upgrades from "hung"
    to "hung in <phase> at step N" — the difference between rebooting a
    pod and knowing to look at the checkpoint filesystem."""

    def __init__(self, rank: int, silent_s: float, detail: str = "",
                 phase: str = "", step: int = -1):
        self.rank = rank
        self.silent_s = silent_s
        self.phase = phase
        self.step = step
        msg = (f"worker rank {rank} sent no heartbeat for "
               f"{silent_s:.0f}s (channel silent — hung, not compiling)")
        if phase:
            msg += (f"; last reported doing {phase!r}"
                    + (f" at step {step}" if step >= 0 else ""))
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


#: traceback / message markers that mean the *infrastructure* failed —
#: the job itself never got a verdict. Matched case-sensitively against
#: the worker traceback or the exception text.
_RETRYABLE_MARKERS = (
    "UNAVAILABLE",            # jaxlib XlaRuntimeError: UNAVAILABLE
    "DEADLINE_EXCEEDED",
    "coordinator",            # jax.distributed rendezvous failures
    "Connection reset",
    "Connection refused",
    "Connection closed by peer",  # a collective PEER died mid-op — the
    #                             surviving rank's view of another
    #                             rank's death (gloo surfaces it as
    #                             FAILED_PRECONDITION, not UNAVAILABLE);
    #                             which rank's failure reaches the
    #                             driver first is a race, and both views
    #                             must classify the same way (observed:
    #                             the kill-drill gate flaking FATAL when
    #                             the survivor's error won)
    "gloo/transport",         # gloo TRANSPORT-layer failures (tcp pair
    #                           resets, timeouts — the source path
    #                           appears in the message) = peer/link
    #                           loss; deliberately NOT a blanket "gloo"
    #                           marker, which would relabel a
    #                           deterministic bug raising through a
    #                           collective as infrastructure
    "Timed out waiting for clients",  # gloo rendezvous: peers never came
    "BrokenPipeError",
    "backend unavailable",
    "heartbeat",
)

#: preemption markers: our own drain exception, plus the signals a
#: platform reclaim delivers. SIGKILL is deliberately NOT here: a
#: platform preemption announces itself with SIGTERM first; a bare
#: SIGKILL is the OOM killer or a hard host failure — restartable, but
#: drawn from the BOUNDED restart budget (a deterministic memory
#: overrun must not get max_preemptions' worth of futile replays).
_PREEMPT_MARKERS = ("PreemptedError", "preemption notice")

_PREEMPT_SIGNALS = ("SIGTERM", "SIGINT", "SIGHUP", "SIGQUIT")

#: trainguard escalation markers (resilience/guard.py): the exception
#: NAMES are the cross-process protocol — they appear verbatim in the
#: worker traceback when a rank unwinds on an anomaly-streak or SDC
#: verdict. SDCDetectedError subclasses TrainingAnomalyError, so order
#: matters: match the more specific name first for the cause slug.
_CORRUPTION_MARKERS = ("SDCDetectedError", "TrainingAnomalyError",
                       "silent data corruption",
                       "training anomaly escalation")


def _corruption_cause(text: str) -> str:
    return "sdc" if ("SDCDetectedError" in text
                     or "silent data corruption" in text) else \
        "anomaly-streak"


@dataclasses.dataclass(frozen=True)
class FailureClass:
    """One classified failure: the verdict plus what the operator reads."""

    kind: str                    # FailureKind.*
    cause: str                   # short slug, e.g. "worker-signal:SIGKILL"
    rank: Optional[int] = None   # failing rank when known
    detail: str = ""             # first line of the underlying error

    @property
    def restartable(self) -> bool:
        return self.kind != FailureKind.FATAL

    def to_dict(self) -> dict:
        return {"kind": self.kind, "cause": self.cause, "rank": self.rank,
                "detail": self.detail}


def _first_line(exc: BaseException) -> str:
    text = str(exc).strip()
    return text.splitlines()[0][:300] if text else type(exc).__name__


def _worker_detail(exc: BaseException) -> str:
    """For a WorkerError: the last non-empty traceback line — the actual
    exception repr — not the boilerplate first line."""
    tb = (getattr(exc, "traceback_str", "") or "").strip()
    lines = [ln for ln in tb.splitlines() if ln.strip()]
    return lines[-1][:300] if lines else _first_line(exc)


def _looks_like_worker_error(exc: BaseException) -> bool:
    # structural match (import-light: see module docstring)
    return (type(exc).__name__ == "WorkerError"
            and hasattr(exc, "rank") and hasattr(exc, "traceback_str"))


def classify_failure(exc: BaseException) -> FailureClass:
    """Map an exception from a supervised run to a FailureClass."""
    name = type(exc).__name__
    text = str(exc)

    if _looks_like_worker_error(exc):
        rank = getattr(exc, "rank", None)
        cause = getattr(exc, "cause", "exception")
        signame = getattr(exc, "signal_name", None)
        tb = getattr(exc, "traceback_str", "") or ""
        if signame in _PREEMPT_SIGNALS or any(
                m in tb for m in _PREEMPT_MARKERS):
            return FailureClass(
                FailureKind.PREEMPTION,
                f"worker-signal:{signame}" if signame else "worker-preempt",
                rank, _worker_detail(exc))
        if cause in ("exit", "signal"):
            # the process vanished without returning a Python verdict —
            # infra (OOM-killer, node loss, a crashed runtime)
            slug = (f"worker-signal:{signame}" if signame
                    else f"worker-exit:{getattr(exc, 'exit_code', None)}")
            return FailureClass(FailureKind.RETRYABLE, slug, rank,
                                _worker_detail(exc))
        if any(m in tb for m in _CORRUPTION_MARKERS):
            # the trainguard unwound this rank on purpose: restart is a
            # ROLLBACK (blessed checkpoint + data-order advance), not a
            # replay of the latest one
            return FailureClass(FailureKind.CORRUPTION,
                                _corruption_cause(tb), rank,
                                _worker_detail(exc))
        if any(m in tb for m in _RETRYABLE_MARKERS):
            return FailureClass(FailureKind.RETRYABLE, "worker-backend",
                                rank, _worker_detail(exc))
        # a real Python traceback out of user/model code: deterministic
        return FailureClass(FailureKind.FATAL, "worker-exception", rank,
                            _worker_detail(exc))

    if name in ("TrainingAnomalyError", "SDCDetectedError") or any(
            m in text for m in _CORRUPTION_MARKERS):
        return FailureClass(FailureKind.CORRUPTION,
                            _corruption_cause(f"{name} {text}"), None,
                            _first_line(exc))
    if isinstance(exc, StallError):
        return FailureClass(FailureKind.RETRYABLE, "stall",
                            getattr(exc, "rank", None), _first_line(exc))
    if isinstance(exc, TimeoutError):
        return FailureClass(FailureKind.RETRYABLE, "timeout", None,
                            _first_line(exc))
    if name == "PreemptedError" or any(m in text for m in _PREEMPT_MARKERS):
        return FailureClass(FailureKind.PREEMPTION, "preempt", None,
                            _first_line(exc))
    if name == "BackendUnavailable":
        # bench.py's bounded init-retry already spent its budget getting
        # here — retrying the whole run would just double the wait, but
        # the caller may still carry a restart budget of its own
        return FailureClass(FailureKind.RETRYABLE, "backend-unavailable",
                            None, _first_line(exc))
    if isinstance(exc, (ConnectionError, EOFError, OSError)):
        return FailureClass(FailureKind.RETRYABLE, "connection", None,
                            _first_line(exc))
    if any(m in text for m in _RETRYABLE_MARKERS):
        return FailureClass(FailureKind.RETRYABLE, "backend", None,
                            _first_line(exc))
    return FailureClass(FailureKind.FATAL, f"exception:{name}", None,
                        _first_line(exc))


@dataclasses.dataclass
class RetryPolicy:
    """Capped exponential backoff + a restart budget.

    ``max_restarts`` bounds TOTAL restarts across the run (attempt 0 is
    the original launch). ``preemptions_count`` controls whether
    PREEMPTION failures draw from the budget — on a preemptible pool a
    nightly run may legitimately be preempted dozens of times, so the
    default excludes them (bounded instead by ``max_preemptions``).
    """

    max_restarts: int = 3
    backoff_base_s: float = 2.0
    backoff_factor: float = 2.0
    backoff_max_s: float = 60.0
    jitter: float = 0.1          # +- fraction of the delay
    preemptions_count: bool = False
    max_preemptions: int = 100
    #: CORRUPTION rollbacks (trainguard escalations) get their own small
    #: budget: each one rewinds real progress to the last blessed
    #: checkpoint, so unlike preemptions they must stay rare — and a run
    #: that keeps corrupting is hardware begging to be drained, not
    #: restarted forever.
    max_rollbacks: int = 2

    def next_delay(self, restart_idx: int) -> float:
        """Delay before restart number ``restart_idx`` (1-based)."""
        exp = self.backoff_base_s * (
            self.backoff_factor ** max(0, restart_idx - 1))
        delay = min(self.backoff_max_s, exp)
        if self.jitter:
            delay *= 1.0 + random.uniform(-self.jitter, self.jitter)
        return max(0.0, delay)

    def allows(self, restarts: int, preemptions: int,
               failure: FailureClass, rollbacks: int = 0) -> bool:
        """True when one more restart is within budget for ``failure``.
        ``restarts``/``preemptions``/``rollbacks`` are the counts
        performed so far, tracked separately by the supervisor."""
        if not failure.restartable:
            return False
        if failure.kind == FailureKind.CORRUPTION:
            return rollbacks < self.max_rollbacks
        if failure.kind == FailureKind.PREEMPTION:
            if self.preemptions_count:
                # preemptions draw from the shared budget: count BOTH
                # tallies against it (the supervisor increments only
                # `preemptions` for this kind)
                return restarts + preemptions < self.max_restarts
            return preemptions < self.max_preemptions
        return restarts < self.max_restarts
