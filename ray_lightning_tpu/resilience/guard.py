"""trainguard: in-step numerics guard, SDC detection, rollback-to-good.

PR 3's supervisor recovers from *process* failures; this module covers
the failure mode that actually ruins long TPU runs — the process stays
alive while training goes bad. Three tiers (docs/RESILIENCE.md
"trainguard"):

  tier 1  in-jit detection and skip. The train step already computes
          ``loss`` and ``grad_norm`` (core/trainer.py); the guard adds a
          finiteness check plus a loss-spike test against an EMA carried
          in the TrainState, and on anomaly a tree-select discards the
          update — params/opt-state/step pass through UNCHANGED, so one
          poisoned batch costs one skipped update, not the run. All of
          it compiles into the existing step: the anomaly flag and the
          counters ride the step's metrics outputs, which the trainer
          already fetches lazily on the log cadence — ZERO new host
          transfers (the guarded step must lint clean under RLT304 and
          its jaxpr carries no new effects; tests/test_trainguard.py
          pins both).

  tier 2  escalation and rollback. ``GuardCallback`` watches the
          counters at the moments they are host-resident anyway (the
          trainer's metric-fetch cadence — reading them costs nothing)
          and, when K anomalous steps land inside the window, writes a
          rollback marker and raises ``TrainingAnomalyError``. The
          supervisor classifies it CORRUPTION, resumes from the last
          **blessed** checkpoint (``latest_checkpoint(good_only=True,
          max_step=last_good_step)`` — the trainer stamps an
          anomaly-free-window verdict into every checkpoint's meta) and
          advances the data order past the poisoned window instead of
          replaying it.

  tier 3  SDC probe. At a configurable cadence the guard computes a
          cheap per-device parameter fingerprint (bitcast-to-uint32
          wraparound sum — order-independent, exact) via shard_map, one
          scalar per device, gathered with a single small collective.
          Devices that hold identical parameter bytes by construction
          (replicas: same coordinates on every sharded mesh axis) must
          produce identical fingerprints; a minority digest identifies
          the divergent device, and its host rank is quarantined in the
          rollback marker. A silent bit-flip on one chip is caught
          within one probe cadence instead of corrupting every
          checkpoint thereafter.
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from ray_lightning_tpu.core.callbacks import Callback
from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)

#: rollback marker file, written beside the supervisor's checkpoints on
#: escalation; the supervisor reads it to pick the rollback target and
#: the relaunched worker reads it to advance the data order. Stale
#: markers are self-invalidating: they apply only when their
#: detected_step is ahead of the restored step.
ROLLBACK_MARKER = ".trainguard_rollback.json"

#: quarantine ledger the supervisor maintains next to the marker —
#: ranks whose hardware produced a divergent parameter fingerprint.
QUARANTINE_FILE = ".quarantine.json"


# --------------------------------------------------------------- config


@dataclasses.dataclass
class GuardConfig:
    """Knobs for all three tiers. The defaults are sized for "a NaN or a
    10x loss spike is an anomaly; a handful of them in quick succession
    is corruption"."""

    #: tier 1 master switch (the compiled-in checks)
    enabled: bool = True
    #: loss > spike_factor * EMA + spike_margin => anomaly (the margin
    #: keeps near-zero losses from flagging noise)
    spike_factor: float = 10.0
    spike_margin: float = 1.0
    ema_decay: float = 0.9
    #: anomaly-free steps the EMA observes before the spike test arms
    #: (finiteness checks are armed from step 0)
    warmup_steps: int = 5
    #: tier 2: escalate when >= escalate_after anomalies land within the
    #: trailing escalate_window steps. Detection latency is bounded by
    #: the trainer's metric-fetch cadence (log_every_n_steps) — the
    #: counters are only read when they are host-resident anyway.
    escalate_after: int = 4
    escalate_window: int = 16
    #: a checkpoint is stamped blessed iff no anomaly occurred within
    #: this many updates before the save (and no streak is active)
    bless_clean_steps: int = 4
    #: tier 3: run the SDC fingerprint probe every N steps (0 disables)
    sdc_every_n_steps: int = 0

    @classmethod
    def coerce(cls, value) -> "GuardConfig":
        if value is True:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, dict):
            return cls(**value)
        raise TypeError(f"cannot build GuardConfig from {value!r}")


# ----------------------------------------------------------- tier 1 jit


@flax.struct.dataclass
class GuardState:
    """The guard's slice of the TrainState — five replicated scalars, so
    carrying it costs nothing next to the params."""

    ema: jnp.ndarray           # f32: EMA of finite losses
    seen: jnp.ndarray          # i32: finite losses observed (EMA warmup)
    skipped: jnp.ndarray       # i32: total anomalous updates discarded
    streak: jnp.ndarray        # i32: consecutive anomalous steps
    last_anomaly: jnp.ndarray  # i32: update index of the last anomaly, -1


def init_guard_state() -> GuardState:
    return GuardState(
        ema=jnp.zeros((), jnp.float32),
        seen=jnp.zeros((), jnp.int32),
        skipped=jnp.zeros((), jnp.int32),
        streak=jnp.zeros((), jnp.int32),
        last_anomaly=jnp.full((), -1, jnp.int32),
    )


def abstract_guard_state() -> GuardState:
    """ShapeDtypeStruct twin of ``init_guard_state`` for jaxpr-level
    audits (bench.py's guard summary) — no backend is ever touched."""
    s = jax.ShapeDtypeStruct
    return GuardState(ema=s((), jnp.float32), seen=s((), jnp.int32),
                      skipped=s((), jnp.int32), streak=s((), jnp.int32),
                      last_anomaly=s((), jnp.int32))


def apply_guard(cfg: GuardConfig, guard: GuardState, step, loss, grad_norm,
                new_params, old_params, new_opt, old_opt):
    """The tier-1 core, called INSIDE the jitted train step.

    Returns ``(params, opt_state, new_step, new_guard, metrics)``: on an
    anomaly the candidate update is discarded by a tree-select (params /
    opt-state / step pass through unchanged — the step index not
    advancing keeps the per-step RNG fold and optimizer bias-correction
    schedule identical to a run that never saw the poisoned batch), and
    the flag/counters are returned as ordinary metric scalars so they
    ride the existing lazy metrics fetch. No cond branches with side
    effects, no callbacks, no transfers.
    """
    loss32 = jnp.asarray(loss).astype(jnp.float32)
    gn32 = jnp.asarray(grad_norm).astype(jnp.float32)
    finite = jnp.isfinite(loss32) & jnp.isfinite(gn32)
    warmed = guard.seen >= cfg.warmup_steps
    spike = warmed & (loss32 > cfg.spike_factor * guard.ema
                      + cfg.spike_margin)
    bad = (~finite) | spike
    badi = bad.astype(jnp.int32)
    first = guard.seen == 0
    ema = jnp.where(
        bad, guard.ema,
        jnp.where(first, loss32,
                  cfg.ema_decay * guard.ema
                  + (1.0 - cfg.ema_decay) * loss32))
    new_guard = GuardState(
        ema=ema,
        seen=guard.seen + 1 - badi,
        skipped=guard.skipped + badi,
        streak=jnp.where(bad, guard.streak + 1, 0),
        last_anomaly=jnp.where(bad, jnp.asarray(step, jnp.int32),
                               guard.last_anomaly),
    )
    keep = lambda new, old: jnp.where(bad, old, new)  # noqa: E731
    params = jax.tree.map(keep, new_params, old_params)
    opt_state = jax.tree.map(keep, new_opt, old_opt)
    new_step = jnp.where(bad, step, step + 1)
    metrics = {
        "guard_anomaly": badi,
        "guard_skipped_steps": new_guard.skipped,
        "guard_streak": new_guard.streak,
        "guard_last_anomaly": new_guard.last_anomaly,
        "guard_loss_ema": ema,
    }
    return params, opt_state, new_step, new_guard, metrics


def bless_verdict(cfg: GuardConfig, guard_host, update_step: int) -> bool:
    """Anomaly-free-window verdict stamped into checkpoint meta
    (``blessed``): no active streak and the last anomaly at least
    ``bless_clean_steps`` updates behind the save point."""
    streak = int(np.asarray(guard_host.streak))
    last = int(np.asarray(guard_host.last_anomaly))
    return streak == 0 and (last < 0
                            or update_step - last >= cfg.bless_clean_steps)


# ------------------------------------------------------------ exceptions


class TrainingAnomalyError(RuntimeError):
    """Tier-2 escalation: K anomalous steps inside the window. The NAME
    is part of the protocol — it travels to the driver inside the worker
    traceback and ``policy.classify_failure`` keys on it (CORRUPTION)."""

    def __init__(self, detected_step: int, count: int, window: int,
                 last_good_step: int):
        self.detected_step = detected_step
        self.last_good_step = last_good_step
        super().__init__(
            f"training anomaly escalation: {count} anomalous step(s) "
            f"within the last {window} steps (detected at step "
            f"{detected_step}; last known-good step {last_good_step}) — "
            "rolling back to the last blessed checkpoint")


class SDCDetectedError(TrainingAnomalyError):
    """Tier-3 verdict: parameter fingerprints diverged across replicas —
    silent data corruption on the named rank(s)."""

    def __init__(self, suspect_ranks: Sequence[int], detected_step: int,
                 last_good_step: int, digests: Sequence[int] = ()):
        self.suspect_ranks = list(suspect_ranks)
        self.detected_step = detected_step
        self.last_good_step = last_good_step
        self.digests = list(digests)
        who = (f"rank(s) {self.suspect_ranks}" if self.suspect_ranks
               else "an unattributable replica (no majority)")
        RuntimeError.__init__(
            self,
            f"silent data corruption detected at step {detected_step}: "
            f"parameter fingerprints diverged across replicas — {who}; "
            f"last probe-verified step {last_good_step}. Rolling back "
            "to the last blessed checkpoint and quarantining the host")


# -------------------------------------------------------- rollback marker


def write_rollback_marker(dirpath: str, payload: Dict[str, Any]) -> None:
    """Atomic (tmp + os.replace), rank-0 only — same discipline as
    checkpoint meta.json. The marker is the worker->driver side channel
    that survives the process teardown."""
    if jax.process_index() != 0:
        return
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, ROLLBACK_MARKER)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def read_rollback_marker(dirpath: str) -> Optional[Dict[str, Any]]:
    path = os.path.join(dirpath, ROLLBACK_MARKER)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ------------------------------------------------------------ tier 3 SDC


def _leaf_digest(x) -> jnp.ndarray:
    """Bitcast-to-uint32 wraparound sum of one leaf block. Exact and
    order-independent (unsigned addition is associative/commutative mod
    2^32), so any reduction schedule yields the same fingerprint and a
    single flipped bit always changes it — EVERY stored bit must reach
    the sum (a lossy cast would make low-bit corruption invisible, the
    exact thing the probe exists to catch), so each dtype width is
    bitcast at its own width and 64-bit words are folded as two 32-bit
    halves."""
    nbits = jnp.dtype(x.dtype).itemsize * 8
    if jnp.issubdtype(x.dtype, jnp.floating):
        uint = {8: jnp.uint8, 16: jnp.uint16, 32: jnp.uint32,
                64: jnp.uint64}[nbits]
        u = jax.lax.bitcast_convert_type(x, uint)
    elif x.dtype == jnp.bool_:
        u = x.astype(jnp.uint32)
    else:
        u = x.astype({8: jnp.uint8, 16: jnp.uint16, 32: jnp.uint32,
                      64: jnp.uint64}[nbits])
    if u.dtype == jnp.uint64:  # only reachable with x64 enabled
        lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (u >> jnp.uint64(32)).astype(jnp.uint32)
        return (jnp.sum(lo, dtype=jnp.uint32)
                + jnp.sum(hi, dtype=jnp.uint32))
    return jnp.sum(u.astype(jnp.uint32), dtype=jnp.uint32)


def _tree_digest(tree) -> jnp.ndarray:
    total = jnp.zeros((), jnp.uint32)
    for i, leaf in enumerate(jax.tree.leaves(tree)):
        # fold the leaf index in so two leaves swapping contents changes
        # the fingerprint despite the commutative sum
        total = total + _leaf_digest(leaf) * jnp.uint32(2 * i + 1)
    return total


def _spec_of(leaf):
    from jax.sharding import NamedSharding, PartitionSpec as P

    s = getattr(leaf, "sharding", None)
    if isinstance(s, NamedSharding):
        return s.spec
    return P()


def replica_groups(params, mesh) -> List[List[int]]:
    """Groups of flat device indices (``mesh.devices.reshape(-1)``
    order) that hold bit-identical parameter bytes by construction:
    devices whose coordinates agree on every axis any param is sharded
    over. Pure DP -> one group of all devices; pure FSDP -> singletons
    (no redundancy to cross-check; the probe degrades to recording)."""
    sharded_axes: set = set()
    for leaf in jax.tree.leaves(params):
        for dim in _spec_of(leaf):
            if dim is None:
                continue
            for name in (dim if isinstance(dim, tuple) else (dim,)):
                sharded_axes.add(name)
    axes = tuple(mesh.axis_names)
    sizes = [dict(mesh.shape)[a] for a in axes]
    n = int(np.prod(sizes)) if sizes else 1
    groups: Dict[Tuple, List[int]] = {}
    for i in range(n):
        coords = np.unravel_index(i, sizes) if sizes else ()
        key = tuple(int(c) for a, c in zip(axes, coords)
                    if a in sharded_axes)
        groups.setdefault(key, []).append(i)
    return [g for g in groups.values() if len(g) >= 2]


def diagnose_digests(digests: Sequence[int],
                     groups: Sequence[Sequence[int]]
                     ) -> Tuple[List[int], bool]:
    """Compare per-device fingerprints within each replica group.
    Returns ``(suspect_device_indices, comparable)``: majority vote
    flags the minority devices; a group with no strict majority flags
    every disagreeing member (attribution indeterminate — with only two
    replicas a mismatch cannot name the liar). ``comparable`` is False
    when no group had redundancy to check."""
    suspects: set = set()
    comparable = False
    for g in groups:
        vals = [int(digests[i]) for i in g]
        counts = Counter(vals)
        comparable = True
        if len(counts) == 1:
            continue
        top, topn = counts.most_common(1)[0]
        if 2 * topn > len(g):
            suspects |= {i for i in g if int(digests[i]) != top}
        else:
            suspects |= set(g)
    return sorted(suspects), comparable


def build_sdc_probe(params, mesh):
    """Compile the fingerprint probe for this param tree/mesh.

    Returns ``(fn, devices, groups)``: ``fn(params)`` is a jitted
    function producing one uint32 fingerprint per device (a shard_map —
    each device digests its OWN local bytes, which is the whole point:
    under plain jit, XLA assumes replicas are consistent and a psum
    would launder the corruption away), gathered to a replicated
    ``(n_devices,)`` vector so every process can fetch it — one small
    collective per probe, nothing per step."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_lightning_tpu.ops import dispatch

    devices = list(mesh.devices.flat)
    groups = replica_groups(params, mesh)
    if len(devices) == 1:
        fn = jax.jit(lambda p: _tree_digest(p).reshape((1,)))
        return fn, devices, groups
    specs = jax.tree.map(_spec_of, params)
    axes = tuple(mesh.axis_names)

    def per_device(p):
        return _tree_digest(p).reshape((1,))

    mapped = dispatch.shard_map(per_device, mesh, in_specs=(specs,),
                                out_specs=P(axes),
                                check_replication=False)
    fn = jax.jit(mapped, out_shardings=NamedSharding(mesh, P()))
    return fn, devices, groups


# -------------------------------------------------------- GuardCallback


class GuardCallback(Callback):
    """Tiers 2+3, host side. Reads the tier-1 counters only at the
    moments the trainer has already fetched them (the log cadence) —
    escalation costs zero additional host syncs; the SDC probe runs
    under its own ``step % N == 0`` cadence guard."""

    def __init__(self, cfg: GuardConfig, marker_dir: Optional[str] = None):
        self.cfg = GuardConfig.coerce(cfg)
        self.marker_dir = marker_dir
        self._hist: List[Tuple[int, float]] = []   # (global_step, skipped)
        self._base = 0.0           # skipped count that aged out of the window
        self._last_good = 0
        self._probe = None
        self._probe_devices: List = []
        self._probe_groups: List[List[int]] = []
        self._probes_run = 0
        self._probe_ok_step = 0
        self._rollbacks_prior = 0

    # -- lifecycle ---------------------------------------------------------

    def _dir(self, trainer) -> str:
        return self.marker_dir or trainer.default_root_dir

    def on_fit_start(self, trainer, module) -> None:
        self._hist = []
        self._base = 0.0
        self._last_good = trainer.global_step
        self._probe_ok_step = trainer.global_step
        if self.cfg.sdc_every_n_steps:
            # retention floor input (core/callbacks.py _prune): with the
            # probe armed, the rollback target must sit at/below the
            # last probe-VERIFIED step — newer checkpoints are blessed
            # yet possibly silently poisoned. The restore point itself
            # counts as verified (it passed its digest check on load).
            trainer._guard_probe_ok_step = trainer.global_step
        marker = read_rollback_marker(self._dir(trainer))
        self._rollbacks_prior = int((marker or {}).get(
            "rollbacks_performed", 0))
        trainer.callback_metrics["guard_rollbacks"] = float(
            self._rollbacks_prior)
        trainer.callback_metrics.setdefault("guard_sdc_probes", 0.0)

    # -- per batch ---------------------------------------------------------

    def on_train_batch_end(self, trainer, module, metrics, batch_idx) -> None:
        step = trainer.global_step
        skipped = metrics.get("guard_skipped_steps") if isinstance(
            metrics, dict) else None
        if skipped is not None and _is_host_value(skipped):
            streak = metrics.get("guard_streak")
            self._note(trainer, step, float(np.asarray(skipped)),
                       float(np.asarray(streak))
                       if streak is not None and _is_host_value(streak)
                       else 0.0)
        if (self.cfg.sdc_every_n_steps
                and step % self.cfg.sdc_every_n_steps == 0):
            self._run_probe(trainer)

    # -- tier 2: escalation ------------------------------------------------

    def _note(self, trainer, step: int, skipped: float,
              streak: float = 0.0) -> None:
        prev_step = self._hist[-1][0] if self._hist else None
        if self._hist and skipped <= self._hist[-1][1]:
            # no new anomalies since the previous observation: every
            # step up to here is known clean
            self._last_good = step
        elif not self._hist and skipped <= 0:
            self._last_good = step
        self._hist.append((step, skipped))
        horizon = step - self.cfg.escalate_window
        while self._hist and self._hist[0][0] < horizon:
            self._base = max(self._base, self._hist.pop(0)[1])
        # The windowed count honors the documented contract only when
        # observations are at least window-dense — with a fetch cadence
        # LONGER than the window, a skipped-count delta spans the whole
        # gap and K-spread-over-many-steps would spuriously escalate.
        # The in-jit streak counter covers that regime exactly: it is
        # per-step accurate regardless of when it is read, so K
        # CONSECUTIVE anomalies always escalate.
        dense = (prev_step is not None
                 and prev_step >= horizon)
        in_window = skipped - self._base
        if (dense and in_window >= self.cfg.escalate_after) \
                or streak >= self.cfg.escalate_after:
            self._escalate(trainer, step,
                           int(max(in_window, streak)))

    def _escalate(self, trainer, step: int, count: int) -> None:
        err = TrainingAnomalyError(step, count, self.cfg.escalate_window,
                                   self._last_good)
        write_rollback_marker(self._dir(trainer), {
            "kind": "anomaly-streak",
            "detected_step": step,
            "last_good_step": self._last_good,
            "epoch": trainer.current_epoch,
            "epoch_batch": trainer._epoch_batches_done,
            "anomalies_in_window": count,
            "quarantine": [],
            "rollbacks_performed": self._rollbacks_prior,
            "at": time.time(),
        })
        log.error("trainguard: %s", err)
        raise err

    # -- tier 3: SDC probe -------------------------------------------------

    def _run_probe(self, trainer) -> None:
        state = trainer.state
        mesh = trainer.strategy.mesh
        if state is None or mesh is None:
            return
        if self._probe is None:
            # the strategy owns the sharding policy, so it builds the
            # probe (Strategy.sdc_probe) — replica grouping must match
            # what it actually placed
            self._probe, self._probe_devices, self._probe_groups = \
                trainer.strategy.sdc_probe(state.params)
            if not self._probe_groups:
                log.info(
                    "trainguard: no replicated parameter bytes on this "
                    "mesh (every device holds a distinct shard) — the "
                    "SDC probe records fingerprints but cannot "
                    "cross-check them")
        digests = np.asarray(jax.device_get(self._probe(state.params)))
        self._probes_run += 1
        trainer.callback_metrics["guard_sdc_probes"] = float(
            self._probes_run)
        suspects, comparable = diagnose_digests(digests,
                                                self._probe_groups)
        if not comparable or not suspects:
            self._probe_ok_step = trainer.global_step
            trainer._guard_probe_ok_step = trainer.global_step
            return
        ranks = sorted({self._probe_devices[i].process_index
                        for i in suspects})
        if len(suspects) >= len(self._probe_devices):
            ranks = []  # every replica disagrees with every other:
            #             attribution impossible, still roll back
        err = SDCDetectedError(ranks, trainer.global_step,
                               self._probe_ok_step,
                               digests=[int(d) for d in digests])
        write_rollback_marker(self._dir(trainer), {
            "kind": "sdc",
            "detected_step": trainer.global_step,
            "last_good_step": self._probe_ok_step,
            "epoch": trainer.current_epoch,
            "epoch_batch": trainer._epoch_batches_done,
            "quarantine": ranks,
            "digests": [int(d) for d in digests],
            "rollbacks_performed": self._rollbacks_prior,
            "at": time.time(),
        })
        log.error("trainguard: %s", err)
        raise err


def _is_host_value(v) -> bool:
    """True when the metric value is already host-resident (the trainer
    fetched it on the log cadence) — reading it then costs nothing. A
    still-on-device jax.Array is left alone: forcing it would add the
    per-step sync this design exists to avoid."""
    return isinstance(v, (bool, int, float, np.generic, np.ndarray))
