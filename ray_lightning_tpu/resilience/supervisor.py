"""supervise(): the restart loop between the driver API and the runtime.

One supervised attempt is one ordinary ``run_distributed`` call — a fresh
worker group, launched and torn down by runtime/launch.py exactly as an
unsupervised run would be. The supervisor adds, around it:

  driver side   classify every failure (policy.classify_failure), sleep
                the backoff, pick the latest VALID checkpoint
                (checkpoint.latest_checkpoint — torn/corrupt candidates
                are skipped), and re-launch with ``ckpt_path`` pointing
                at it; the trainer's existing mid-epoch resume
                bookkeeping (core/trainer.py ``_resume_skip_batches``)
                replays the REST of the interrupted epoch, no batch
                twice, none skipped. A HealthMonitor rides the queue
                channel (heartbeats) and the pump's watchdog hook.
  worker side   the shipped trainer factory is wrapped to attach the
                periodic step-cadence checkpoint feeding the resume
                loop, the heartbeat sender, the SIGTERM drain
                (preempt.PreemptionGuard), and — when configured — the
                deterministic fault injector.

FATAL failures (a real Python exception in user code) fail fast with the
classified cause; the underlying WorkerError — rank-tagged, log tail
attached (runtime/group.py) — stays chained underneath.
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import signal as _signal
import time
from typing import Any, Callable, Dict, List, Optional

from ray_lightning_tpu.checkpoint import latest_checkpoint
from ray_lightning_tpu.resilience.health import HealthMonitor, HeartbeatCallback
from ray_lightning_tpu.resilience.policy import (
    FailureKind,
    RetryPolicy,
    classify_failure,
)
from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)


@dataclasses.dataclass
class ResilienceConfig:
    """Everything supervise() needs beyond the job itself.

    ``checkpoint_dir`` is the supervisor's OWN durable state: periodic
    step-cadence saves, preemption emergency saves, and the resume
    source of truth all live there (keep it distinct from a
    user ModelCheckpoint's dirpath — the supervisor prunes it).
    """

    checkpoint_dir: str
    policy: RetryPolicy = dataclasses.field(default_factory=RetryPolicy)
    save_every_n_steps: int = 50
    keep_checkpoints: int = 2       # >= 2: corrupt-latest still resumes
    heartbeat_interval_s: float = 5.0
    stall_timeout_s: float = 180.0  # <= 0 disables health monitoring
    startup_grace_s: float = 600.0
    preempt_grace_s: float = 30.0
    resume: str = "auto"            # "auto" | "never": pick up an earlier
    #                                 run's checkpoints on first launch
    faults: Optional[str] = None    # fault-plan spec (faults.parse_faults)
    fault_state_dir: Optional[str] = None  # fire-once markers across
    #                                 restarts (defaults beside ckpts)
    #: step-cadence checkpoints stream in the background instead of
    #: stalling every rank at each save (checkpoint/io.py block=False;
    #: docs/PERFORMANCE.md). Emergency preemption saves always block.
    async_save: bool = True
    #: persistent XLA compile cache shared across restarts, so attempt N
    #: deserializes the train step instead of recompiling it (the cold
    #: compile otherwise multiplies by the restart budget). None derives
    #: ``<checkpoint_dir>/.compile_cache``; "off" disables.
    compile_cache_dir: Optional[str] = None
    #: trainguard (resilience/guard.py): a GuardConfig (or True for the
    #: defaults) compiles the in-step anomaly guard into every worker's
    #: train step and arms escalation + the SDC probe. A CORRUPTION
    #: escalation makes the supervisor ROLL BACK: resume from the last
    #: blessed checkpoint at/below the marker's last-good step, advance
    #: the data order past the poisoned window, and record any
    #: quarantined rank in <checkpoint_dir>/.quarantine.json.
    guard: Any = None
    #: telemetry (telemetry/, docs/OBSERVABILITY.md): True (default)
    #: arms the span recorder in every worker with the run's shared
    #: ``<checkpoint_dir>/telemetry`` dir, the driver records its own
    #: attempt/backoff spans there, and supervise() assembles the
    #: goodput classification into SupervisedResult.goodput +
    #: ``telemetry/goodput.json``. False disables end to end.
    telemetry: Any = True
    #: elastic supervision (elastic/budget.py, docs/ELASTIC.md): an
    #: ElasticBudget makes the world size a LADDER instead of a pin —
    #: when the retry policy refuses another same-size relaunch (k
    #: hosts gone for good), the supervisor reshards the latest valid
    #: checkpoint onto the largest legal survivor world and resumes
    #: smaller; when the budget's capacity oracle reports capacity
    #: back, it grows on the next relaunch. Every change is recorded
    #: in SupervisedResult.reshards with its honest batch plan. None
    #: (default): fixed world size, exactly the old behavior.
    elastic: Any = None
    #: SLO watch (telemetry/watch.py, docs/OBSERVABILITY.md "watch
    #: rules & incidents"): True (or a WatchConfig / rule tuple) arms
    #: the declarative rule engine over the run's persisted evidence —
    #: evaluated driver-side after every classified failure and at the
    #: terminal bookkeeping, pure tail-bounded reads, ZERO effect on
    #: the compiled program (same discipline as telemetry=off,
    #: test-pinned). Breaches land in <checkpoint_dir>/incidents.jsonl
    #: and in SupervisedResult.incidents. None (default): off.
    watch: Any = None

    def resolved_compile_cache_dir(self) -> Optional[str]:
        if self.compile_cache_dir == "off":
            return None
        return self.compile_cache_dir or os.path.join(
            self.checkpoint_dir, ".compile_cache")

    def resolved_telemetry_dir(self) -> Optional[str]:
        if not self.telemetry:
            return None
        from ray_lightning_tpu.telemetry import TelemetryConfig

        cfg = TelemetryConfig.coerce(self.telemetry)
        return cfg.dir or os.path.join(self.checkpoint_dir, "telemetry")


@dataclasses.dataclass
class SupervisedResult:
    """The job's FitResult plus the supervision ledger."""

    result: Any                     # runtime.fit.FitResult
    restarts: int                   # retryable restarts performed
    preemptions: int                # preemption resumes performed
    failures: List[Dict[str, Any]]  # classified history, launch order
    rollbacks: int = 0              # trainguard corruption rollbacks
    quarantined: List[int] = dataclasses.field(default_factory=list)
    #                                 ranks the SDC probe attributed
    #: goodput classification of the TOTAL supervised wall time
    #: (telemetry/goodput.py buckets; None when telemetry is off) —
    #: also written to <checkpoint_dir>/telemetry/goodput.json
    goodput: Optional[Dict[str, Any]] = None
    #: elastic world-size changes, launch order (docs/ELASTIC.md): one
    #: entry per shrink/grow with from/to world, reason, and the honest
    #: batch plan (ElasticBudget.batch_plan). Also persisted append-only
    #: to <checkpoint_dir>/reshards.jsonl with a clock-alignment header
    #: (the timeline merger ingests it — docs/OBSERVABILITY.md)
    reshards: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)
    #: watch-rule breaches fired during supervision
    #: (ResilienceConfig.watch; the on-disk record is
    #: <checkpoint_dir>/incidents.jsonl)
    incidents: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)

    @property
    def final_world(self) -> Optional[int]:
        """World size of the attempt that finished (None = unchanged
        from launch). Only ACTUAL world changes count — the ledger
        also records ``grow_refused`` entries (capacity-oracle
        refusals, docs/AUTOSCALE.md) which carry no ``to_world``."""
        for entry in reversed(self.reshards):
            if entry.get("reason") in ("shrink", "grow"):
                return entry["to_world"]
        return None

    @property
    def total_attempts(self) -> int:
        return 1 + self.restarts + self.preemptions + self.rollbacks


class SupervisedFailure(RuntimeError):
    """A supervised run that will not be retried: FATAL classification.
    The original exception (WorkerError with rank + log tail) is chained
    as __cause__."""

    def __init__(self, classified, attempts: int):
        self.classified = classified
        self.attempts = attempts
        super().__init__(
            f"supervised run failed FATALLY after {attempts} attempt(s): "
            f"[{classified.kind}/{classified.cause}"
            + (f" rank {classified.rank}" if classified.rank is not None
               else "")
            + f"] {classified.detail} — restarts will not help; see the "
              "chained worker error for the rank-tagged traceback and "
              "log tail")


class RestartBudgetExceeded(SupervisedFailure):
    def __init__(self, classified, attempts: int, budget: int):
        RuntimeError.__init__(
            self,
            f"supervised run still failing after {attempts} attempt(s) "
            f"(restart budget {budget} exhausted): "
            f"[{classified.kind}/{classified.cause}] {classified.detail}")
        self.classified = classified
        self.attempts = attempts


#: on-disk reshard ledger beside the checkpoints — the elastic story's
#: evidence stream (previously only in-memory on SupervisedResult)
RESHARD_LEDGER = "reshards.jsonl"
RESHARD_LEDGER_VERSION = "rlt-reshards-v1"


def _append_reshard_ledger(directory: str, entry: Dict[str, Any]) -> None:
    """Append one reshard entry (shrink/grow/grow_refused) to
    ``<directory>/reshards.jsonl``, writing the clock-alignment header
    first when creating the file — the same ``t0_wall``/monotonic
    stamp every other ledger carries, so the timeline merger
    (telemetry/timeline.py) never guesses this stream's epoch. Entries
    additionally carry their own epoch ``at`` stamp. Best-effort: a
    failed bookkeeping write must never cost the run its relaunch."""
    try:
        with open(os.path.join(directory, RESHARD_LEDGER), "a") as f:
            if f.tell() == 0:
                f.write(json.dumps({
                    "version": RESHARD_LEDGER_VERSION,
                    "t0_wall": time.time(),
                    "t0_perf": time.perf_counter(),
                    "pid": os.getpid(),
                }) + "\n")
            f.write(json.dumps(entry, default=str) + "\n")
    except OSError:
        log.exception("could not append to the reshard ledger")


def _wrapped_trainer_factory(trainer_factory: Callable[[], Any],
                             cfg: ResilienceConfig):
    """Runs in EVERY worker process (shipped by value via cloudpickle):
    the user's trainer plus the supervision callbacks."""
    from ray_lightning_tpu.core.callbacks import ModelCheckpoint
    from ray_lightning_tpu.resilience.faults import (
        FaultInjector,
        faults_from_env,
        parse_faults,
    )
    from ray_lightning_tpu.resilience.preempt import (
        PreemptionGuard,
        reset_preemption,
    )

    trainer = trainer_factory()
    reset_preemption()  # fresh process; stale flags impossible but cheap
    cache_dir = cfg.resolved_compile_cache_dir()
    if cache_dir and not trainer.compile_cache_dir:
        # restart N must deserialize the step, not recompile it — the
        # trainer reports the (near-zero) warm compile as compile_time_s
        trainer.compile_cache_dir = cache_dir
    has_periodic = any(
        isinstance(c, ModelCheckpoint)
        and getattr(c, "dirpath", None) == cfg.checkpoint_dir
        for c in trainer.callbacks)
    # Async step-cadence saves only when this job is single-process: the
    # in-tree orbax finalizes multi-host writes with a sync_global_devices
    # barrier (an XLA psum) on its background commit thread, which could
    # interleave with the step's own collectives mid-epoch. Multi-process
    # jobs keep the blocking save until the barrier rides the
    # coordination service (docs/PERFORMANCE.md "async checkpointing").
    import jax

    async_ok = cfg.async_save and jax.process_count() == 1
    if not has_periodic:
        trainer.callbacks.append(ModelCheckpoint(
            dirpath=cfg.checkpoint_dir, monitor=None,
            every_n_train_steps=max(1, cfg.save_every_n_steps),
            save_top_k=max(2, cfg.keep_checkpoints),
            async_save=async_ok))
    if cfg.heartbeat_interval_s > 0:
        trainer.callbacks.append(
            HeartbeatCallback(cfg.heartbeat_interval_s))
    trainer.callbacks.append(PreemptionGuard(
        cfg.checkpoint_dir, grace_s=cfg.preempt_grace_s,
        signals=(_signal.SIGTERM,)))
    if cfg.guard:
        from ray_lightning_tpu.resilience.guard import (
            GuardCallback,
            GuardConfig,
            read_rollback_marker,
        )

        trainer.guard = GuardConfig.coerce(cfg.guard)
        if not any(isinstance(c, GuardCallback) for c in trainer.callbacks):
            trainer.callbacks.append(GuardCallback(
                trainer.guard, marker_dir=cfg.checkpoint_dir))
        marker = read_rollback_marker(cfg.checkpoint_dir)
        if marker:
            # after a corruption rollback: advance the data order past
            # the poisoned window (trainer._apply_rollback_skip; stale
            # markers from older incidents no-op there)
            trainer.resume_skip_past = marker
    tdir = cfg.resolved_telemetry_dir()
    if tdir and trainer.telemetry is None:
        # every supervised worker records spans + goodput ledgers into
        # the run's shared telemetry dir; an explicit Trainer(telemetry=)
        # wins — the user already chose a destination
        from ray_lightning_tpu.telemetry import TelemetryConfig

        trainer.telemetry = TelemetryConfig(dir=tdir)
    faults = parse_faults(cfg.faults) if cfg.faults else faults_from_env()
    if faults:
        state_dir = (cfg.fault_state_dir
                     or os.environ.get("RLT_FAULT_STATE_DIR")
                     or os.path.join(cfg.checkpoint_dir, ".fault_state"))
        trainer.callbacks.append(FaultInjector(faults, state_dir))
    return trainer


def supervise(
    kind: str,
    module_factory: Callable[[], Any],
    trainer_factory: Callable[[], Any],
    data_factory: Callable[[], Any],
    num_processes: int,
    *,
    resilience: ResilienceConfig,
    **kw: Any,
) -> SupervisedResult:
    """Run one distributed job under supervision; returns the job result
    plus the restart ledger. Accepts every ``run_distributed`` keyword."""
    from functools import partial

    from ray_lightning_tpu.runtime.fit import run_distributed

    cfg = resilience
    policy = cfg.policy
    os.makedirs(cfg.checkpoint_dir, exist_ok=True)

    original_ckpt = kw.pop("ckpt_path", None)
    ckpt_path = original_ckpt
    if kind == "fit" and cfg.resume == "auto":
        found = latest_checkpoint(cfg.checkpoint_dir)
        if found is not None:
            log.info("supervise: resuming from earlier run's %s", found)
            ckpt_path = found

    world = num_processes
    launch_world = num_processes

    def _make_monitor(n: int) -> Optional[HealthMonitor]:
        if (kind == "fit" and cfg.stall_timeout_s > 0
                and cfg.heartbeat_interval_s > 0):
            # fit only: HeartbeatCallback starts its sender in
            # on_fit_start, which the eval-family jobs never fire — a
            # monitor there would declare a healthy long validate()
            # hung at startup_grace_s
            return HealthMonitor(
                n, stall_timeout_s=cfg.stall_timeout_s,
                startup_grace_s=cfg.startup_grace_s)
        return None

    monitor: Optional[HealthMonitor] = _make_monitor(world)

    user_q = kw.pop("on_queue_item", None)
    user_watchdog = kw.pop("watchdog", None)

    def _watchdog() -> None:
        if monitor is not None:
            monitor.check()
        if user_watchdog is not None:
            user_watchdog()

    def _on_queue_item(rank: int, item: Any) -> None:
        if monitor is not None and monitor.consume(rank, item):
            return
        if user_q is not None:
            user_q(rank, item)
        elif callable(item):
            item()  # the pump trampoline the group would have applied
        else:
            log.debug("dropping non-callable queue item from rank %d", rank)

    wrapped_tf = partial(_wrapped_trainer_factory, trainer_factory, cfg)

    # driver-side telemetry: attempt/backoff spans into the run's shared
    # dir (rank -1 = the driver), plus the wall/backoff ledger the
    # goodput assembly closes its books against
    telemetry_dir = (cfg.resolved_telemetry_dir() if kind == "fit"
                     else None)
    driver_rec = None
    if telemetry_dir:
        from ray_lightning_tpu.telemetry.spans import (
            PH_ATTEMPT,
            PH_BACKOFF,
            TelemetryRecorder,
        )

        driver_rec = TelemetryRecorder(directory=telemetry_dir, rank=-1)
    wall_t0 = time.perf_counter()
    backoff_s = 0.0

    # SLO watch (telemetry/watch.py): driver-side rule evaluation over
    # the run's persisted evidence — polled after every classified
    # failure (restart-rate breaches fire mid-run, not post-mortem)
    # and at the terminal bookkeeping (goodput_fraction sees the
    # assembled report). Pure file reads: the workers' compiled
    # program is untouched (test-pinned).
    watch_engine = None
    if kind == "fit" and cfg.watch:
        from ray_lightning_tpu.telemetry.watch import (
            WatchConfig,
            WatchEngine,
        )

        # telemetry_dir threaded explicitly: a TelemetryConfig(dir=...)
        # run keeps its spans/goodput ledgers OUTSIDE
        # <checkpoint_dir>/telemetry, and the watch must read where
        # they actually are
        watch_engine = WatchEngine(cfg.checkpoint_dir,
                                   WatchConfig.coerce(cfg.watch),
                                   telemetry_dir=telemetry_dir)

    def _watch_poll() -> List[Dict[str, Any]]:
        if watch_engine is None:
            return []
        try:
            watch_engine.poll()
        except Exception:  # noqa: BLE001 — observability must never
            # cost the run its result
            log.exception("watch evaluation failed")
        return list(watch_engine.incidents)

    def _assemble(restarts, preemptions, rollbacks):
        if telemetry_dir is None:
            return None
        from ray_lightning_tpu.telemetry import goodput as _gp

        try:
            if driver_rec is not None:
                driver_rec.close()
            report = _gp.assemble_goodput(
                telemetry_dir, time.perf_counter() - wall_t0,
                backoff_s=backoff_s, restarts=restarts,
                preemptions=preemptions, rollbacks=rollbacks)
            _gp.write_goodput(telemetry_dir, report)
            return report
        except Exception:  # noqa: BLE001 — accounting must never cost
            # the run its result
            log.exception("goodput assembly failed")
            return None

    restarts = 0
    preemptions = 0
    rollbacks = 0
    quarantined: List[int] = []
    failures: List[Dict[str, Any]] = []
    reshards: List[Dict[str, Any]] = []
    while True:
        if monitor is not None:
            monitor.reset()
        attempts = 1 + restarts + preemptions + rollbacks
        try:
            attempt_ctx = (driver_rec.span(PH_ATTEMPT,
                                           meta={"attempt": attempts,
                                                 "world": world})
                           if driver_rec is not None
                           else contextlib.nullcontext())
            with attempt_ctx:
                result = run_distributed(
                    kind, module_factory, wrapped_tf, data_factory,
                    world,
                    ckpt_path=ckpt_path,
                    on_queue_item=_on_queue_item,
                    watchdog=(_watchdog if (monitor is not None
                                            or user_watchdog is not None)
                              else None),
                    **kw,
                )
            goodput = _assemble(restarts, preemptions, rollbacks)
            return SupervisedResult(result, restarts, preemptions,
                                    failures, rollbacks, quarantined,
                                    goodput=goodput,
                                    reshards=reshards,
                                    incidents=_watch_poll())
        except BaseException as exc:
            if isinstance(exc, (KeyboardInterrupt, SystemExit)):
                raise
            fc = classify_failure(exc)
            failures.append({"attempt": attempts, **fc.to_dict(),
                             "at": time.time()})
            log.warning("supervised attempt %d failed: [%s/%s] %s",
                        attempts, fc.kind, fc.cause, fc.detail)
            # mid-run watch cadence: a restart-rate / guard-streak
            # breach fires NOW, while an operator can still act on it
            _watch_poll()
            if fc.kind == FailureKind.FATAL:
                # land the driver's attempt/backoff spans for the
                # post-mortem report before failing for good
                _assemble(restarts, preemptions, rollbacks)
                _watch_poll()
                raise SupervisedFailure(fc, attempts) from exc
            allowed = policy.allows(restarts, preemptions, fc, rollbacks)
            new_world = None
            if (kind == "fit" and cfg.elastic is not None
                    and fc.kind != FailureKind.CORRUPTION):
                # elastic supervision (docs/ELASTIC.md): a refused
                # same-size relaunch becomes a SHRINK onto the largest
                # legal survivor world; an allowed relaunch whose
                # capacity oracle reports a different size moves toward
                # it (growth back when capacity returns). Only ACTUAL
                # world changes spend max_reshards — refusal records in
                # the ledger are free.
                spent = sum(1 for e in reshards
                            if e.get("reason") in ("shrink", "grow"))
                new_world, grow_refusal = _elastic_decision(
                    cfg.elastic, world, launch_world, allowed, spent)
                if grow_refusal is not None:
                    # the oracle kept a shrunk run small: record its
                    # answer (worlds + source) in the reshard ledger —
                    # the capacity truth is auditable, never implicit
                    refusal_entry = {**grow_refusal,
                                     "attempt": attempts,
                                     "at": time.time()}
                    reshards.append(refusal_entry)
                    _append_reshard_ledger(cfg.checkpoint_dir,
                                           refusal_entry)
                    log.warning(
                        "supervise: grow %d -> %d refused — capacity "
                        "oracle (%s) reports %s schedulable world(s)",
                        world, grow_refusal["resolved_max"],
                        grow_refusal["capacity_source"],
                        grow_refusal["capacity"])
            if new_world is None and not allowed:
                _assemble(restarts, preemptions, rollbacks)
                _watch_poll()
                raise RestartBudgetExceeded(
                    fc, attempts,
                    policy.max_rollbacks
                    if fc.kind == FailureKind.CORRUPTION
                    else policy.max_restarts) from exc
            if fc.kind == FailureKind.PREEMPTION:
                preemptions += 1
            elif fc.kind == FailureKind.CORRUPTION:
                rollbacks += 1
            else:
                restarts += 1
            delay = policy.next_delay(restarts + preemptions + rollbacks)
            if fc.kind == FailureKind.CORRUPTION and kind == "fit":
                ckpt_path = _rollback_target(cfg, rollbacks, quarantined,
                                             original_ckpt)
            elif kind == "fit":
                found = latest_checkpoint(cfg.checkpoint_dir)
                ckpt_path = found if found is not None else original_ckpt
            if new_world is not None:
                from ray_lightning_tpu.elastic.reshard import ReshardError

                try:
                    entry = _begin_reshard(cfg, world, new_world,
                                           ckpt_path, attempts,
                                           driver_rec)
                except ReshardError as rexc:
                    if allowed:
                        # a refused resize (legacy resume source) must
                        # not cost an otherwise-allowed same-size
                        # relaunch — skip the resize, keep supervising
                        log.error("supervise: elastic resize %d -> %d "
                                  "refused (%s); relaunching same-size",
                                  world, new_world, rexc)
                    else:
                        # the fixed-size budget is spent AND the resize
                        # cannot proceed: terminal — land the goodput
                        # postmortem like every other terminal path and
                        # fail with the classified cause, the refusal
                        # chained underneath
                        _assemble(restarts, preemptions, rollbacks)
                        _watch_poll()
                        raise RestartBudgetExceeded(
                            fc, attempts, policy.max_restarts) from rexc
                else:
                    reshards.append(entry)
                    _append_reshard_ledger(cfg.checkpoint_dir, entry)
                    world = new_world
                    monitor = _make_monitor(world)
            log.warning(
                "supervise: restart %d (retryable %d, preemptions %d, "
                "rollbacks %d) in %.1fs at world %d, resuming from %s",
                restarts + preemptions + rollbacks, restarts,
                preemptions, rollbacks, delay, world,
                ckpt_path or "scratch")
            backoff_ctx = (driver_rec.span(PH_BACKOFF)
                           if driver_rec is not None
                           else contextlib.nullcontext())
            with backoff_ctx:
                time.sleep(delay)
            backoff_s += delay


def _elastic_target_world(budget, world: int, launch_world: int,
                          allowed: bool,
                          reshards_done: int) -> Optional[int]:
    """Back-compat wrapper over `_elastic_decision`: just the target
    world (tests and external callers keep their contract)."""
    return _elastic_decision(budget, world, launch_world, allowed,
                             reshards_done)[0]


def _elastic_decision(budget, world: int, launch_world: int,
                      allowed: bool, reshards_done: int):
    """The elastic supervision decision (docs/ELASTIC.md): given the
    current world, whether the retry policy still allows a SAME-SIZE
    relaunch, and how many topology changes were already spent, pick
    the next world size — or None for "no change" (the caller then
    relaunches same-size or, when !allowed, exhausts the budget).

      * !allowed — the fixed-size story is over (k hosts are not
        coming back within budget): shrink to the largest legal world
        STRICTLY below the current one, bounded by reported capacity.
      * allowed + the capacity oracle reports a different size: move
        toward it (this is how a shrunk run grows back — the next
        relaunch after capacity returns resumes at the bigger world).

    Returns ``(target, grow_refusal)``: ``target`` is None for "no
    change"; ``grow_refusal`` is a ledger-shaped dict when the run sits
    BELOW its resolved max and the capacity oracle's answer is what
    kept it there — the supervisor records the oracle's answer (worlds
    + source, docs/AUTOSCALE.md "capacity oracle") in the reshard
    ledger so a run that stayed small has its reason on the record.

    Never proposes the current world, never exceeds max_reshards, and
    only proposes rungs `ElasticBudget.legal` accepts (divisibility via
    the plan checker's own MeshSpec/dp_degree machinery)."""
    if budget is None or reshards_done >= budget.max_reshards:
        return None, None
    answer = budget.capacity_answer(launch_world)
    raw_cap = answer.worlds if answer.worlds is not None \
        else budget.resolved_max(launch_world)
    cap = min(raw_cap, budget.resolved_max(launch_world))
    if not allowed:
        return (budget.largest_legal(min(cap, world - 1), launch_world),
                None)
    if cap != world:
        target = budget.largest_legal(cap, launch_world)
        if target is not None and target != world:
            return target, None
    if world < budget.resolved_max(launch_world) and cap <= world:
        # a shrunk run could grow but the oracle says capacity has not
        # returned: refuse, and say WHO said so
        return None, {
            "reason": "grow_refused",
            "from_world": world,
            "resolved_max": budget.resolved_max(launch_world),
            "capacity": raw_cap,
            "capacity_source": answer.source,
            "capacity_detail": answer.detail,
        }
    return None, None


def _begin_reshard(cfg: ResilienceConfig, world: int, new_world: int,
                   ckpt_path: Optional[str], attempts: int,
                   driver_rec) -> Dict[str, Any]:
    """Validate + record one elastic world change. The resume source
    must carry sharding provenance (a legacy checkpoint can only be
    restored onto the identical sharding — resharding it would be a
    silent lie about what was trained); the actual cross-topology
    restore happens worker-side in the relaunched trainer
    (core/trainer.py `_reshard_move`), accounted as the `reshard`
    goodput bucket."""
    from ray_lightning_tpu.checkpoint.io import read_meta
    from ray_lightning_tpu.elastic.reshard import (
        ReshardError,
        validate_reshard,
    )

    move = None
    if ckpt_path is not None:
        meta = read_meta(ckpt_path)
        if "mesh_spec" not in meta:
            raise ReshardError(
                f"elastic resize {world} -> {new_world} refused: resume "
                f"source {ckpt_path} carries no sharding provenance "
                "(legacy checkpoint — its writing mesh is unknowable, "
                "so the move cannot be validated). Re-save it once on "
                "the current mesh, or start the elastic run from a "
                "provenance-stamped checkpoint")
        # mesh-level validation against the WRITER's provenance, with
        # the budget's REAL mesh template as the target (largest_legal
        # only proposed worlds the template resolves at); the worker
        # validates again against the mesh it actually builds
        target_sizes = cfg.elastic.spec_for(new_world).resolve(
            new_world).sizes()
        move = validate_reshard(meta, target_sizes)["from_mesh"]
    entry: Dict[str, Any] = {
        "from_world": world,
        "to_world": new_world,
        "reason": "shrink" if new_world < world else "grow",
        "attempt": attempts,
        "at": time.time(),
        "ckpt": ckpt_path,
        "from_mesh": move,
        "batch_plan": cfg.elastic.batch_plan(world, new_world),
    }
    log.warning(
        "supervise: elastic %s %d -> %d (resuming from %s); batch "
        "plan: %s", entry["reason"], world, new_world,
        ckpt_path or "scratch",
        entry["batch_plan"].get("note", "global batch preserved"))
    if driver_rec is not None:
        from ray_lightning_tpu.telemetry.spans import PH_RESHARD

        with driver_rec.span(PH_RESHARD, meta={
                k: entry[k] for k in ("from_world", "to_world",
                                      "reason", "attempt")}):
            pass
    return entry


def _rollback_target(cfg: ResilienceConfig, rollbacks: int,
                     quarantined: List[int],
                     original_ckpt: Optional[str]) -> Optional[str]:
    """Pick the resume source after a trainguard CORRUPTION escalation:
    the newest BLESSED checkpoint at/below the marker's last-good step
    (a blessed-but-newer one could already carry the silent corruption
    the probe only just caught). Also folds the marker's quarantine
    verdict into the ledger and the on-disk ``.quarantine.json`` the
    next scheduler/operator reads, and stamps the rollback count back
    into the marker so the relaunched workers can surface it as the
    ``guard_rollbacks`` metric."""
    import json

    from ray_lightning_tpu.resilience.guard import (
        QUARANTINE_FILE,
        read_rollback_marker,
        write_rollback_marker,
    )

    marker = read_rollback_marker(cfg.checkpoint_dir) or {}
    max_step = marker.get("last_good_step")
    for rank in marker.get("quarantine") or []:
        if rank not in quarantined:
            quarantined.append(rank)
    if quarantined:
        qpath = os.path.join(cfg.checkpoint_dir, QUARANTINE_FILE)
        tmp = qpath + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"excluded": sorted(quarantined),
                       "at": time.time()}, f)
        os.replace(tmp, qpath)
        log.error("supervise: quarantining rank(s) %s (divergent "
                  "parameter fingerprint) — recorded in %s",
                  sorted(quarantined), qpath)
    if marker:
        write_rollback_marker(cfg.checkpoint_dir,
                              {**marker, "rollbacks_performed": rollbacks})
    if max_step is not None:
        # Abandon the poisoned window FOR GOOD: every checkpoint newer
        # than the last known-good step moves into quarantined.ckpts/
        # (kept for forensics, out of every candidate set). Without
        # this, a later RETRYABLE/PREEMPTION restart — or a driver
        # relaunch with resume="auto" — would pick the newest
        # blessed-but-silently-poisoned checkpoint right back up. Safe
        # to move here: the worker group is already torn down.
        _quarantine_newer_checkpoints(cfg.checkpoint_dir, int(max_step))
    found = latest_checkpoint(
        cfg.checkpoint_dir, good_only=True,
        max_step=int(max_step) if max_step is not None else None)
    if found is None:
        log.warning("supervise: no blessed checkpoint at/below step %s — "
                    "rolling back to %s", max_step,
                    original_ckpt or "scratch")
    return found if found is not None else original_ckpt


def _quarantine_newer_checkpoints(directory: str, max_step: int) -> None:
    """Move checkpoint subdirs with a recorded global_step above the
    rollback horizon into ``<directory>/quarantined.ckpts/`` — one
    level down, so ``latest_checkpoint`` (which scans immediate
    subdirs) never sees them again."""
    import json

    dest_root = os.path.join(directory, "quarantined.ckpts")
    try:
        names = os.listdir(directory)
    except OSError:
        return
    for name in names:
        cand = os.path.join(directory, name)
        meta_path = os.path.join(cand, "meta.json")
        if not os.path.isdir(os.path.join(cand, "state")):
            continue
        try:
            with open(meta_path) as f:
                step = int(json.load(f).get("global_step", -1))
        except (OSError, ValueError, TypeError):
            continue  # unreadable: verify_checkpoint already rejects it
        if step <= max_step:
            continue
        os.makedirs(dest_root, exist_ok=True)
        try:
            os.rename(cand, os.path.join(
                dest_root, f"{name}.rb{int(time.time())}"))
            log.warning("supervise: quarantined poisoned checkpoint %s "
                        "(step %d > last good %d)", cand, step, max_step)
        except OSError:
            log.exception("could not quarantine checkpoint %s", cand)


def fit_supervised(
    module_factory: Callable[[], Any],
    trainer_factory: Callable[[], Any],
    data_factory: Callable[[], Any],
    num_processes: int,
    *,
    resilience: ResilienceConfig,
    **kw: Any,
) -> SupervisedResult:
    """Supervised ``fit_distributed``: every transient pod failure becomes
    a resumed run instead of a lost one. See supervise()."""
    return supervise("fit", module_factory, trainer_factory, data_factory,
                     num_processes, resilience=resilience, **kw)
