"""Hot-loop overlap: keep the device dispatch queue non-empty.

The fit loop pays three host-blocking costs the hardware never asked
for: the per-step ``device_put`` of the next batch, the cold ``jax.jit``
compile on every (re)start, and the all-ranks stall of a blocking
checkpoint. This package removes them (docs/PERFORMANCE.md):

  * `DevicePrefetcher` — a bounded N-buffer stage that overlaps host
    batch assembly + sharded device placement with the previous step's
    compute, so the jitted step's input is resident when it dispatches;
  * `compile_cache` — AOT ``lower().compile()`` warm start for the
    train/eval steps plus the persistent XLA compilation cache keyed
    per sharding plan, so restart N recompiles nothing and compile time
    is a first-class metric (`CompileStats`);
  * `overlap` — the CPU-measurable proof harness: a deliberately slow
    synthetic loader must show prefetch hiding the host time (bench.py
    leg, ``python -m ray_lightning_tpu perf --smoke`` format.sh gate).

Async checkpointing — the third overlap — lives with the checkpoint
format itself (checkpoint/io.py `save_checkpoint(block=False)`): a
no-donation device snapshot decouples the write from the donated train
state, and a background finalizer publishes meta.json + digest the
moment the state write commits.
"""
from ray_lightning_tpu.pipeline.compile_cache import (
    CompileStats,
    WarmStep,
    enable_persistent_cache,
    plan_cache_dir,
)
from ray_lightning_tpu.pipeline.prefetch import (
    DevicePrefetcher,
    PrefetchStats,
)

__all__ = [
    "DevicePrefetcher",
    "PrefetchStats",
    "CompileStats",
    "WarmStep",
    "enable_persistent_cache",
    "plan_cache_dir",
]
