"""CPU-measurable evidence that the overlap machinery works.

Why this is honest on a box with no TPU: jax's async dispatch already
hides a slow loader *as long as nothing ever synchronizes* — but every
real loop synchronizes: metric logging, validation, checkpoint cadence,
progress bars. The moment a step output is fetched, the host serializes
(loader + fetch) per step and the device starves for exactly the loader
time. This harness builds that case explicitly — per-step metric fetch
(``log_every_n_steps=1``) and a `ThrottledLoader` whose per-batch delay
is CALIBRATED to the measured step time (the worst case for overlap:
speedup ceiling 2x, reached only if the pipeline actually overlaps) —
and measures steps/s with the prefetcher off vs on.

The same harness reports the warm-start metrics: the first trainer's
``compile_time_s`` is the cold AOT compile; the second trainer compiles
the identical program and must land a persistent-cache hit (~zero XLA
time). Everything here runs on whatever backend jax has — the bench leg
works with the TPU tunnel down.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np


def _build_module(dim: int, hidden: int):
    import flax.linen as nn
    import jax
    import optax

    from ray_lightning_tpu.core.module import TpuModule

    class _MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(hidden)(x))
            x = nn.relu(nn.Dense(hidden)(x))
            return nn.Dense(2)(x)

    class _OverlapModel(TpuModule):
        def configure_model(self):
            return _MLP()

        def configure_optimizers(self):
            return optax.adam(1e-3)

        def training_step(self, params, batch, rng):
            logits = self.apply(params, batch["x"])
            labels = jax.nn.one_hot(batch["y"], 2)
            return optax.softmax_cross_entropy(logits, labels).mean()

    return _OverlapModel()


class _StepSpan:
    """Callback measuring wall time across the timed steps only —
    compile, init, and the first batch's pipeline fill are excluded so
    the ratio reflects steady-state throughput."""

    def __init__(self):
        self.first: Optional[float] = None
        self.last: Optional[float] = None
        self.steps = 0

    def __call__(self, trainer=None, module=None, metrics=None,
                 batch_idx=None) -> None:
        now = time.perf_counter()
        if self.first is None:
            self.first = now
        self.last = now
        self.steps += 1

    @property
    def steps_per_sec(self) -> float:
        if self.first is None or self.steps < 2:
            return 0.0
        return (self.steps - 1) / max(self.last - self.first, 1e-9)


def _one_fit(data: Dict[str, np.ndarray], *, batch: int, steps: int,
             delay_s: float, prefetch: int, dim: int, hidden: int,
             seed: int = 0) -> tuple:
    from ray_lightning_tpu.core.callbacks import Callback
    from ray_lightning_tpu.core.data import DataLoader, ThrottledLoader
    from ray_lightning_tpu.core.trainer import Trainer

    class _SpanCB(Callback):
        def __init__(self, span):
            self.span = span

        def on_train_batch_end(self, trainer, module, metrics, batch_idx):
            self.span(trainer, module, metrics, batch_idx)

    span = _StepSpan()
    loader: Any = DataLoader(data, batch_size=batch)
    if delay_s > 0:
        loader = ThrottledLoader(loader, delay_s)
    trainer = Trainer(
        max_epochs=1_000_000,  # max_steps terminates
        max_steps=steps,
        log_every_n_steps=1,   # the per-step sync every real loop has
        enable_checkpointing=False,
        enable_progress_bar=False,
        seed=seed,
        prefetch_to_device=prefetch,
        callbacks=[_SpanCB(span)],
    )
    trainer.fit(_build_module(dim, hidden), loader)
    return span, trainer


def measure_prefetch_overlap(
    steps: int = 40,
    depth: int = 2,
    batch: int = 128,
    dim: int = 256,
    hidden: int = 512,
    delay_s: Optional[float] = None,
    cache_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """Run the calibrate → sync → prefetch comparison; returns one flat
    dict ready to be emitted as a structured JSON line.

    ``delay_s=None`` calibrates the synthetic loader delay to the
    measured steady-state step time (clamped to [2 ms, 100 ms]), the
    regime where overlap matters and its absence is visible.
    """
    from ray_lightning_tpu.pipeline.compile_cache import (
        active_cache_dir,
        enable_persistent_cache,
    )

    import jax

    owns_tmp = False
    prev_cfg_dir = jax.config.jax_compilation_cache_dir
    if cache_dir is None and active_cache_dir() is None:
        # the warm-start half of the evidence needs a persistent cache;
        # default to a throwaway one rather than silently measuring
        # cold compiles twice — restored + cleaned below so a bench leg
        # never leaves the process-global cache repointed at a doomed
        # temp dir (or the temp dirs accreting across CI runs)
        import tempfile

        cache_dir = tempfile.mkdtemp(prefix="rlt_compile_cache_")
        owns_tmp = True
    if cache_dir is not None:
        enable_persistent_cache(cache_dir)

    n = batch * (steps + depth + 4)
    rng = np.random.default_rng(0)
    data = {
        "x": rng.standard_normal((n, dim), dtype=np.float32),
        "y": rng.integers(0, 2, n).astype(np.int32),
    }

    try:
        # calibration: no throttle, no prefetch — measures the step time
        # and pays the cold compile (the warm-start baseline)
        cal_span, cal_trainer = _one_fit(
            data, batch=batch, steps=steps, delay_s=0.0, prefetch=0,
            dim=dim, hidden=hidden)
        step_s = ((1.0 / cal_span.steps_per_sec)
                  if cal_span.steps_per_sec else 0.01)
        if delay_s is None:
            # slightly BELOW the step time: overlap still hides ~all of
            # the loader (speedup ceiling ~1.85x) and the producer
            # reliably outpaces the consumer, so occupancy — the
            # smoke-gate signal — is not a per-step coin flip
            delay_s = min(max(0.85 * step_s, 0.002), 0.1)

        sync_span, sync_trainer = _one_fit(
            data, batch=batch, steps=steps, delay_s=delay_s, prefetch=0,
            dim=dim, hidden=hidden)
        pre_span, pre_trainer = _one_fit(
            data, batch=batch, steps=steps, delay_s=delay_s,
            prefetch=depth, dim=dim, hidden=hidden)
    finally:
        if owns_tmp:
            import shutil

            jax.config.update("jax_compilation_cache_dir", prev_cfg_dir)
            try:
                from jax._src import compilation_cache as _cc

                _cc.reset_cache()
            except Exception:  # noqa: BLE001 — best-effort restore
                pass
            shutil.rmtree(cache_dir, ignore_errors=True)

    sync_sps = sync_span.steps_per_sec
    pre_sps = pre_span.steps_per_sec
    m = pre_trainer.callback_metrics
    return {
        "metric": "prefetch_overlap_speedup",
        "value": round(pre_sps / sync_sps, 3) if sync_sps else 0.0,
        "unit": "x",
        "steps": steps,
        "prefetch_depth": depth,
        "loader_delay_ms": round(delay_s * 1e3, 2),
        "calibrated_step_ms": round(step_s * 1e3, 2),
        "steps_per_sec_sync": round(sync_sps, 2),
        "steps_per_sec_prefetch": round(pre_sps, 2),
        "pipeline_occupancy": round(
            float(m.get("prefetch_occupancy", 0.0)), 3),
        "prefetch_wait_s": round(float(m.get("prefetch_wait_s", 0.0)), 4),
        # warm start: calibration paid the cold compile; the later
        # trainers compiled the identical program → persistent-cache hit
        "compile_cold_s": round(
            float(cal_trainer.callback_metrics.get("compile_time_s", 0.0)),
            4),
        "compile_warm_s": round(
            float(m.get("compile_time_s", 0.0)), 4),
        # the dir the legs were measured against (the throwaway default
        # is restored+cleaned before returning; report it as ephemeral)
        "compile_cache_dir": ("<ephemeral>" if owns_tmp
                              else active_cache_dir()),
    }
