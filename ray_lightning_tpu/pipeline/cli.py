"""``python -m ray_lightning_tpu perf`` — the hot-loop overlap proof.

Runs the CPU-measurable prefetch/warm-start comparison
(pipeline/overlap.py) and prints ONE structured JSON line. ``--smoke``
is the format.sh gate: a slow-loader run must show pipeline occupancy
> 0 (the prefetcher demonstrably kept batches resident ahead of the
step) — exit 1 otherwise. docs/PERFORMANCE.md explains the numbers.
"""
from __future__ import annotations

import argparse
import json
import sys


def add_perf_parser(sub) -> None:
    p = sub.add_parser(
        "perf",
        help="measure the device-prefetch overlap win + warm-start "
             "compile metrics with a synthetic slow loader (CPU-safe)")
    p.add_argument("--steps", type=int, default=40,
                   help="timed optimizer steps per leg")
    p.add_argument("--depth", type=int, default=2,
                   help="prefetch buffer depth for the overlapped leg")
    p.add_argument("--delay-ms", type=float, default=None,
                   help="synthetic per-batch loader delay; default "
                        "calibrates to the measured step time")
    p.add_argument("--cache-dir", default=None,
                   help="persistent compile cache dir for the "
                        "warm-start legs (default: jax's configured one)")
    p.add_argument("--smoke", action="store_true",
                   help="gate mode: exit 1 unless pipeline occupancy > 0 "
                        "AND the collective-overlap leg proves the "
                        "prefetch schedule (fingerprint in the jaxpr, "
                        "throttled interleave faster than serial)")
    p.add_argument("--no-overlap-leg", action="store_true",
                   help="skip the collective-overlap leg (the static "
                        "schedule trace + throttled fake-collective "
                        "interleave demo)")
    p.add_argument("--overlap-layers", type=int, default=8,
                   help="layers in the throttled interleave demo")
    p.add_argument("--overlap-comm-ms", type=float, default=20.0,
                   help="fake collective latency for the interleave demo")
    # parses into the SAME namespace as the parent --json (see plan_p)
    p.add_argument("--json", action="store_true", dest="as_json",
                   default=argparse.SUPPRESS)


def run_perf(args) -> int:
    from ray_lightning_tpu.pipeline.overlap import measure_prefetch_overlap

    result = measure_prefetch_overlap(
        steps=args.steps,
        depth=args.depth,
        delay_s=(args.delay_ms / 1e3 if args.delay_ms is not None else None),
        cache_dir=args.cache_dir,
    )
    if not args.no_overlap_leg:
        from ray_lightning_tpu.pipeline.collective_overlap import (
            measure_collective_overlap,
        )

        try:
            result.update(measure_collective_overlap(
                n_layers=args.overlap_layers,
                t_comm_s=args.overlap_comm_ms / 1e3))
        except Exception as exc:  # noqa: BLE001 — an analysis bug must
            # not cost the CLI the prefetch/occupancy evidence it
            # already measured: emit the structured line with the
            # failure named, and let --smoke fail on the verdict below
            result["overlap_error"] = (
                f"{type(exc).__name__}: {str(exc)[:200]}")
            result["overlap_schedule_ok"] = False
    print(json.dumps(result), flush=True)
    if args.smoke and result["pipeline_occupancy"] <= 0.0:
        print("perf smoke FAILED: prefetch pipeline occupancy is 0 — the "
              "prefetcher never had a batch resident ahead of the step",
              file=sys.stderr)
        return 1
    if args.smoke and not args.no_overlap_leg:
        if not result.get("overlap_schedule_ok"):
            print("perf smoke FAILED: the collective-overlap schedule "
                  "did not verify (prefetch fingerprint missing, or the "
                  "off-trace failed to flag exposed gathers — see "
                  "overlap_trace)", file=sys.stderr)
            return 1
        # the floor scales with the demo's own roofline so tuning
        # --overlap-comm-ms/--overlap-layers cannot make a perfectly
        # interleaved schedule fail: demand half the ideal gain, capped
        # at the 1.15 the 20ms/20ms default comfortably clears
        floor = min(1.15, 1 + 0.5 * (result.get("ideal_speedup", 1.3) - 1))
        if result.get("overlap_speedup", 0.0) < floor:
            print(f"perf smoke FAILED: throttled interleave demo shows "
                  f"no latency hiding (speedup "
                  f"{result.get('overlap_speedup')} < floor "
                  f"{floor:.3f}; serial {result.get('serial_s')}s vs "
                  f"overlapped {result.get('overlapped_s')}s)",
                  file=sys.stderr)
            return 1
    return 0
