"""``python -m ray_lightning_tpu perf`` — the hot-loop overlap proof.

Runs the CPU-measurable prefetch/warm-start comparison
(pipeline/overlap.py) and prints ONE structured JSON line. ``--smoke``
is the format.sh gate: a slow-loader run must show pipeline occupancy
> 0 (the prefetcher demonstrably kept batches resident ahead of the
step) — exit 1 otherwise. docs/PERFORMANCE.md explains the numbers.
"""
from __future__ import annotations

import argparse
import json
import sys


def add_perf_parser(sub) -> None:
    p = sub.add_parser(
        "perf",
        help="measure the device-prefetch overlap win + warm-start "
             "compile metrics with a synthetic slow loader (CPU-safe)")
    p.add_argument("--steps", type=int, default=40,
                   help="timed optimizer steps per leg")
    p.add_argument("--depth", type=int, default=2,
                   help="prefetch buffer depth for the overlapped leg")
    p.add_argument("--delay-ms", type=float, default=None,
                   help="synthetic per-batch loader delay; default "
                        "calibrates to the measured step time")
    p.add_argument("--cache-dir", default=None,
                   help="persistent compile cache dir for the "
                        "warm-start legs (default: jax's configured one)")
    p.add_argument("--smoke", action="store_true",
                   help="gate mode: exit 1 unless pipeline occupancy > 0")
    # parses into the SAME namespace as the parent --json (see plan_p)
    p.add_argument("--json", action="store_true", dest="as_json",
                   default=argparse.SUPPRESS)


def run_perf(args) -> int:
    from ray_lightning_tpu.pipeline.overlap import measure_prefetch_overlap

    result = measure_prefetch_overlap(
        steps=args.steps,
        depth=args.depth,
        delay_s=(args.delay_ms / 1e3 if args.delay_ms is not None else None),
        cache_dir=args.cache_dir,
    )
    print(json.dumps(result), flush=True)
    if args.smoke and result["pipeline_occupancy"] <= 0.0:
        print("perf smoke FAILED: prefetch pipeline occupancy is 0 — the "
              "prefetcher never had a batch resident ahead of the step",
              file=sys.stderr)
        return 1
    return 0
