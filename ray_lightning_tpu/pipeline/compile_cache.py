"""AOT warm start + persistent compilation cache for the jitted steps.

Two costs hide in "the first step is slow":

  * the in-process trace+compile of the train/eval step — paid lazily on
    step 1 under plain ``jit``, which makes compile time invisible
    (it reads as a slow first batch) and unreportable;
  * the cross-process recompile on every restart — the resilience
    supervisor relaunches workers, and without a persistent cache each
    restart pays the full XLA compile again, multiplied by the restart
    budget.

`WarmStep` fixes the first: it wraps a jitted function and eagerly
``lower().compile()``s it for the known input shapes (static shapes are
the framework contract — loaders drop ragged tails), recording trace/
lower/compile wall time as `CompileStats` so compile time is a
first-class metric (``trainer.callback_metrics["compile_time_s"]``).
Calls with matching shapes dispatch the AOT executable directly; a
shape drift (a user loader yielding a ragged batch) falls back to the
jitted path permanently rather than erroring — AOT is an optimization,
never a new constraint.

`enable_persistent_cache` fixes the second: it points jax's persistent
compilation cache (``jax_compilation_cache_dir``) at a per-plan
directory (`plan_cache_dir`), with the entry thresholds dropped to zero
so even fast-compiling steps are cached. Restart N then recompiles
nothing: the lowered program hashes to the same key and the executable
is deserialized from disk. The cache key is XLA's own (computed from
the lowered HLO + compile options), so keying the *directory* per plan
is only hygiene — different meshes/plans never collide anyway, but a
shared dir across experiments grows without bound.
"""
from __future__ import annotations

import dataclasses
import hashlib
import os
import time
from typing import Any, Callable, Optional, Tuple

import jax

from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)


@dataclasses.dataclass
class CompileStats:
    """Wall-clock breakdown of one AOT warm start."""

    lower_s: float = 0.0     # trace + lower to StableHLO
    compile_s: float = 0.0   # XLA compile (near-zero on a persistent-cache hit)
    total_s: float = 0.0
    aot: bool = False        # an AOT executable is installed
    cache_dir: Optional[str] = None  # persistent cache in effect, if any

    def to_metrics(self, prefix: str = "") -> dict:
        return {
            f"{prefix}compile_time_s": self.total_s,
            f"{prefix}compile_lower_s": self.lower_s,
            f"{prefix}compile_xla_s": self.compile_s,
        }


def enable_persistent_cache(cache_dir: str) -> str:
    """Point jax's persistent compilation cache at ``cache_dir`` (created
    if needed) and drop the size/time thresholds so every step program is
    cached. Idempotent; returns the directory. Process-global — the last
    caller wins, which is why the supervisor sets it once per worker from
    one resolved config."""
    cache_dir = os.path.abspath(cache_dir)
    os.makedirs(cache_dir, exist_ok=True)
    previous = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_enable_compilation_cache", True)
    if previous != cache_dir:
        # jax binds the on-disk cache object to the directory on first
        # use; without a reset a dir change after any compile in this
        # process is silently ignored
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # noqa: BLE001 — private API; a jax that
            # re-reads the config per compile doesn't need the nudge
            log.debug("could not reset jax compilation cache",
                      exc_info=True)
    # cache everything: the trainer's step is THE program that matters
    # here, and on a restart even a 0.5 s compile is pure waste
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    return cache_dir


def active_cache_dir() -> Optional[str]:
    """The persistent cache directory currently in effect (config beats
    env, matching jax's own resolution), or None."""
    configured = jax.config.jax_compilation_cache_dir
    return configured or os.environ.get("JAX_COMPILATION_CACHE_DIR") or None


def plan_cache_key(*parts: Any) -> str:
    """Stable short hash over plan-identifying parts (mesh axes, strategy
    and module class names, precision...). Same key ⇒ same cache dir ⇒
    restarts and repeat runs of the same plan share compiled artifacts."""
    blob = "|".join(str(p) for p in parts)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def plan_cache_dir(base_dir: str, *parts: Any) -> str:
    """``<base_dir>/<plan_cache_key(parts)>`` — one cache dir per plan."""
    return os.path.join(os.path.abspath(base_dir), plan_cache_key(*parts))


def _abstract(tree: Any) -> Any:
    """ShapeDtypeStructs (sharding-carrying when available) for lower()."""
    def one(x):
        return jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=getattr(x, "sharding", None))

    return jax.tree.map(one, tree)


def _shape_sig(tree: Any) -> Tuple:
    """Hashable (shape, dtype) signature used to gate the AOT fast path."""
    return tuple((tuple(x.shape), str(x.dtype))
                 for x in jax.tree.leaves(tree))


class WarmStep:
    """A jitted step with an eagerly-compiled AOT fast path.

    ``warm(*example_args)`` lowers and compiles for those exact shapes
    (donation and shardings come from the wrapped ``jax.jit``); calls
    whose leaf shapes/dtypes match then run the AOT executable, others
    fall back to the jitted function (which re-traces as jit always did).
    The fallback is permanent after the first mismatch — a loader that
    yields ragged batches gets classic jit semantics, not errors.
    """

    def __init__(self, jitted: Callable, label: str = "step",
                 auto: bool = False,
                 check_args: Optional[Tuple[int, ...]] = None,
                 recorder: Any = None):
        from ray_lightning_tpu.telemetry.spans import NULL_RECORDER

        self._jitted = jitted
        self._label = label
        #: telemetry recorder (telemetry/spans.py): warm() runs under a
        #: "compile" span, so heartbeats report the phase live (a
        #: 20-minute big-model compile names itself instead of reading
        #: as a frozen step counter) and the goodput compile bucket is
        #: measured, not inferred
        self._recorder = recorder or NULL_RECORDER
        self._compiled = None
        self._sig: Optional[Tuple] = None
        self._attempted = False
        #: which positional args' shapes are re-checked per call. The
        #: trainer passes (1,) — only the BATCH can drift (the state is
        #: trainer-managed and the rng key is fixed), so the per-step
        #: check stays O(batch leaves) instead of walking a possibly
        #: hundreds-of-leaves TrainState on the hot path this package
        #: exists to de-host. None = check everything (generic use).
        self._check_args = check_args
        #: auto=True AOT-compiles on the first call's shapes (the eval
        #: step, whose batch shape is unknown until validation runs);
        #: auto=False waits for an explicit warm() (the train step, warmed
        #: eagerly at fit start) and is a plain jit passthrough otherwise.
        self._auto = auto
        self.stats = CompileStats()

    def warm(self, *example_args: Any) -> CompileStats:
        """AOT-compile for ``example_args``' shapes. Failures degrade to
        the jitted path with a logged warning — warm start must never be
        able to fail a fit that plain jit would have survived."""
        from ray_lightning_tpu.telemetry.spans import PH_COMPILE

        with self._recorder.span(PH_COMPILE,
                                 meta={"label": self._label}):
            return self._warm_inner(*example_args)

    def _warm_inner(self, *example_args: Any) -> CompileStats:
        self._attempted = True
        t0 = time.perf_counter()
        try:
            abstract = tuple(_abstract(a) for a in example_args)
            lowered = self._jitted.lower(*abstract)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            t2 = time.perf_counter()
        except Exception:  # noqa: BLE001 — optimization, not a contract
            log.exception("AOT warm start failed for %s; falling back to "
                          "lazy jit compilation", self._label)
            self.stats = CompileStats(total_s=time.perf_counter() - t0)
            return self.stats
        self._compiled = compiled
        idx = (range(len(abstract)) if self._check_args is None
               else self._check_args)
        self._sig = (len(abstract),
                     tuple(_shape_sig(abstract[i]) for i in idx))
        self.stats = CompileStats(
            lower_s=t1 - t0, compile_s=t2 - t1, total_s=t2 - t0,
            aot=True, cache_dir=active_cache_dir())
        log.info("%s warm start: lower %.3fs + compile %.3fs (persistent "
                 "cache: %s)", self._label, self.stats.lower_s,
                 self.stats.compile_s, self.stats.cache_dir or "off")
        return self.stats

    def _sig_of(self, args: Tuple) -> Tuple:
        idx = (range(len(args)) if self._check_args is None
               else self._check_args)
        return (len(args), tuple(_shape_sig(args[i]) for i in idx))

    def __call__(self, *args: Any) -> Any:
        if self._auto and not self._attempted and self._compiled is None:
            self.warm(*args)
        if self._compiled is not None:
            if self._sig_of(args) == self._sig:
                return self._compiled(*args)
            # shape drift: AOT assumptions broken — classic jit from here
            log.warning("%s input shapes drifted from the warm-start "
                        "shapes; disabling the AOT fast path", self._label)
            self._compiled = None
        return self._jitted(*args)

    @property
    def aot_active(self) -> bool:
        return self._compiled is not None
