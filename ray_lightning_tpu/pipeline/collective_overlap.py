"""CPU-runnable evidence for the collective-overlap schedule (ISSUE 6).

No accelerator can MEASURE collective/compute overlap on this box (XLA's
CPU backend runs collectives synchronously), so the perf CLI's overlap
leg proves the schedule two ways, both honest about what they are:

  1. **Static proof on the real model** (`trace_overlap_schedule`): the
     tiny scanned Llama step is traced with the strategy's
     ``overlap="on"`` knob and audited by tracecheck. The assertion is
     structural — the jaxpr carries the double-buffer fingerprint
     (`ops.dispatch.OVERLAP_PREFETCH_NAME`) and the per-trip prefetch
     gathers are classified against the compute window — i.e. the
     program the TPU would run IS the prefetch schedule.

  2. **Throttled interleave demo** (`simulate_overlap_schedule`): the
     same double-buffer discipline executed on the host with a fake
     collective (a timed sleep on a background thread, standing in for
     the DMA engine that runs a real TPU all-gather) against real
     matmul compute. The serial schedule pays gather+compute per layer;
     the double-buffered schedule pays max(gather, compute) per layer
     after the prologue — the measured speedup converging to
     ``(t_g + t_c) / max(t_g, t_c)`` is the latency-hiding claim of
     docs/PERFORMANCE.md "collective overlap", demonstrated end to end.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict

__all__ = ["trace_overlap_schedule", "simulate_overlap_schedule",
           "measure_collective_overlap"]


def trace_overlap_schedule(n_devices: int = 8) -> Dict[str, Any]:
    """tracecheck the tiny scanned Llama under ``overlap="on"`` vs
    ``"off"`` on an abstract ``v5e-<n>`` FSDP slice (zero devices
    touched). Returns the structural verdict: the on-trace must carry
    the prefetch fingerprint, the off-trace must not (and must flag the
    exposed gathers as RLT305)."""
    import numpy as np

    from ray_lightning_tpu.analysis.costmodel import topology_for_kind
    from ray_lightning_tpu.analysis.tracecheck import audit_step
    from ray_lightning_tpu.models.llama import LlamaConfig, LlamaModule
    from ray_lightning_tpu.parallel.strategy import ShardedMesh

    # big enough that the compute window is non-trivial against the ICI
    # model, small enough to trace in seconds
    cfg = LlamaConfig.tiny(dim=256, n_layers=4, n_heads=8, n_kv_heads=4,
                           hidden_dim=1024, max_seq_len=512)
    batch = {"tokens": np.zeros((n_devices, 513), np.int32)}
    topo = topology_for_kind("TPU v5e", n_devices)

    def _audit(overlap: str):
        return audit_step(
            LlamaModule(cfg), ShardedMesh(fsdp=n_devices, overlap=overlap),
            batch, topology=topo, label=f"perf overlap={overlap}")

    on, off = _audit("on"), _audit("off")
    on_ov, off_ov = on.overlap or {}, off.overlap or {}
    return {
        "scheduled": bool(on_ov.get("scheduled")),
        "off_scheduled": bool(off_ov.get("scheduled")),
        "hidden_fraction_on": round(on.overlap_hidden_fraction, 4),
        "hidden_fraction_off": round(off.overlap_hidden_fraction, 4),
        "exposed_findings_off": sum(
            1 for f in off.findings if f.rule == "RLT305"),
        "per_scope_on": on_ov.get("per_scope", []),
    }


def simulate_overlap_schedule(
    n_layers: int = 8,
    t_comm_s: float = 0.02,
    compute_ms_target: float = 20.0,
) -> Dict[str, Any]:
    """Execute the double-buffer discipline on the host: a throttled
    fake collective (sleep on a worker thread — the stand-in for a DMA
    engine) against real numpy-on-jax matmul compute.

    serial:      for i: gather(i); compute(i)
    overlapped:  gather(0); for i: start gather(i+1); compute(i); join

    Returns measured wall times and the speedup; ``ideal_speedup`` is
    the roofline ``(t_g + t_c) / max(t_g, t_c)`` the schedule converges
    to as n_layers grows (the prologue gather amortizes away).
    """
    import jax
    import jax.numpy as jnp

    # calibrate a matmul whose wall time approximates the target
    n = 256
    x = jnp.ones((n, n), jnp.float32)
    f = jax.jit(lambda a: a @ a)
    f(x).block_until_ready()
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        f(x).block_until_ready()
    per = (time.perf_counter() - t0) / reps
    loops = max(1, int((compute_ms_target / 1e3) / max(per, 1e-6)))

    def compute():
        for _ in range(loops):
            f(x).block_until_ready()

    def fake_gather():
        time.sleep(t_comm_s)

    # measured per-layer compute (for the roofline denominator)
    t0 = time.perf_counter()
    compute()
    t_c = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(n_layers):
        fake_gather()
        compute()
    serial_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    fake_gather()  # prologue: layer 0's exposed gather
    for i in range(n_layers):
        th = None
        if i + 1 < n_layers:
            th = threading.Thread(target=fake_gather)
            th.start()  # issue layer i+1's gather BEFORE layer i's compute
        compute()
        if th is not None:
            th.join()  # the double buffer is ready when the trip ends
    overlapped_s = time.perf_counter() - t0

    ideal = (t_comm_s + t_c) / max(t_comm_s, t_c)
    return {
        "n_layers": n_layers,
        "t_comm_ms": round(t_comm_s * 1e3, 2),
        "t_compute_ms": round(t_c * 1e3, 2),
        "serial_s": round(serial_s, 4),
        "overlapped_s": round(overlapped_s, 4),
        "overlap_speedup": round(serial_s / max(overlapped_s, 1e-9), 3),
        "ideal_speedup": round(ideal, 3),
    }


def measure_collective_overlap(
    n_layers: int = 8,
    t_comm_s: float = 0.02,
    trace_devices: int = 8,
) -> Dict[str, Any]:
    """The perf CLI's overlap leg: static schedule proof + throttled
    interleave demo, one dict (keys prefixed for the perf JSON line)."""
    out: Dict[str, Any] = {}
    trace = trace_overlap_schedule(n_devices=trace_devices)
    out["overlap_trace"] = trace
    out.update(simulate_overlap_schedule(
        n_layers=n_layers, t_comm_s=t_comm_s))
    # strict >: the off-trace hides nothing (0.0), so this doubles as a
    # hidden_fraction_on > 0 check — a classification pass that silently
    # stops counting compute (and so hides nothing) must fail the leg,
    # not vacuously tie the off schedule
    out["overlap_schedule_ok"] = bool(
        trace["scheduled"] and not trace["off_scheduled"]
        and trace["exposed_findings_off"] > 0
        and trace["hidden_fraction_on"] > trace["hidden_fraction_off"])
    return out
