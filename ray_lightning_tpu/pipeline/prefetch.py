"""Device prefetch pipeline: overlap host batch work with device compute.

The trainer's hot loop used to be strictly serial per step: assemble the
batch on the host (index/copy/cast), ``device_put`` it (sharded,
multi-process aware), THEN dispatch the jitted step. jax dispatch is
async, so the device finishes the previous step while the host sits in
numpy — but the *next* step cannot dispatch until its input exists on
device, and at production batch sizes the host work is milliseconds the
dispatch queue spends empty ("Exploring the limits of Concurrency in ML
Training on Google TPUs": the win is keeping that queue non-empty).

`DevicePrefetcher` is the classic bounded double/N-buffer stage: a
single background thread pulls host batches from the iterator, runs the
caller's ``place_fn`` (cast + shard — `Strategy.shard_batch` or the
trainer's accumulation split; `jax.device_put` and
`make_array_from_process_local_data` are both thread-safe and issue only
local work), and parks up to ``depth`` device-resident batches in a
bounded queue. The consumer's ``next()`` then usually returns a batch
whose transfer was issued one step ago.

Contracts the trainer relies on:

  * ORDER: batches come out exactly in iterator order (single producer,
    FIFO queue) — bitwise-identical training vs the synchronous path.
  * BACKPRESSURE: at most ``depth`` placed batches + 1 in the producer's
    hands exist at any time; slow consumers never accumulate device
    memory. ``depth`` buffers of HBM is the deliberate, bounded cost.
  * SHUTDOWN: ``close()`` (or exiting the context / exhausting the
    iterator) unblocks and joins the producer thread — a mid-epoch
    ``break`` (max_steps, early stop, preemption drain) must not leak a
    thread holding the loader. Idempotent.
  * ERRORS: a producer-side exception (bad batch, loader bug) is
    re-raised at the consumer's ``next()``, not swallowed in a thread.
  * METRICS: `stats` counts how often the consumer found a batch already
    waiting (`occupancy`) and how long it blocked (`wait_s`) — the
    pipeline-health numbers surfaced through ``callback_metrics``.
"""
from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Iterator, Optional

from ray_lightning_tpu.telemetry.spans import (
    NULL_RECORDER,
    PH_DATA_WAIT,
    PH_H2D,
    THREAD_PRODUCER,
)


@dataclass
class PrefetchStats:
    """Occupancy accounting for one prefetcher's lifetime."""

    batches: int = 0      # batches handed to the consumer
    hits: int = 0         # ...that were already buffered (no wait)
    wait_s: float = 0.0   # total consumer time blocked on the queue
    put_wait_s: float = 0.0  # total producer time blocked (backpressure)
    _depth: int = field(default=0, repr=False)

    @property
    def occupancy(self) -> float:
        """Fraction of batches served without blocking — 1.0 means the
        device never waited for the host; 0.0 means no overlap at all
        (the synchronous behavior this pipeline exists to beat)."""
        return self.hits / self.batches if self.batches else 0.0

    def to_metrics(self) -> dict:
        return {
            "prefetch_batches": float(self.batches),
            "prefetch_occupancy": self.occupancy,
            "prefetch_wait_s": self.wait_s,
            "prefetch_depth": float(self._depth),
        }


class _Stop:
    """Queue sentinel: normal end of the source iterator."""


class _Raise:
    """Queue sentinel carrying a producer-side exception."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class DevicePrefetcher(Iterable[Any]):
    """Iterate ``source`` with ``place_fn`` applied N batches ahead.

    ``place_fn`` maps one host batch to its device-resident form; it runs
    on the producer thread. ``depth`` >= 1 is the buffer bound (2 — the
    classic double buffer — hides one full host latency per step and is
    the default the trainer uses).
    """

    def __init__(self, source: Iterable[Any],
                 place_fn: Callable[[Any], Any],
                 depth: int = 2, name: str = "rlt-prefetch",
                 recorder: Any = None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.depth = depth
        #: telemetry span recorder (telemetry/spans.py): H2D placement
        #: spans from the producer thread (overlapped with compute —
        #: thread-tagged so goodput never double-charges them) and
        #: data-wait spans when the consumer actually blocked
        self._recorder = recorder or NULL_RECORDER
        self.stats = PrefetchStats(_depth=depth)
        self._source = iter(source)
        self._place = place_fn
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._produce, name=name, daemon=True)
        self._thread.start()
        self._closed = False

    # ---- producer --------------------------------------------------------

    def _produce(self) -> None:
        try:
            for item in self._source:
                if self._stop.is_set():
                    return
                with self._recorder.span(PH_H2D,
                                         thread=THREAD_PRODUCER):
                    placed = self._place(item)
                # bounded put with a timeout poll so close() can always
                # unblock the producer even if the consumer vanished
                # without draining
                t0 = time.perf_counter()
                while not self._stop.is_set():
                    try:
                        self._q.put(placed, timeout=0.05)
                        break
                    except queue.Full:
                        continue
                self.stats.put_wait_s += time.perf_counter() - t0
            self._final_put(_Stop())
        except BaseException as exc:  # noqa: BLE001 — carried to consumer
            self._final_put(_Raise(exc))

    def _final_put(self, sentinel: Any) -> None:
        while not self._stop.is_set():
            try:
                self._q.put(sentinel, timeout=0.05)
                return
            except queue.Full:
                continue

    # ---- consumer --------------------------------------------------------

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self._closed:
            raise StopIteration
        hit = not self._q.empty()
        t0 = time.perf_counter()
        item = self._q.get()
        waited = time.perf_counter() - t0
        if isinstance(item, _Stop):
            self.close()
            raise StopIteration
        if isinstance(item, _Raise):
            self.close()
            raise item.exc
        self.stats.batches += 1
        if hit:
            self.stats.hits += 1
        else:
            self.stats.wait_s += waited
            # a miss is real main-thread data-wait: the device's input
            # was not resident when the loop asked — the timeline span
            # that explains a goodput data_wait bucket
            self._recorder.record(PH_DATA_WAIT, t0, waited)
        return item

    # ---- lifecycle -------------------------------------------------------

    def close(self) -> None:
        """Stop the producer and join it. Safe to call repeatedly and
        from ``finally`` blocks; buffered batches are dropped (they are
        just device arrays — the GC reclaims them)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        # drain so a producer blocked in put() sees the stop flag promptly
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            # a place_fn wedged >5s (e.g. a multi-host device_put against
            # a dead peer): don't hang the trainer's exit path on it, but
            # never let the leak be invisible either
            import logging

            logging.getLogger(__name__).warning(
                "prefetch producer %r still alive after close(); a "
                "placement call is wedged — the thread is daemon and "
                "will not block process exit", self._thread.name)

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> Optional[bool]:
        self.close()
        return None


def prefetch_to_device(source: Iterable[Any],
                       place_fn: Callable[[Any], Any],
                       depth: int = 2,
                       recorder: Any = None) -> Iterable[Any]:
    """Functional form: ``depth <= 0`` returns the synchronous pipeline
    (place inline, no thread) so call sites can switch with one knob.
    ``recorder`` (telemetry/spans.py) tags H2D/data-wait spans; in the
    synchronous path the placement blocks the main thread, so its span
    is main-thread (timeline-visible, deliberately outside the goodput
    stall buckets — it is the cost the prefetcher exists to hide)."""
    if depth <= 0:
        rec = recorder or NULL_RECORDER
        def _sync():
            for item in source:
                with rec.span(PH_H2D):
                    placed = place_fn(item)
                yield placed
        return _sync()
    return DevicePrefetcher(source, place_fn, depth=depth,
                            recorder=recorder)
