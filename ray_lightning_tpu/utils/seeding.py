"""Deterministic seeding.

Parity: the reference propagates ``PL_GLOBAL_SEED`` to every worker
(reference: ray_lightning/ray_ddp.py:158-164). Here a single seed drives
numpy, python random, and the JAX PRNG key threaded through the Trainer.
"""
from __future__ import annotations

import os
import random
from typing import Optional

import numpy as np

GLOBAL_SEED_ENV = "RLT_GLOBAL_SEED"


def seed_everything(seed: Optional[int] = None) -> int:
    """Seed python/numpy and export the seed for worker processes.

    Returns the seed actually used (drawn from the env var or 0 if unset).
    """
    if seed is None:
        seed = int(os.environ.get(GLOBAL_SEED_ENV, 0))
    os.environ[GLOBAL_SEED_ENV] = str(seed)
    random.seed(seed)
    np.random.seed(seed % (2**32))
    return seed
