"""Device-emulation helper: the TPU analog of the reference's throwaway
local Ray cluster (`ray.init(num_cpus=2)`, reference tests/test_ddp.py:16-21).

Call before any other JAX use (works even if jax is already imported, as
long as no backend has initialized yet)."""
from __future__ import annotations

import os
import re


def simulate_cpu_devices(n: int = 8) -> None:
    """Emulate an n-device mesh on host CPU for tests/laptops/CI.

    Authoritative about the count: an inherited
    ``--xla_force_host_platform_device_count`` (e.g. leaked from an outer
    test harness into a subprocess) is replaced, not kept — callers asking
    for n devices get n.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    want = f"--xla_force_host_platform_device_count={n}"
    if "--xla_force_host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", want, flags
        )
    else:
        flags = f"{flags} {want}"
    os.environ["XLA_FLAGS"] = flags.strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
