"""Framework logger (reference uses PTL's logger; ray_lightning/ray_ddp.py:9)."""
from __future__ import annotations

import logging
import sys

_CONFIGURED = False


def get_logger(name: str = "ray_lightning_tpu") -> logging.Logger:
    global _CONFIGURED
    if not _CONFIGURED:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("[%(asctime)s %(name)s %(levelname)s] %(message)s")
        )
        root = logging.getLogger("ray_lightning_tpu")
        root.addHandler(handler)
        root.setLevel(logging.INFO)
        root.propagate = False
        _CONFIGURED = True
    return logging.getLogger(name)
