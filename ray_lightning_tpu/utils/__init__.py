from ray_lightning_tpu.utils.pytree import (
    tree_size_bytes,
    tree_param_count,
    named_leaves,
    host_copy,
)
from ray_lightning_tpu.utils.seeding import seed_everything
from ray_lightning_tpu.utils.logging import get_logger
from ray_lightning_tpu.utils.devices import simulate_cpu_devices

__all__ = [
    "tree_size_bytes",
    "tree_param_count",
    "named_leaves",
    "host_copy",
    "seed_everything",
    "get_logger",
    "simulate_cpu_devices",
]
