"""Pytree helpers shared across the framework."""
from __future__ import annotations

from typing import Any, Iterator

import jax
import numpy as np


def tree_param_count(tree: Any) -> int:
    """Total number of array elements in a pytree."""
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree))


def tree_size_bytes(tree: Any) -> int:
    """Total bytes of a pytree of arrays."""
    return sum(
        int(np.prod(x.shape)) * x.dtype.itemsize for x in jax.tree.leaves(tree)
    )


def named_leaves(tree: Any) -> Iterator[tuple[str, Any]]:
    """Yield ("path/to/leaf", leaf) pairs with slash-joined string keys."""
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        yield _path_str(path), leaf


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def host_copy(tree: Any) -> Any:
    """Fetch a (possibly sharded) pytree of device arrays to host numpy.

    Sharded arrays are gathered; this is the small-model convenience path —
    large models should go through the sharded checkpoint writer instead.
    """
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)
