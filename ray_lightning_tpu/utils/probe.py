"""Device throughput probe + public spec-sheet peaks.

Single source of truth for the bare-matmul health probe that bench.py
embeds in its JSON line and ``python -m ray_lightning_tpu --probe``
prints: far below the chip's spec-sheet peak means the chip is
externally contended (shared/tunneled), and model numbers measured in
the same session are lower bounds, not capability.
"""
from __future__ import annotations

import time
from typing import Optional

#: bf16 peak TFLOP/s per chip, by PJRT device_kind (public spec sheets)
PEAK_TFLOPS = {
    "TPU v3": 123.0,
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,  # v5e
    "TPU v5e": 197.0,
    "TPU v5": 459.0,       # v5p
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,  # v6e / Trillium
    "TPU v6e": 918.0,
}
DEFAULT_PEAK = 197.0  # assume v5e-class when unknown (CPU runs, new kinds)


def device_peak_tflops(kind: str) -> float:
    return PEAK_TFLOPS.get(kind, DEFAULT_PEAK)


def matmul_tflops(loop_iters: Optional[int] = None,
                  windows: Optional[int] = None,
                  n: Optional[int] = None) -> float:
    """Measured bf16 matmul TFLOP/s on the default device.

    The chain of dependent n^3 matmuls runs inside ONE jitted
    `fori_loop` (~70 TFLOP per dispatch at the TPU sizing), so
    per-dispatch latency — which through a remote-device tunnel dwarfs a
    single matmul and would make a per-call probe measure dispatch, not
    throughput — amortizes to noise; measured saturation on v5e: 64
    iters reads within 1% of 128. `b` holds 1/n in every entry so the
    iterate stays exactly 1: no overflow, nothing for XLA to fold (both
    operands are runtime inputs). Best-of-windows timing shrugs off
    contention bursts.

    Sizing defaults are device-aware: known accelerator kinds get the
    full ~280-TFLOP probe (seconds on a TPU); unknown kinds (CPU smoke
    runs) get a tiny one that still reports a number.
    """
    import jax
    import jax.numpy as jnp

    if loop_iters is None or n is None or windows is None:
        known = jax.devices()[0].device_kind in PEAK_TFLOPS
        if loop_iters is None:
            loop_iters = 64 if known else 4
        if n is None:
            n = 8192 if known else 1024
        if windows is None:
            windows = 3 if known else 1

    b = jnp.full((n, n), 1.0 / n, jnp.bfloat16)

    @jax.jit
    def chain(a, b):
        return jax.lax.fori_loop(
            0, loop_iters, lambda _, acc: acc @ b, a, unroll=4
        )

    a = jnp.ones((n, n), jnp.bfloat16)
    float(jax.device_get(chain(a, b)[0, 0]))  # compile + warm
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        float(jax.device_get(chain(a, b)[0, 0]))
        best = min(best, time.perf_counter() - t0)
    return 2 * n**3 * loop_iters / best / 1e12
