"""Fused (chunked) cross-entropy: lm_head projection + CE without ever
materializing the full [B, S, V] logits tensor.

The HBM hazard: at Llama-3-8B scale (V=128256) full-sequence f32 logits
are ~2 GB per 4k-token microbatch — they dominate activation memory and
stall the matmul pipeline on writeback. (The reference has no LM path at
all — its models are MLPs, reference tests/utils.py:96-120 — so this is
net-new capability, built TPU-first.)

Design (XLA-idiomatic, no hand-scheduling):
  * flatten tokens, `lax.scan` over chunks of C tokens: each step computes
    a [C, V] logits tile (bf16 matmul on the MXU, f32 accumulation via
    ``preferred_element_type``), reduces it to per-token loss, and
    discards it — live logits memory is O(C·V) instead of O(B·S·V);
  * `jax.checkpoint` on the chunk body: backward RECOMPUTES the tile
    instead of saving it, so the residual set stays O(C·V) there too
    (the classic Liger-style fused-CE memory shape, expressed as remat
    + scan rather than a hand-written kernel — XLA fuses the matmul,
    logsumexp and subtraction into the tile);
  * grad w.r.t. the lm_head weight accumulates across scan steps
    automatically (scan's backward carries the cotangent sum).

Matches `cross_entropy_loss` (models/llama.py) bit-for-bit in f32 up to
reduction order.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def fused_cross_entropy(
    hidden: jnp.ndarray,
    lm_head: jnp.ndarray,
    targets: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    chunk_tokens: int = 1024,
    compute_dtype: jnp.dtype = jnp.bfloat16,
) -> jnp.ndarray:
    """Mean token CE of ``normalize(hidden) @ lm_head`` vs ``targets``.

    hidden:  [B, S, D] final-norm'd activations (any float dtype).
    lm_head: [D, V] projection weight (the `lm_head/kernel` param, or the
             transposed embedding for tied-embedding models).
    targets: [B, S] int labels.
    mask:    optional [B, S] 0/1 validity mask.
    chunk_tokens: logits tile height C; live logits memory is C×V.

    Returns the scalar mean loss (f32), masked-token weighted.
    """
    B, S, D = hidden.shape
    T = B * S
    x = hidden.reshape(T, D).astype(compute_dtype)
    t = targets.reshape(T)
    m = (jnp.ones((T,), jnp.float32) if mask is None
         else mask.reshape(T).astype(jnp.float32))
    w = lm_head.astype(compute_dtype)

    # Static tiling: pad T up to a multiple of the tile height with
    # zero-masked rows (never fall back to one giant tile — an awkward
    # prime T must not silently materialize the [T, V] logits this
    # function exists to avoid).
    C = min(max(1, chunk_tokens), T)
    pad = (-T) % C
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, D), x.dtype)])
        t = jnp.concatenate([t, jnp.zeros((pad,), t.dtype)])
        m = jnp.concatenate([m, jnp.zeros((pad,), m.dtype)])
    n_chunks = (T + pad) // C

    @jax.checkpoint
    def chunk_loss(x_c, t_c):
        # [C, V] tile: bf16 MXU matmul, f32 accumulation
        logits = jnp.dot(x_c, w, preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, t_c[:, None], axis=-1)[:, 0]
        return lse - tgt  # [C] f32

    def body(carry, inp):
        loss_sum, weight_sum = carry
        x_c, t_c, m_c = inp
        losses = chunk_loss(x_c, t_c)
        return (loss_sum + (losses * m_c).sum(),
                weight_sum + m_c.sum()), None

    (loss_sum, weight_sum), _ = jax.lax.scan(
        body,
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (x.reshape(n_chunks, C, D), t.reshape(n_chunks, C),
         m.reshape(n_chunks, C)),
    )
    return loss_sum / jnp.maximum(weight_sum, 1.0)
