"""Fused (chunked) cross-entropy: lm_head projection + CE without ever
materializing the full [B, S, V] logits tensor.

The HBM hazard: at Llama-3-8B scale (V=128256) full-sequence f32 logits
are ~2 GB per 4k-token microbatch — they dominate activation memory and
stall the matmul pipeline on writeback. (The reference has no LM path at
all — its models are MLPs, reference tests/utils.py:96-120 — so this is
net-new capability, built TPU-first.)

Design (XLA-idiomatic, no hand-scheduling):
  * flatten tokens, `lax.scan` over chunks of C tokens: each step computes
    a [C, V] logits tile (bf16 matmul on the MXU, f32 accumulation via
    ``preferred_element_type``), reduces it to per-token loss, and
    discards it — live logits memory is O(C·V) instead of O(B·S·V);
  * `jax.checkpoint` on the chunk body: backward RECOMPUTES the tile
    instead of saving it, so the residual set stays O(C·V) there too
    (the classic Liger-style fused-CE memory shape, expressed as remat
    + scan rather than a hand-written kernel — XLA fuses the matmul,
    logsumexp and subtraction into the tile);
  * grad w.r.t. the lm_head weight accumulates across scan steps
    automatically (scan's backward carries the cotangent sum).

Matches `cross_entropy_loss` (models/llama.py) bit-for-bit in f32 up to
reduction order.
"""
from __future__ import annotations

import os
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _inline_unroll_max() -> int:
    """Chunk-count ceiling for unrolling the inline-CE forward (above it,
    fall back to lax.scan). Parse-or-default on the env override — a
    malformed value must degrade (with a warning, so a mistyped override
    is debuggable), not fail the training step at trace time — the same
    policy as the flash block-size knobs (ops/pallas/flash.py
    _env_block)."""
    raw = os.environ.get("RLT_CE_INLINE_UNROLL_MAX")
    if raw is None:
        return 16
    try:
        return int(raw)
    except ValueError:
        import warnings

        warnings.warn(
            f"RLT_CE_INLINE_UNROLL_MAX={raw!r} is not an int; "
            "using default 16", stacklevel=2)
        return 16


def fused_cross_entropy(
    hidden: jnp.ndarray,
    lm_head: jnp.ndarray,
    targets: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    chunk_tokens: int = 1024,
    compute_dtype: jnp.dtype = jnp.bfloat16,
    inline_backward: bool = False,
) -> jnp.ndarray:
    """Mean token CE of ``normalize(hidden) @ lm_head`` vs ``targets``.

    hidden:  [B, S, D] final-norm'd activations (any float dtype).
    lm_head: [D, V] projection weight (the `lm_head/kernel` param, or the
             transposed embedding for tied-embedding models).
    targets: [B, S] int labels.
    mask:    optional [B, S] 0/1 validity mask.
    chunk_tokens: logits tile height C; live logits memory is C×V.
    inline_backward: compute the CE gradients DURING the forward pass
             (see ``_ce_inline``) instead of rematerializing each logits
             tile in the backward; trades a D×V residual (the lm_head's
             dtype) for one fewer [C, D]×[D, V] matmul pass per step.
             Exact for hidden/lm_head gradients at any cotangent scale.
             Caveat: the MASK cotangent is zero on this path (the default
             path differentiates through the mean's weighting) — do not
             use it with a learnable mask.

    Returns the scalar mean loss (f32), masked-token weighted.
    """
    if inline_backward:
        # dtype travels as its NAME: custom_vjp static args must be
        # plain hashable non-array values (a np.dtype is rejected)
        return _ce_inline(chunk_tokens, jnp.dtype(compute_dtype).name,
                          hidden, lm_head, targets,
                          jnp.ones(targets.shape, jnp.float32)
                          if mask is None
                          else mask.astype(jnp.float32))
    x, t, m, n_chunks, C = _prep_chunks(
        hidden, targets, mask, chunk_tokens, compute_dtype)
    D = hidden.shape[-1]
    w = lm_head.astype(compute_dtype)

    @jax.checkpoint
    def chunk_loss(x_c, t_c):
        # [C, V] tile: bf16 MXU matmul, f32 accumulation
        logits = jnp.dot(x_c, w, preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, t_c[:, None], axis=-1)[:, 0]
        return lse - tgt  # [C] f32

    def body(carry, inp):
        loss_sum, weight_sum = carry
        x_c, t_c, m_c = inp
        losses = chunk_loss(x_c, t_c)
        return (loss_sum + (losses * m_c).sum(),
                weight_sum + m_c.sum()), None

    (loss_sum, weight_sum), _ = jax.lax.scan(
        body,
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (x.reshape(n_chunks, C, D), t.reshape(n_chunks, C),
         m.reshape(n_chunks, C)),
    )
    return loss_sum / jnp.maximum(weight_sum, 1.0)


def _prep_chunks(hidden, targets, mask, chunk_tokens, compute_dtype):
    """Shared flatten/cast/pad tiling for both CE paths.

    Static tiling: pad T up to a multiple of the tile height with
    zero-masked rows (never fall back to one giant tile — an awkward
    prime T must not silently materialize the [T, V] logits this module
    exists to avoid). Returns flat (x [T+pad, D], t, m, n_chunks, C).
    """
    B, S, D = hidden.shape
    T = B * S
    x = hidden.reshape(T, D).astype(compute_dtype)
    t = targets.reshape(T)
    m = (jnp.ones((T,), jnp.float32) if mask is None
         else mask.reshape(T).astype(jnp.float32))
    C = min(max(1, chunk_tokens), T)
    pad = (-T) % C
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, D), x.dtype)])
        t = jnp.concatenate([t, jnp.zeros((pad,), t.dtype)])
        m = jnp.concatenate([m, jnp.zeros((pad,), m.dtype)])
    return x, t, m, (T + pad) // C, C


# ---- inline-backward variant ---------------------------------------------
#
# The chunked-remat path above pays a pure recompute tax in backward: each
# [C, V] logits tile is materialized a SECOND time (jax.checkpoint) just to
# rebuild the softmax, then two more matmuls produce dx and dW — 4 tile
# matmul passes per step where 3 carry useful FLOPs. At the flagship bench
# shape (D=2048, V=128256) that recompute is ~10% of the whole training
# step's executed FLOPs.
#
# The fix (the Liger-kernel idea, expressed as XLA-level scan + custom_vjp
# rather than a hand-written kernel): CE is the ROOT of the loss graph, and
# its gradient is LINEAR in the upstream cotangent g — so compute
# (dx, dW) for g=1 during the forward scan, store them as residuals, and
# have the backward just scale by g. Exact for any g (grad-accumulation
# scans, loss weighting); no logits tile is ever built twice. Bonus: dW
# accumulates in f32 across chunks (the autodiff path accumulates the
# bf16-cast weight's cotangent chunk-by-chunk in bf16).
#
# Cost: residual memory dx [T, D] (activation-sized) + dW [D, V] stored in
# the lm_head's dtype (f32 for this framework's f32-param models) — the
# same footprint as the weight-grad buffer backward allocates anyway, just
# live earlier. At 8B/128k-vocab scale that is ~2 GB/chip under fsdp=8,
# acceptable against the recompute saving; it is NOT the default because
# tiny-memory configs may prefer the remat path.


def _ce_inline_fwd(chunk_tokens, dtype_name, hidden, lm_head, targets, m):
    compute_dtype = jnp.dtype(dtype_name)
    B, S, D = hidden.shape
    T = B * S
    V = lm_head.shape[1]
    x, t, mm, n_chunks, C = _prep_chunks(
        hidden, targets, m, chunk_tokens, compute_dtype)
    pad = n_chunks * C - T
    w = lm_head.astype(compute_dtype)
    # Σm is known BEFORE the scan, so per-chunk dlogits can carry the
    # final 1/Σm normalization and dW is a plain sum across chunks.
    weight_sum = mm.sum()
    inv = 1.0 / jnp.maximum(weight_sum, 1.0)

    def body(dw_acc, inp):
        x_c, t_c, m_c = inp
        logits = jnp.dot(x_c, w, preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, t_c[:, None], axis=-1)[:, 0]
        loss_c = ((lse - tgt) * m_c).sum()
        # d(mean CE)/d(logits) = (softmax - onehot) * m/Σm — computed
        # here, once, from the tile that is already live. The onehot is a
        # broadcasted-iota compare, NOT a scatter: elementwise, so XLA
        # fuses exp + subtract + scale + cast into one pass over the tile
        # and the only materialized [C, V] intermediates are the f32
        # logits and the bf16 dlogits (a scatter would force a second
        # f32 [C, V] buffer — the peak-memory cliff that kept the larger
        # inline batches from compiling on a 16 GB chip).
        coeff = m_c * inv
        onehot = (
            jax.lax.broadcasted_iota(t_c.dtype, logits.shape, 1)
            == t_c[:, None]
        )
        dlogits = (
            (jnp.exp(logits - lse[:, None]) - onehot) * coeff[:, None]
        ).astype(compute_dtype)
        dx_c = jnp.dot(dlogits, w.T, preferred_element_type=jnp.float32)
        dw_acc = dw_acc + jnp.dot(x_c.T, dlogits,
                                  preferred_element_type=jnp.float32)
        return dw_acc, (loss_c, dx_c.astype(hidden.dtype))

    xs = (x.reshape(n_chunks, C, D), t.reshape(n_chunks, C),
          mm.reshape(n_chunks, C))
    if n_chunks <= _inline_unroll_max():
        # Straight-line chunk chain instead of a `while` loop: n_chunks is
        # static, and a lax.scan whose CARRY is the [D, V] f32 dW
        # accumulator (~1 GB at Llama-3 vocab) is the program shape the
        # TPU compile path handled worst in our sweeps (observed on v5e:
        # minutes-long or helper-crashing compiles at n_chunks >= 2,
        # scripts/sweep_flagship_results.jsonl); unrolling removes the
        # while-loop + giant-carry structure entirely. The
        # optimization_barrier threads each chunk's inputs through the
        # previous chunk's dW so the bodies form a data-dependence CHAIN:
        # without it only the dw adds are ordered and the scheduler may
        # overlap several [C, V] logits tiles, silently breaking the
        # O(C·V) live-logits bound this module exists to provide (and
        # that parallel/plan.py charges for exactly once).
        dw = jnp.zeros((D, V), jnp.float32)
        loss_parts, dx_parts = [], []
        for i in range(n_chunks):
            inp = jax.tree.map(lambda a: a[i], xs)
            if i:
                # ALL of the previous chunk's outputs go through the
                # barrier, not just dw: dx_c consumes the dlogits tile,
                # and leaving it outside the chain would let the
                # scheduler defer every dx matmul to the end — n_chunks
                # dlogits tiles live at once, the exact blow-up the
                # barrier exists to forbid.
                inp, dw, loss_parts[-1], dx_parts[-1] = (
                    jax.lax.optimization_barrier(
                        (inp, dw, loss_parts[-1], dx_parts[-1])))
            dw, (loss_c, dx_c) = body(dw, inp)
            loss_parts.append(loss_c)
            dx_parts.append(dx_c)
        loss_chunks = jnp.stack(loss_parts)
        dx = jnp.stack(dx_parts)
    else:
        dw, (loss_chunks, dx) = jax.lax.scan(
            body, jnp.zeros((D, V), jnp.float32), xs)
    loss = loss_chunks.sum() * inv
    dx_full = dx.reshape(T + pad, D)[:T].reshape(B, S, D)
    # residuals must be arrays only (shapes/dtypes are recovered from dx
    # in bwd; the mask was normalized to f32 at the entry point)
    return loss, (dx_full, dw.astype(lm_head.dtype))


def _ce_inline_bwd(chunk_tokens, dtype_name, res, g):
    dx, dw = res
    t_shape = dx.shape[:2]  # targets/mask are [B, S]
    # integer targets take a float0 cotangent; the mask's true gradient is
    # unused by every caller (it is a data-validity indicator) — zeros.
    return (dx * g.astype(dx.dtype), dw * g.astype(dw.dtype),
            np.zeros(t_shape, jax.dtypes.float0),
            jnp.zeros(t_shape, jnp.float32))


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _ce_inline(chunk_tokens, dtype_name, hidden, lm_head, targets, m):
    # primal-only call (no differentiation): plain chunked loss, zero
    # gradient work — the fwd rule below runs only under grad
    return fused_cross_entropy(hidden, lm_head, targets, m,
                               chunk_tokens=chunk_tokens,
                               compute_dtype=jnp.dtype(dtype_name))


_ce_inline.defvjp(_ce_inline_fwd, _ce_inline_bwd)
