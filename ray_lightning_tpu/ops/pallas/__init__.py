"""Pallas TPU kernels for the hot ops.

Kernels run in interpret mode automatically off-TPU (CPU tests), so the
same code path is exercised by the virtual-device test harness.
"""
from ray_lightning_tpu.ops.pallas.flash import flash_attention_pallas
from ray_lightning_tpu.ops.pallas.paged_attention import (
    paged_attention_pallas,
    paged_shapes_supported,
)
from ray_lightning_tpu.ops.pallas.rmsnorm import rms_norm_pallas

__all__ = ["flash_attention_pallas", "paged_attention_pallas",
           "paged_shapes_supported", "rms_norm_pallas"]
