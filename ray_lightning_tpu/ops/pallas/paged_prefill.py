"""Fused paged-attention PREFILL kernel (pallas TPU).

PR 11's decode kernel (`ops/pallas/paged_attention.py`) retired the
serving engine's capacity-wide dense KV view, but the prefill lane kept
gathering a `[L, prefill_batch, gathered_len, Hkv, hd]` per-group view
every chunk — at the flagship llama3-8b shape the remaining multi-GiB
HBM charge and the dominant per-chunk KV traffic in
`serve_memory_summary`. This kernel retires that last copy: the head
FIFO group's CH-token query chunk attends **causally** to the slot's
already-written pool blocks (plus the in-chunk K/V, which the model's
paged-prefill branch has already scattered into owned blocks through
the scratch-block-0 redirect) DIRECTLY through the per-row block
tables — the dense per-group gather never exists on the fused path.

Schedule (one layer's pool, the head group's chunk):

    q       [B, CH, H, hd]        the group's query chunk (B = group
                                  rows incl. vacant scratch rows)
    pool_k  [n_blocks, P, Hkv, hd]  the shared block pool (k; v alike)
    tables  [B, M] int32          row -> pool block ids (0 = scratch)
    pos     [1] int32             the group's shared cache write offset
                                  (chunk token j sits at pos + j)
    pad     [B] int32             per-row left pad (ragged batched
                                  prefill; 0 = none)

grid = (B, CH/bq, M): for row b, query tile qi streams that row's M
table-named KV tiles through VMEM — the BlockSpec index_map reads the
scalar-prefetched table (`pltpu.PrefetchScalarGridSpec`, exactly the
decode kernel's discipline), so the DMA engine fetches pool block
`tables[b, m]` while compute runs and no gathered copy ever exists in
HBM. Per tile: one `[bq·H, P]` score panel, online-softmax statistics
(running max / sum / accumulator in f32 VMEM scratch — the
`ops/pallas/flash.py` discipline), per-row `pad <= kv_pos <= pos + j`
causal masking applied BEFORE the running max with masked
probabilities zeroed EXPLICITLY (a fully-masked tile's
`exp(-1e30 - (-1e30)) = 1` sentinel trap applies here exactly as it
did in decode — test-pinned), GQA KV heads read in place via the
grouped contraction (no repeat, no extra traffic). Tiles entirely past
the tile's last query position (or entirely under the row's pad) are
skipped (predicated body).

Inference-only: prefill under a serving engine has no backward, so
there is no VJP — the XLA reference twin with identical semantics is
`ops.attention.paged_prefill_reference`, and dispatch follows the
flash discipline (`ops.attention.paged_prefill_uses_pallas` as the
single predicate; interpret mode off-TPU).

Block sizes: the KV tile IS the pool block (`block_size`), the query
tile halves down from 128 until it divides CH (`_fit_q_block`). The
on-TPU sweep over `block_size`/`blocks_per_slot` for BOTH paged
kernels lives in `serve/sweep.py` (docs/SERVING.md "block-size
autotune").
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_lightning_tpu.ops.dispatch import interpret_mode as _interpret

_NEG_INF = -1e30  # never true -inf: exp(-inf - -inf) = nan on empty rows


def _fit_q_block(ch: int, cap: int = 128) -> int:
    """Largest query tile <= ``cap`` that divides the chunk width
    (halving search, the flash `_fit_block` discipline)."""
    b = min(cap, ch)
    while b > 1 and ch % b != 0:
        b //= 2
    return b


def paged_prefill_shapes_supported(q_shape, pool_shape) -> bool:
    """Would the prefill kernel accept these shapes on a real TPU?

    q [B, CH, H, hd], pool [n_blocks, P, Hkv, hd]: the head dim must be
    lane-aligned (128, or 64 which still tiles acceptably — the decode
    kernel's rule), the pool block must be sublane-aligned (P % 8), the
    GQA ratio must be whole, and the flattened score panel rows
    (q-tile x heads) must be sublane-aligned. Callers that must know
    the dispatch outcome use `ops.attention.paged_prefill_uses_pallas`,
    never this directly — one predicate, no drift."""
    if len(q_shape) != 4 or len(pool_shape) != 4:
        return False
    _, ch, h, hd = q_shape
    _, p, hkv, hd2 = pool_shape
    if hd != hd2:
        return False
    if hd % 128 != 0 and hd not in (64,):
        return False
    if hkv < 1 or h % hkv != 0:
        return False
    if p % 8 != 0:
        return False
    if ch < 1 or (_fit_q_block(ch) * h) % 8 != 0:
        return False
    return True


def _prefill_kernel(tbl_ref, pos_ref, pad_ref, q_ref, k_ref, v_ref,
                    o_ref, acc, m_scr, l_scr, *, scale, block_p,
                    block_q, num_kv_blocks, n_rep):
    """One (row, q-tile, kv-tile) grid step. Scratch persists across
    the innermost kv-tile axis (the flash forward's accumulation
    contract)."""
    b = pl.program_id(0)
    m = pl.program_id(2)

    @pl.when(m == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    pos = pos_ref[0]
    pad = pad_ref[b]
    # cache position of this q tile's first/last query row
    q_start = pos + pl.program_id(1) * block_q
    q_end = q_start + block_q - 1
    kv_start = m * block_p

    # tiles entirely past the tile's last query position (causal: no
    # query can see them) or entirely under the row's left pad hold
    # nothing visible — skip the DMA'd tile's compute (its garbage
    # never reaches the stats)
    @pl.when((kv_start <= q_end) & (kv_start + block_p > pad))
    def _body():
        q = q_ref[0].astype(jnp.float32)       # [bq, H, hd]
        k = k_ref[0].astype(jnp.float32)       # [P, Hkv, hd]
        v = v_ref[0].astype(jnp.float32)
        bq, h, hd = q.shape
        hkv = k.shape[1]
        # GQA head map: query head g*n_rep + r reads kv head g — group
        # the q heads and batch the contraction over kv heads, so KV
        # tiles are consumed in place (no repeat; the decode kernel's
        # grouped-contraction discipline, extended over the q tile)
        qg = (q.reshape(bq, hkv, n_rep, hd)
              .transpose(1, 0, 2, 3).reshape(hkv, bq * n_rep, hd))
        kg = k.transpose(1, 0, 2)              # [Hkv, P, hd]
        vg = v.transpose(1, 0, 2)
        s = jax.lax.dot_general(
            qg, kg, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale                              # [Hkv, bq*n_rep, P]
        s4 = s.reshape(hkv, bq, n_rep, block_p)
        kv_pos = kv_start + jax.lax.broadcasted_iota(
            jnp.int32, s4.shape, 3)
        q_pos = q_start + jax.lax.broadcasted_iota(
            jnp.int32, s4.shape, 1)
        # causal + pad, BEFORE the running max: scratch-block garbage,
        # table tails, pad columns and future in-chunk positions all
        # read _NEG_INF
        visible = (kv_pos <= q_pos) & (kv_pos >= pad)
        s4 = jnp.where(visible, s4, _NEG_INF)
        # flatten to the stats layout [bq*H, P] (row-major q x heads)
        sf = s4.transpose(1, 0, 2, 3).reshape(bq * h, block_p)
        vf = visible.transpose(1, 0, 2, 3).reshape(bq * h, block_p)
        m_prev = m_scr[:, 0]                   # [bq*H]
        m_new = jnp.maximum(m_prev, jnp.max(sf, axis=1))
        # masked positions are zeroed EXPLICITLY, not only through the
        # exp: a fully-masked row (every position under the row's pad,
        # or a pad-column query) has s == m_new == _NEG_INF and
        # exp(s - m_new) == 1 — the sentinel-minus-sentinel trap would
        # weight garbage at full probability
        p = jnp.where(vf, jnp.exp(sf - m_new[:, None]), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, 0] = corr * l_scr[:, 0] + jnp.sum(p, axis=1)
        pg = (p.reshape(bq, hkv, n_rep, block_p)
              .transpose(1, 0, 2, 3).reshape(hkv, bq * n_rep, block_p))
        av = jax.lax.dot_general(
            pg, vg, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                      # [Hkv, bq*n_rep, hd]
        avf = (av.reshape(hkv, bq, n_rep, hd)
               .transpose(1, 0, 2, 3).reshape(bq * h, hd))
        acc[:] = corr[:, None] * acc[:] + avf
        m_scr[:, 0] = m_new

    @pl.when(m == num_kv_blocks - 1)
    def _finish():
        l = l_scr[:, 0]
        safe_l = jnp.where(l == 0.0, 1.0, l)   # fully-masked row -> 0s
        bq, h, hd = o_ref.shape[1:]
        o_ref[0] = (acc[:] / safe_l[:, None]).reshape(
            bq, h, hd).astype(o_ref.dtype)


def paged_prefill_pallas(
    q: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    tables: jnp.ndarray,
    pos,
    pad: jnp.ndarray | None = None,
    scale: float | None = None,
) -> jnp.ndarray:
    """Chunked causal prefill attention over the paged pool:
    [B, CH, H, hd] out.

    ``tables`` names each group row's pool blocks (block 0 = reserved
    scratch — readable garbage, always masked); chunk token ``j`` sits
    at cache position ``pos + j`` and attends to
    ``pad[b] <= kv_pos <= pos + j`` — the already-written blocks plus
    the in-chunk prefix, which the caller has scattered into the pool
    BEFORE this call (write-then-attend, the decode lane's ordering).
    ``pad[b]`` masks a left-padded row's pad columns; a query that is
    itself a pad column sees nothing and emits zeros (discarded by the
    engine's active-row scatter)."""
    b, ch, h, hd = q.shape
    n_blocks, p, hkv, _ = pool_k.shape
    m = tables.shape[1]
    n_rep = h // hkv
    scale = scale if scale is not None else hd ** -0.5
    if pad is None:
        pad = jnp.zeros((b,), jnp.int32)
    bq = _fit_q_block(ch)
    nq = ch // bq
    kernel = functools.partial(
        _prefill_kernel, scale=scale, block_p=p, block_q=bq,
        num_kv_blocks=m, n_rep=n_rep)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # tables, pos, pad
        grid=(b, nq, m),
        in_specs=[
            pl.BlockSpec((1, bq, h, hd),
                         lambda bi, qi, mi, tbl, ps, pd:
                         (bi, qi, 0, 0)),
            # the paged trick: the KV tile for (row, m) is whichever
            # pool block the scalar-prefetched table names — the tile
            # streams HBM -> VMEM with no intermediate gathered copy
            pl.BlockSpec((1, p, hkv, hd),
                         lambda bi, qi, mi, tbl, ps, pd:
                         (tbl[bi, mi], 0, 0, 0)),
            pl.BlockSpec((1, p, hkv, hd),
                         lambda bi, qi, mi, tbl, ps, pd:
                         (tbl[bi, mi], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, h, hd),
                               lambda bi, qi, mi, tbl, ps, pd:
                               (bi, qi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((bq * h, hd), jnp.float32),
            pltpu.VMEM((bq * h, 1), jnp.float32),
            pltpu.VMEM((bq * h, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, ch, h, hd), q.dtype),
        interpret=_interpret(),
    )(tables.astype(jnp.int32),
      jnp.asarray(pos, jnp.int32).reshape(1),
      pad.astype(jnp.int32), q, pool_k, pool_v)
