"""Flash attention (forward + backward) as pallas TPU kernels.

Online-softmax tiling (Flash-Attention-2 schedule): the S×S score matrix is
never materialized in HBM; each grid step streams one KV tile through VMEM
against a resident Q tile, keeping running (max, sum, acc) statistics in
f32 scratch. Causal blocks that are fully masked are skipped (predicated
body). Backward recomputes P from the saved logsumexp, in two passes:
one gridded over KV tiles (dK, dV) and one over Q tiles (dQ) — no atomics,
which TPUs don't have.

Layout: kernels work on [B, H, S, D]; the public wrapper takes the
framework-standard [B, S, H, D] and transposes (XLA folds the transpose
into neighboring ops). GQA is handled by an index_map trick: KV tiles are
indexed with h // n_rep, so KV heads are read in place — no repeat, no
extra HBM traffic.

Tiling constraints: block sizes start from the tuned defaults (512 Q /
1024 KV) and halve until they divide S (`_fit_block`), so any S that is
a multiple of a small power of two tiles; D should be a multiple of 128
(MXU lane width) — callers check `shapes_supported` and fall back to the
XLA path otherwise.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tuned on v5e (B=4, S=2048, H=16, D=128, fwd+bwd sweep 2026-07): larger
# KV tiles amortize the HBM streaming against the resident Q tile;
# (512, 1024) ran 1.49x faster than (256, 256), and 2048-wide tiles blow
# the VMEM budget. Still clamped to S when S is smaller.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
_NEG_INF = -1e30  # avoid true -inf: exp(-inf - -inf) = nan on fully-masked rows


from ray_lightning_tpu.ops.dispatch import interpret_mode as _interpret


def _fit_block(block: int, s: int) -> int:
    """Largest block <= `block` that divides s (halving search)."""
    b = min(block, s)
    while b > 8 and s % b != 0:
        b //= 2
    return b


def shapes_supported(q_shape, k_shape) -> bool:
    """[B, S, H, D]: blocks must tile S; D must be lane-aligned."""
    b, sq, hq, d = q_shape
    _, sk, hk, _ = k_shape
    if d % 128 != 0 and d not in (64,):  # 64 still tiles acceptably
        return False
    if hq % hk != 0:
        return False
    if sq % 8 != 0 or sk % 8 != 0:  # sublane alignment
        return False
    # blocks below 128 starve the MXU (8-wide tiles on S=8*odd would
    # "fit" but run far slower than the fused XLA path) — fall back.
    bq, bk = _fit_block(DEFAULT_BLOCK_Q, sq), _fit_block(DEFAULT_BLOCK_K, sk)
    return (sq % bq == 0 and bq >= min(sq, 128)
            and sk % bk == 0 and bk >= min(sk, 128))


# ----------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr,
                *, scale, causal, q_offset, block_q, block_k, num_kv_blocks):
    i = pl.program_id(2)  # q block
    j = pl.program_id(3)  # kv block (innermost: scratch persists across it)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # causal skip: block fully masked iff smallest q pos < smallest kv pos
    q_start = i * block_q + q_offset
    kv_start = j * block_k
    run = (not causal) or (q_start + block_q - 1 >= kv_start)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)  # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kv_pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= kv_pos, s, _NEG_INF)
        m_prev = m_scr[:, 0]  # [bq]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, 0] = corr * l_scr[:, 0] + jnp.sum(p, axis=1)
        acc[:] = corr[:, None] * acc[:] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:, 0] = m_new

    @pl.when(j == num_kv_blocks - 1)
    def _finish():
        l = l_scr[:, 0]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc[:] / safe_l[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0, :, 0] = m_scr[:, 0] + jnp.log(safe_l)


def _fwd(q, k, v, scale, causal, q_offset, block_q, block_k):
    """q,k,v: [B, H, S, D] (kv may have fewer heads). Returns (o, lse)."""
    b, h, sq, d = q.shape
    hk = k.shape[1]
    n_rep = h // hk
    bq = _fit_block(block_q, sq)
    bk = _fit_block(block_k, k.shape[2])
    nq, nk = sq // bq, k.shape[2] // bk
    grid = (b, h, nq, nk)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, q_offset=q_offset,
        block_q=bq, block_k=bk, num_kv_blocks=nk,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, i, j, n_rep=n_rep: (b_, h_ // n_rep, j, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, i, j, n_rep=n_rep: (b_, h_ // n_rep, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------- backward


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale, causal, q_offset, block_q, block_k,
                    num_q_blocks):
    j = pl.program_id(2)  # kv block (outer)
    i = pl.program_id(3)  # q block (inner: accumulators persist)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = i * block_q + q_offset
    kv_start = j * block_k
    run = (not causal) or (q_start + block_q - 1 >= kv_start)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0]      # [bq]
        delta = delta_ref[0, 0, :, 0]  # [bq] = rowsum(dO * O)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kv_pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= kv_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])  # [bq, bk]
        # dV += P^T dO
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        ds = p * (dp - delta[:, None]) * scale
        # dK += dS^T Q
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(i == num_q_blocks - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc,
                   *, scale, causal, q_offset, block_q, block_k,
                   num_kv_blocks):
    i = pl.program_id(2)  # q block (outer)
    j = pl.program_id(3)  # kv block (inner)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = i * block_q + q_offset
    kv_start = j * block_k
    run = (not causal) or (q_start + block_q - 1 >= kv_start)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kv_pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= kv_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == num_kv_blocks - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd(scale, causal, q_offset, block_q, block_k, res, do):
    q, k, v, o, lse = res
    b, h, sq, d = q.shape
    hk = k.shape[1]
    n_rep = h // hk
    sk = k.shape[2]
    bq = _fit_block(block_q, sq)
    bk = _fit_block(block_k, sk)
    nq, nk = sq // bq, sk // bk

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [B,H,Sq,1]

    # pass 1: dK, dV — grid over kv blocks, accumulate over q blocks.
    # GQA: compute per-Q-head dk/dv at [B, H, Sk, D], then segment-sum the
    # rep groups down to [B, Hk, Sk, D] outside the kernel (one reshape-sum).
    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, q_offset=q_offset,
        block_q=bq, block_k=bk, num_q_blocks=nq,
    )
    dk_full, dv_full = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, j, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, j, i, n_rep=n_rep: (b_, h_ // n_rep, j, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, j, i, n_rep=n_rep: (b_, h_ // n_rep, j, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, j, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, j, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, j, i: (b_, h_, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, j, i: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, j, i: (b_, h_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    if n_rep > 1:
        dk = dk_full.reshape(b, hk, n_rep, sk, d).sum(axis=2)
        dv = dv_full.reshape(b, hk, n_rep, sk, d).sum(axis=2)
    else:
        dk, dv = dk_full, dv_full

    # pass 2: dQ — grid over q blocks, accumulate over kv blocks.
    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, q_offset=q_offset,
        block_q=bq, block_k=bk, num_kv_blocks=nk,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, i, j, n_rep=n_rep: (b_, h_ // n_rep, j, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, i, j, n_rep=n_rep: (b_, h_ // n_rep, j, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------------ public


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhsd(q, k, v, scale, causal, q_offset, block_q, block_k):
    o, _ = _fwd(q, k, v, scale, causal, q_offset, block_q, block_k)
    return o


def _flash_fwd_rule(q, k, v, scale, causal, q_offset, block_q, block_k):
    o, lse = _fwd(q, k, v, scale, causal, q_offset, block_q, block_k)
    # Label the VJP residuals for jaxpr readability. NOTE these names
    # alone cannot make a remat policy save the residuals — a custom_vjp
    # fwd rule is not part of the primal trace, so a named-saveable
    # policy sees nothing (verified in tests/test_ops.py). The working
    # mechanism for remat_policy="attn_out" is optimize_remat=True below,
    # which hoists this rule into a `remat_opt` call whose outputs the
    # policy saves (models/llama.py _attn_residuals_saveable).
    from jax.ad_checkpoint import checkpoint_name

    res = tuple(checkpoint_name(t, "flash_residuals")
                for t in (q, k, v, o, lse))
    return o, res


def _flash_bwd_rule(scale, causal, q_offset, block_q, block_k, res, do):
    return _bwd(scale, causal, q_offset, block_q, block_k, res, do)


# optimize_remat: without it a custom_vjp is OPAQUE to remat policies —
# the residuals live only in the fwd rule, which is not part of the
# primal trace, so save_only_these_names("flash_residuals") had nothing
# to save and the kernel forward re-ran in every remat backward (counted
# via pallas_call occurrences in the jaxpr, tests/test_ops.py). With it,
# JAX rewrites the call so the fwd rule's residual outputs are visible
# to the surrounding checkpoint and the policy decides their fate.
_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule, optimize_remat=True)


def _env_block(name: str, default: int) -> int:
    """Tuning-knob env parse: a malformed value falls back to the tuned
    default with a warning instead of failing the whole training step at
    trace time (same policy as the bench watchdog's env parse)."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        value = int(raw)
        if value <= 0:
            # 0 would divide-by-zero in the grid math, a negative value
            # would yield a negative block — both kill the step at trace
            # time, the exact failure this fallback exists to prevent
            raise ValueError(raw)
    except ValueError:
        import warnings

        warnings.warn(
            f"ignoring malformed {name}={raw!r}; using {default}",
            stacklevel=2,
        )
        return default
    return value


def flash_attention_pallas(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    q_offset: int = 0,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
) -> jnp.ndarray:
    """Flash attention on [B, S, H, D] tensors (framework layout).

    ``block_q``/``block_k`` default to the tuned module constants,
    overridable per-process via ``RLT_FLASH_BLOCK_Q``/``RLT_FLASH_BLOCK_K``
    (read at trace time — the sweep harness's tuning knob)."""
    if block_q is None:
        block_q = _env_block("RLT_FLASH_BLOCK_Q", DEFAULT_BLOCK_Q)
    if block_k is None:
        block_k = _env_block("RLT_FLASH_BLOCK_K", DEFAULT_BLOCK_K)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    qt = q.transpose(0, 2, 1, 3)  # [B, H, S, D]
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    o = _flash_bhsd(qt, kt, vt, scale, causal, q_offset, block_q, block_k)
    return o.transpose(0, 2, 1, 3)
