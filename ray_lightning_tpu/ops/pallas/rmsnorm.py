"""Fused RMSNorm pallas kernel.

One VMEM pass per row block: mean-of-squares reduction, rsqrt, scale —
fused so the activation is read from HBM once (the jnp version usually
fuses too, but this pins it). Backward is analytic jnp (cheap, fuses into
the surrounding backward ops).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


from ray_lightning_tpu.ops.dispatch import interpret_mode as _interpret


def _kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _rmsnorm_fwd_2d(x2, w, eps, block_rows):
    n, d = x2.shape
    br = min(block_rows, n)
    if n % br != 0:
        br = 1
    return pl.pallas_call(
        functools.partial(_kernel, eps=eps),
        grid=(n // br,),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((br, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x2.dtype),
        interpret=_interpret(),
    )(x2, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rmsnorm(x, w, eps):
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    return _rmsnorm_fwd_2d(x2, w, eps, 256).reshape(shape)


def _fwd_rule(x, w, eps):
    return _rmsnorm(x, w, eps), (x, w)


def _bwd_rule(eps, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xf * rstd
    gw = gf * wf
    dx = rstd * (gw - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    dw = jnp.sum(gf * xhat, axis=tuple(range(x.ndim - 1)))
    return dx.astype(x.dtype), dw.astype(w.dtype)


_rmsnorm.defvjp(_fwd_rule, _bwd_rule)


def rms_norm_pallas(x: jnp.ndarray, weight: jnp.ndarray,
                    eps: float = 1e-5) -> jnp.ndarray:
    return _rmsnorm(x, weight, eps)
