"""Fused paged-attention decode kernel (pallas TPU).

The serving engine's reference decode lane feeds the model's cache path
from a dense per-slot gathered view of the block-paged KV pool —
`[L, C, gathered_len, Hkv, hd]` of HBM and a full pool read+write of
traffic every tick, charged honestly by `serve/audit.py`. This kernel
retires that copy: decode attention consumes the pool **directly
through the per-slot block tables**.

Schedule (one layer's pool, all slots):

    q       [C, H, hd]           one query token per slot
    pool_k  [n_blocks, P, Hkv, hd]  the shared block pool (k; v alike)
    tables  [C, M] int32         slot -> pool block ids (0 = scratch)
    lengths [C] int32            valid cache positions per slot
    pad     [C] int32            left-pad columns to mask (ragged
                                 batched prefill; 0 = none)

grid = (C, M): for slot c the kernel streams that slot's M table-named
KV tiles through VMEM — the BlockSpec index_map reads the
scalar-prefetched table (`pltpu.PrefetchScalarGridSpec`), so the DMA
engine fetches pool block `tables[c, m]` while compute runs, and no
gathered copy ever exists in HBM. Per tile: one [H, P] score panel,
online-softmax statistics (running max / sum / accumulator in f32 VMEM
scratch, exactly the flash-attention discipline of
`ops/pallas/flash.py`), masked by `pad <= kv_pos < length` BEFORE the
max so scratch-block garbage (block 0, and table tails past a slot's
length) contributes exactly zero. Tiles entirely past `length` are
skipped (predicated body). GQA reads KV heads in place via the
`h // (H // Hkv)` head map — no repeat, no extra traffic.

Inference-only: decode has no backward, so there is no VJP — the
XLA reference path with identical semantics lives in
`ops.attention.paged_attention_reference`, and dispatch follows the
flash discipline (`ops.dispatch.use_pallas`, interpret mode off-TPU,
`ops.attention.paged_attention_uses_pallas` as the single predicate).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_lightning_tpu.ops.dispatch import interpret_mode as _interpret

_NEG_INF = -1e30  # never true -inf: exp(-inf - -inf) = nan on empty rows


def paged_shapes_supported(q_shape, pool_shape) -> bool:
    """Would the kernel accept these shapes on a real TPU?

    q [C, H, hd], pool [n_blocks, P, Hkv, hd]: the head dim must be
    lane-aligned (128, or 64 which still tiles acceptably — same rule
    as flash), the pool block must be sublane-aligned (P % 8), and the
    GQA ratio must be whole. Callers that must know the dispatch
    outcome use `ops.attention.paged_attention_uses_pallas`, never this
    directly — one predicate, no drift."""
    if len(q_shape) != 3 or len(pool_shape) != 4:
        return False
    _, h, hd = q_shape
    _, p, hkv, hd2 = pool_shape
    if hd != hd2:
        return False
    if hd % 128 != 0 and hd not in (64,):
        return False
    if hkv < 1 or h % hkv != 0:
        return False
    if p % 8 != 0:
        return False
    return True


def _decode_kernel(tbl_ref, len_ref, pad_ref, q_ref, k_ref, v_ref, o_ref,
                   acc, m_scr, l_scr, *, scale, block_p, num_kv_blocks,
                   n_rep):
    """One (slot, kv-tile) grid step. Scratch persists across the
    innermost tile axis (the flash forward's accumulation contract)."""
    c = pl.program_id(0)
    m = pl.program_id(1)

    @pl.when(m == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    length = len_ref[c]
    kv_start = m * block_p

    # tiles entirely past the slot's length (or entirely under its
    # left pad) hold nothing visible — skip the DMA'd tile's compute
    # (its garbage never reaches the stats)
    @pl.when((kv_start < length) & (kv_start + block_p > pad_ref[c]))
    def _body():
        q = q_ref[0].astype(jnp.float32)       # [H, hd]
        k = k_ref[0].astype(jnp.float32)       # [P, Hkv, hd]
        v = v_ref[0].astype(jnp.float32)
        h, hd = q.shape
        hkv = k.shape[1]
        # GQA head map: query head g*n_rep + r reads kv head g — group
        # the q heads and batch the contraction over kv heads, so KV
        # tiles are consumed in place (no repeat)
        qg = q.reshape(hkv, n_rep, hd)
        kg = k.transpose(1, 0, 2)              # [Hkv, P, hd]
        vg = v.transpose(1, 0, 2)
        s = jax.lax.dot_general(
            qg, kg, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale                              # [Hkv, n_rep, P]
        kv_pos = kv_start + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 2)
        visible = (kv_pos < length) & (kv_pos >= pad_ref[c])
        s = jnp.where(visible, s, _NEG_INF).reshape(h, block_p)
        m_prev = m_scr[:, 0]                   # [H]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        # masked positions are zeroed EXPLICITLY, not only through the
        # exp: a fully-masked tile (every position below the slot's
        # pad) has s == m_new == _NEG_INF and exp(s - m_new) == 1 —
        # the sentinel-minus-sentinel trap would weight garbage at
        # full probability
        p = jnp.where(visible.reshape(h, block_p),
                      jnp.exp(s - m_new[:, None]), 0.0)  # [H, P]
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, 0] = corr * l_scr[:, 0] + jnp.sum(p, axis=1)
        av = jax.lax.dot_general(
            p.reshape(hkv, n_rep, block_p), vg,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                      # [Hkv, n_rep, hd]
        acc[:] = corr[:, None] * acc[:] + av.reshape(h, hd)
        m_scr[:, 0] = m_new

    @pl.when(m == num_kv_blocks - 1)
    def _finish():
        l = l_scr[:, 0]
        safe_l = jnp.where(l == 0.0, 1.0, l)   # fully-masked slot -> 0s
        o_ref[0] = (acc[:] / safe_l[:, None]).astype(o_ref.dtype)


def paged_attention_pallas(
    q: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    tables: jnp.ndarray,
    lengths: jnp.ndarray,
    pad: jnp.ndarray | None = None,
    scale: float | None = None,
) -> jnp.ndarray:
    """Decode attention over the paged pool: [C, H, hd] out.

    ``tables`` names each slot's pool blocks (block 0 = reserved
    scratch — readable garbage, always masked by ``lengths``/``pad``);
    ``lengths[c]`` is the number of valid cache positions (including
    the just-written query token); ``pad[c]`` masks a left-padded
    slot's pad columns (positions < pad never attend)."""
    c, h, hd = q.shape
    n_blocks, p, hkv, _ = pool_k.shape
    m = tables.shape[1]
    n_rep = h // hkv
    scale = scale if scale is not None else hd ** -0.5
    if pad is None:
        pad = jnp.zeros_like(lengths)
    kernel = functools.partial(
        _decode_kernel, scale=scale, block_p=p, num_kv_blocks=m,
        n_rep=n_rep)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # tables, lengths, pad
        grid=(c, m),
        in_specs=[
            pl.BlockSpec((1, h, hd),
                         lambda ci, mi, tbl, ln, pd: (ci, 0, 0)),
            # the paged trick: the KV tile for (slot, m) is whichever
            # pool block the scalar-prefetched table names — the tile
            # streams HBM -> VMEM with no intermediate gathered copy
            pl.BlockSpec((1, p, hkv, hd),
                         lambda ci, mi, tbl, ln, pd:
                         (tbl[ci, mi], 0, 0, 0)),
            pl.BlockSpec((1, p, hkv, hd),
                         lambda ci, mi, tbl, ln, pd:
                         (tbl[ci, mi], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, hd),
                               lambda ci, mi, tbl, ln, pd: (ci, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, hd), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((c, h, hd), q.dtype),
        interpret=_interpret(),
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32),
      pad.astype(jnp.int32), q, pool_k, pool_v)
