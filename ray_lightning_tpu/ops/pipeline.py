"""Pipeline parallelism: a GPipe-style microbatch pipeline over the mesh's
`pipe` axis.

SURVEY §2.3 lists pipeline parallelism as absent from the reference and
out of its scope; this is a beyond-parity building block, designed the
TPU way: no schedulers, no per-stage processes — ONE compiled SPMD
program in which every `pipe`-axis device holds a contiguous block of
layers and microbatch activations flow stage→stage over ICI
`ppermute`s inside a `lax.scan` (the "pipelined scan" pattern).

Schedule (GPipe, fill-and-drain): with P stages and M microbatches the
scan runs T = M + P - 1 steps; at step t stage p computes microbatch
t - p (when in range), so utilization is M / (M + P - 1) — choose
M >> P. Backward is ordinary jax AD through the scan: ppermute
transposes to the reverse permute, reproducing the reverse-order
pipeline without any hand-written schedule. Per-stage activation
stash is the usual GPipe O(M) — wrap ``stage_fn`` cost down with
``remat=True``.

Composes with the other axes: batch stays sharded on data/fsdp axes,
tensor/seq manual islands keep working inside ``stage_fn`` — the
shard_map here is manual over every mesh axis (like ops/ring_attention's
islands), with batch dims passed through per-shard.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_lightning_tpu.parallel.mesh import dp_axis_names


def pipeline_perm(pipe: int) -> list[tuple[int, int]]:
    """The GPipe stage-to-stage schedule: an OPEN chain (stage i sends to
    i+1, no wrap-around hop — stage 0 never reads its recv, so the
    longest link would carry dead payload; ppermute zero-fills unlisted
    destinations). Schedule metadata for tracecheck (RLT303): a partial
    permutation is legal precisely when, like this one, it has no
    duplicate sources or destinations."""
    return [(i, i + 1) for i in range(pipe - 1)]


def gpipe_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stacked_params: Any,
    x: jnp.ndarray,
    mesh: Mesh,
    *,
    microbatches: int,
    axis_name: str = "pipe",
    remat: bool = False,
    remat_policy: Optional[Callable] = None,
    extra: tuple = (),
) -> jnp.ndarray:
    """Apply L stacked layers to ``x``, stage-split over ``axis_name``.

    stage_fn(layer_params, h, *extra) -> h : ONE layer's forward; its
        ``layer_params`` is one leading-axis slice of ``stacked_params``.
    stacked_params : pytree whose leaves have leading dim L (the scanned
        layer stack — the same layout `nn.scan` produces), L % P == 0.
        Each stage owns a contiguous [L/P] block (sharded on `pipe`).
    x : [B, ...] global activations; B % microbatches == 0 per shard.
    extra : broadcast operands passed to every stage_fn call (e.g. rope
        tables) — replicated over the pipe axis.

    Returns ``x`` after all L layers (same shape/sharding as input).
    With pipe size 1 this degrades to a plain layer scan.

    Composition caveat: "composes with data/fsdp" means the BATCH axis —
    activations stay dp/fsdp-sharded. Parameters do NOT: each stage's
    in_spec shards only the layer axis on `pipe` and replicates every
    other param dim, so combining pipe>1 with fsdp>1 all-gathers each
    stage's full layer block inside the shard_map for the duration of
    the step (GPipe owns whole layers by design). For memory-bound
    models prefer fsdp WITHOUT pipe, or accept per-stage unsharded
    weights as the pipeline's cost.
    """
    pipe = mesh.shape.get(axis_name, 1)
    body = (jax.checkpoint(stage_fn, policy=remat_policy) if remat
            else stage_fn)

    if pipe <= 1:
        def seq_body(h, lp):
            return body(lp, h, *extra), None

        return jax.lax.scan(seq_body, x, stacked_params)[0]

    leaves = jax.tree.leaves(stacked_params)
    L = leaves[0].shape[0]
    if L % pipe:
        raise ValueError(f"{L} layers not divisible by pipe={pipe}")
    M = microbatches

    # same batch-axis vocabulary as the Trainer's batch sharding — ONE
    # source of truth for which axes carry the batch
    x_spec = P(dp_axis_names(mesh), *([None] * (x.ndim - 1)))
    param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
    extra_specs = tuple(jax.tree.map(lambda _: P(), e) for e in extra)

    def local(params_local, x_local, *extra_local):
        # params_local leaves: [L/P, ...] — this stage's layer block
        p_idx = jax.lax.axis_index(axis_name)
        B = x_local.shape[0]
        if B % M:
            raise ValueError(
                f"per-shard batch {B} not divisible by microbatches={M}"
            )
        mbs = x_local.reshape((M, B // M) + x_local.shape[1:])

        def stage(h):
            def layer(h, lp):
                return body(lp, h, *extra_local), None

            return jax.lax.scan(layer, h, params_local)[0]

        def step(carry, t):
            recv, out = carry
            # stage 0 feeds from the microbatch queue; later stages from
            # the activation received last step (clamped index: steps
            # past the queue re-feed the last microbatch, results unused)
            feed = jax.lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
            )
            h = jnp.where(p_idx == 0, feed, recv)
            y = stage(h)
            # open chain, not a ring: stage 0 never reads its recv, so the
            # wrap-around hop (the longest link) would carry dead payload;
            # ppermute zero-fills unlisted destinations
            recv_next = jax.lax.ppermute(y, axis_name, pipeline_perm(pipe))
            # the LAST stage emits microbatch t-(P-1)'s final activation
            out_idx = t - (pipe - 1)
            idx = jnp.clip(out_idx, 0, M - 1)
            valid = (p_idx == pipe - 1) & (out_idx >= 0)
            cur = jax.lax.dynamic_index_in_dim(out, idx, 0, keepdims=False)
            out = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(valid, y, cur), idx, 0
            )
            return (recv_next, out), None

        out0 = jnp.zeros_like(mbs)
        (_, out), _ = jax.lax.scan(
            step, (jnp.zeros_like(mbs[0]), out0), jnp.arange(M + pipe - 1)
        )
        # only the last stage holds real outputs; replicate over the pipe
        out = jax.lax.psum(
            jnp.where(p_idx == pipe - 1, out, jnp.zeros_like(out)),
            axis_name,
        )
        return out.reshape(x_local.shape)

    from ray_lightning_tpu.ops.dispatch import shard_map

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs, x_spec) + extra_specs,
        out_specs=x_spec,
        check_replication=False,  # mixes pipe-varying and replicated
    )(stacked_params, x, *extra)


def pipeline_param_spec(inner: Optional[P] = None,
                        axis_name: str = "pipe") -> P:
    """PartitionSpec for a layer-stacked parameter under pipeline
    parallelism: leading (layer) axis on `pipe`, then the given per-layer
    spec. Modules put this in param_specs() for their stacked blocks."""
    inner = inner or P()
    return P(axis_name, *inner)
