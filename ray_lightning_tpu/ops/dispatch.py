"""Single home for the kernel-dispatch policy.

All ops decide "pallas TPU kernel vs XLA reference path" the same way; a
future backend (or a forced-interpret env knob) changes here only.
"""
from __future__ import annotations

import contextlib
import contextvars
import os

import jax

#: context-scoped dispatch override (see `force_xla`): unlike the env
#: knob this never leaks across threads/tasks in the same process.
_forced: contextvars.ContextVar[bool | None] = contextvars.ContextVar(
    "rlt_pallas_forced", default=None
)


@contextlib.contextmanager
def force_xla():
    """Pin dispatch to the XLA reference path for the current context.

    For trace-only consumers (the pre-flight planner): the pallas
    decision path queries `jax.default_backend()`, which would
    INITIALIZE a backend — and kernel choice cannot change shapes, so an
    abstract trace loses nothing by skipping it. A contextvar, not an
    env write: concurrent traces in other threads keep their kernels.
    """
    token = _forced.set(False)
    try:
        yield
    finally:
        _forced.reset(token)


@contextlib.contextmanager
def force_pallas():
    """Pin dispatch to the pallas kernel path for the current context.

    The mirror image of `force_xla`, for tracecheck
    (analysis/tracecheck.py): a CPU-host audit of a TPU step must trace
    the program the TPU will actually run — with the flash kernel, the
    giant [S, S] score matrix of the XLA reference path never exists, so
    auditing the reference path would report an HBM peak the production
    step does not have. Like force_xla this short-circuits the backend
    probe, so no backend is ever initialized at trace time."""
    token = _forced.set(True)
    try:
        yield
    finally:
        _forced.reset(token)


def on_tpu() -> bool:
    """True when the default backend is a real TPU."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # backend init failure → reference path
        return False


def interpret_mode() -> bool:
    """Pallas kernels run in interpret mode everywhere but TPU (so tests
    exercise kernel logic on the CPU mesh)."""
    return not on_tpu()


def shard_map(f, mesh, in_specs, out_specs, *,
              check_replication: bool = True):
    """Version-portable shard_map: `jax.shard_map` (current jax, where
    the replication-check kwarg is `check_vma`) with a fallback to
    `jax.experimental.shard_map.shard_map` (jax <= 0.4.x, `check_rep`).
    The manual islands (ring/ulysses attention, the GPipe pipeline) go
    through here so a jax upgrade/downgrade is one-file work — the same
    contract as `parallel.plan.abstract_mesh`."""
    if hasattr(jax, "shard_map"):
        import inspect

        params = inspect.signature(jax.shard_map).parameters
        kw = "check_vma" if "check_vma" in params else "check_rep"
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs,
                             **{kw: check_replication})
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_replication)


# ---- collective-overlap shim (models/llama.py double-buffered FSDP) ------

#: the checkpoint_name tag the double-buffered weight-gather prefetch
#: stamps on every prefetched leaf (models/llama.py _overlapped_hidden).
#: tracecheck (analysis/tracecheck.py) keys its hidden-vs-exposed
#: overlap classification on this exact string: the `name` equations it
#: produces are the static fingerprint that the traced program runs the
#: overlap schedule (same fingerprinting technique as the flash kernel's
#: "flash_residuals" tag).
OVERLAP_PREFETCH_NAME = "rlt_overlap_prefetch"


def prefetch_named(tree):
    """Stamp every leaf of a prefetched weight tree with the overlap
    marker (`checkpoint_name`). Inert at runtime (an identity `name`
    equation no remat policy in this repo matches); load-bearing for the
    static audit."""
    from jax.ad_checkpoint import checkpoint_name

    return jax.tree.map(
        lambda t: checkpoint_name(t, OVERLAP_PREFETCH_NAME), tree)


@jax.custom_vjp
def overlap_barrier(trees):
    """Differentiable, version-portable `lax.optimization_barrier`.

    The double-buffered schedule must pin "issue layer i+1's weight
    gather BEFORE layer i's compute consumes x" — without a data
    dependence XLA's scheduler is free to sink the gather to its use and
    re-expose the latency. `optimization_barrier` provides the ordering
    but (as of jax 0.4.x) has no differentiation rule, so this wraps it
    in a custom_vjp: barrier applied in the forward, cotangents passed
    straight through (the backward scan builds its own schedule from the
    transposed collectives). On jax builds without the primitive the
    barrier degrades to identity — the schedule is then merely advisory,
    never wrong."""
    return _barrier(trees)


def _barrier(trees):
    fn = getattr(jax.lax, "optimization_barrier", None)
    return fn(trees) if fn is not None else trees


def _overlap_barrier_fwd(trees):
    return _barrier(trees), None


def _overlap_barrier_bwd(_, g):
    return (g,)


overlap_barrier.defvjp(_overlap_barrier_fwd, _overlap_barrier_bwd)


@jax.custom_vjp
def fusion_fence(trees):
    """Symmetric fusion fence: `optimization_barrier` on the value in
    forward AND on its cotangent in backward.

    XLA fuses a subgraph differently depending on the program AROUND
    it, and fusion reassociates bf16/f32 reductions — so the same layer
    block surrounded by two different (value-identical) gather
    schedules can produce different bits (measured: 1-2 bf16 ulp per
    layer at small shapes). The overlap path (models/llama.py) fences
    the block region so it is an identical compilation unit under the
    prefetched and serial schedules — the bitwise-parity guarantee
    rests on it."""
    return _barrier(trees)


def _fence_fwd(trees):
    return _barrier(trees), None


def _fence_bwd(_, g):
    return (_barrier(g),)


fusion_fence.defvjp(_fence_fwd, _fence_bwd)


def use_pallas(override: bool | None = None,
               default: bool | None = None) -> bool:
    """Dispatch decision: explicit argument > force_xla context >
    RLT_PALLAS env > ``default`` (ops whose policy is not
    backend-derived, e.g. rms_norm's off-by-default — also skips the
    backend probe entirely) > backend."""
    if override is not None:
        return override
    forced = _forced.get()
    if forced is not None:
        return forced
    env = os.environ.get("RLT_PALLAS")
    if env is not None:
        return env == "1"
    if default is not None:
        return default
    return on_tpu()
