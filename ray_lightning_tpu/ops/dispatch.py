"""Single home for the kernel-dispatch policy.

All ops decide "pallas TPU kernel vs XLA reference path" the same way; a
future backend (or a forced-interpret env knob) changes here only.
"""
from __future__ import annotations

import os

import jax


def on_tpu() -> bool:
    """True when the default backend is a real TPU."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # backend init failure → reference path
        return False


def interpret_mode() -> bool:
    """Pallas kernels run in interpret mode everywhere but TPU (so tests
    exercise kernel logic on the CPU mesh)."""
    return not on_tpu()


def use_pallas(override: bool | None = None) -> bool:
    """Dispatch decision: explicit argument > RLT_PALLAS env > backend."""
    if override is not None:
        return override
    env = os.environ.get("RLT_PALLAS")
    if env is not None:
        return env == "1"
    return on_tpu()
