"""Normalization ops: RMSNorm reference implementation.

`rms_norm` here is the jnp reference; `ray_lightning_tpu.ops.pallas.rmsnorm`
provides the fused TPU kernel and `rms_norm(..., use_pallas=True)` (or the
RLT_PALLAS=1 env var) selects it. The reduction is done in float32 even for
bf16 activations — matches Llama reference numerics.
"""
from __future__ import annotations

import jax.numpy as jnp


def rms_norm(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    eps: float = 1e-5,
    use_pallas: bool | None = None,
) -> jnp.ndarray:
    """y = x / rms(x) * weight, reducing over the last axis in f32."""
    if use_pallas is None:
        from ray_lightning_tpu.ops import dispatch

        # one dispatch policy for all ops (dispatch.py) — this op's only
        # deviation is its default: OFF unless RLT_PALLAS=1 (default=False
        # also skips the backend probe, which trace-only force_xla()
        # contexts must never reach)
        use_pallas = dispatch.use_pallas(default=False)
    if use_pallas:
        from ray_lightning_tpu.ops.pallas.rmsnorm import rms_norm_pallas

        return rms_norm_pallas(x, weight, eps=eps)
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jnp.reciprocal(jnp.sqrt(var + eps))
    return (y * weight.astype(jnp.float32)).astype(x.dtype)
