"""Normalization ops: RMSNorm reference implementation.

`rms_norm` here is the jnp reference; `ray_lightning_tpu.ops.pallas.rmsnorm`
provides the fused TPU kernel and `rms_norm(..., use_pallas=True)` (or the
RLT_PALLAS=1 env var) selects it. The reduction is done in float32 even for
bf16 activations — matches Llama reference numerics.
"""
from __future__ import annotations

import os

import jax.numpy as jnp


def rms_norm(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    eps: float = 1e-5,
    use_pallas: bool | None = None,
) -> jnp.ndarray:
    """y = x / rms(x) * weight, reducing over the last axis in f32."""
    if use_pallas is None:
        from ray_lightning_tpu.ops.dispatch import forced_choice

        # honor force_xla() (trace-only contexts must not reach the
        # kernel path, whose interpret_mode probe touches the backend);
        # otherwise this op defaults OFF unless RLT_PALLAS=1
        forced = forced_choice()
        use_pallas = (forced if forced is not None
                      else os.environ.get("RLT_PALLAS", "0") == "1")
    if use_pallas:
        from ray_lightning_tpu.ops.pallas.rmsnorm import rms_norm_pallas

        return rms_norm_pallas(x, weight, eps=eps)
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jnp.reciprocal(jnp.sqrt(var + eps))
    return (y * weight.astype(jnp.float32)).astype(x.dtype)
