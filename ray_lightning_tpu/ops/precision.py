"""Mixed-precision building blocks: f32-accumulating dots and denses.

The single-rounding contract (numcheck RLT801's sanctioned shape):
matmul OPERANDS stay narrow (bf16 hits the MXU at full rate), the
ACCUMULATOR is pinned to f32 with ``preferred_element_type``, and the
result is rounded at most ONCE — after the full contraction, never
inside it. The MXU accumulates a bf16 dot in f32 internally either
way, so on TPU this costs nothing; pinning it makes the contract
explicit in the jaxpr (auditable by analysis/numcheck.py) and widens
the backward dgrad/wgrad dots to f32, so gradient reduce-scatters
ride the wire at f32 instead of bf16 (RLT804). On CPU the rounded
variant is bitwise identical to the plain narrow dot.

Three shapes of the same contract:

  * `f32_acc_dot_general` — drop-in ``nn.Dense(dot_general=...)``:
    f32 accumulator, output rounded once back to the operand dtype.
  * `f32_out_dot_general` — the vocab-projection variant: the output
    KEEPS the f32 accumulator (logits head straight into f32
    loss/sampling math, so rounding first would only discard the low
    bits the softmax normalization runs on).
  * `F32AccDense` — a biased dense that also adds the bias at f32
    before the single rounding, so the backward bias gradient (a
    token-extent reduce_sum) accumulates in f32 too — the part
    ``nn.Dense(dot_general=f32_acc_dot_general)`` cannot reach,
    because flax rounds the dot output before its bias add. Param
    names/shapes/initializers match ``nn.Dense`` exactly (kernel,
    bias), so PartitionSpecs and checkpoint mappings are unchanged.
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["f32_acc_dot_general", "f32_out_dot_general", "F32AccDense"]


def f32_acc_dot_general(lhs, rhs, dimension_numbers, precision=None,
                        preferred_element_type=None):
    """`nn.Dense` dot_general that accumulates in f32 and rounds ONCE
    at the output — see the module docstring for the full contract."""
    del preferred_element_type
    out = jax.lax.dot_general(lhs, rhs, dimension_numbers,
                              precision=precision,
                              preferred_element_type=jnp.float32)
    return out.astype(jnp.result_type(lhs, rhs))


def f32_out_dot_general(lhs, rhs, dimension_numbers, precision=None,
                        preferred_element_type=None):
    """The vocab-projection variant of `f32_acc_dot_general`: bf16
    operands (full MXU rate), f32 accumulator, and the output KEEPS
    the f32 accumulator for downstream f32 loss/sampling math."""
    del preferred_element_type
    return jax.lax.dot_general(lhs, rhs, dimension_numbers,
                               precision=precision,
                               preferred_element_type=jnp.float32)


class F32AccDense(nn.Module):
    """``nn.Dense`` with the whole pre-activation kept at f32: narrow
    operands, f32 dot accumulator, f32 bias add, ONE rounding at the
    end. At ``dtype=float32`` this is bitwise ``nn.Dense``."""

    features: int
    dtype: Any = jnp.bfloat16
    use_bias: bool = True

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (x.shape[-1], self.features), jnp.float32)
        y = jax.lax.dot_general(
            x.astype(self.dtype), kernel.astype(self.dtype),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros_init(),
                              (self.features,), jnp.float32)
            y = y + bias
        return y.astype(self.dtype)
