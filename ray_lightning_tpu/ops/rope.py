"""Rotary position embeddings (RoPE), Llama-3 style.

Pure jnp: XLA fuses the sin/cos + elementwise rotate into surrounding ops;
a hand kernel buys nothing here (HBM-bound elementwise work that already
fuses into the attention projections).
"""
from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(
    head_dim: int,
    max_seq_len: int,
    theta: float = 500000.0,
    dtype=jnp.float32,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Precompute (cos, sin) tables of shape [max_seq_len, head_dim//2].

    theta=500000 is the Llama-3 base (10000 is the classic RoPE base).
    """
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [S, D/2]
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(
    x: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    positions: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Rotate query/key tensor x: [..., S, H, D] with tables [S_max, D/2].

    `positions` ([..., S] int) selects rows of the tables; defaults to
    arange(S) (i.e. sequence-start at 0 — pass explicit positions for
    sequence-parallel shards or KV-cache decoding).
    """
    seq_len = x.shape[-3]
    if positions is None:
        c = cos[:seq_len]  # [S, D/2]
        s = sin[:seq_len]
    else:
        c = cos[positions]  # [..., S, D/2]
        s = sin[positions]
    # broadcast over the head axis: [..., S, 1, D/2]
    c = c[..., :, None, :]
    s = s[..., :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    rotated = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return rotated.astype(x.dtype)
