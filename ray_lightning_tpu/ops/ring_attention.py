"""Ring attention: sequence/context parallelism over the `seq` mesh axis.

Long-context machinery the reference lacks entirely (SURVEY §5.7: no
attention code at all in the reference — this is net-new capability that
the TPU rebuild treats as first-class). Design is TPU-idiomatic:

  * the sequence axis is sharded over the `seq` mesh axis; each device
    holds [B, S/n, H, D] of Q, K, V;
  * attention runs in n ring steps: every device computes blockwise
    attention of its local Q against the KV block it currently holds
    (online-softmax accumulation, flash-attention style — the S×S score
    matrix never materializes), then rotates the KV block to its ring
    neighbor with `lax.ppermute` — nearest-neighbor traffic that maps
    onto the physical ICI torus;
  * causality uses global offsets from `lax.axis_index`, so blocks
    entirely in a query's future contribute exp(-inf)=0 and the math
    stays exact (results match full attention to float tolerance);
  * compute is fully overlappable with the permute by XLA's async
    collective scheduling (the next block's matmul does not depend on
    the in-flight send).

Two entry points:
  * `ring_attention(q, k, v, mesh=...)` — standalone: wraps `shard_map`
    over the mesh (the usual "manual island inside an auto-sharded jit"
    pattern).
  * `ring_attention_local(...)` — the per-shard body, for callers already
    inside a `shard_map` of their own.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_lightning_tpu.ops.attention import repeat_kv

_NEG_INF = float("-inf")


def ring_perm(axis_size: int) -> list[tuple[int, int]]:
    """The canonical ring schedule: one single-cycle rotation, every rank
    sends to its +1 neighbor. This is schedule METADATA as much as
    implementation — tracecheck (analysis/tracecheck.py RLT303) validates
    every traced ppermute against exactly the properties this shape
    guarantees (no duplicate src/dst, full permutations form ONE cycle),
    so the ring path and the auditor cannot drift apart."""
    return [(j, (j + 1) % axis_size) for j in range(axis_size)]


def _accum_block(q, k, v, o, m, l, *, q_off, kv_off, causal, scale):
    """One online-softmax update of (o, m, l) with a KV block.

    q: [B, Sq, H, D]; k, v: [B, Skv, Hkv, D] (GQA-repeated here so the
    ring only ever ships the small KV). o: [B, H, Sq, D] f32 accumulator;
    m, l: [B, H, Sq] running max / denominator, f32.
    """
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = repeat_kv(k, rep)
        v = repeat_kv(v, rep)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        q_pos = jnp.arange(q.shape[1])[:, None] + q_off
        kv_pos = jnp.arange(k.shape[1])[None, :] + kv_off
        s = jnp.where(q_pos >= kv_pos, s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # rows with nothing visible yet keep m=-inf; exp against a 0 stand-in
    # still yields exactly 0 contributions.
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - safe_m[..., None])                     # [B,H,Sq,Skv]
    alpha = jnp.where(
        jnp.isfinite(m), jnp.exp(m - safe_m), 0.0
    )                                                      # [B,H,Sq]
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return o_new, m_new, l_new


def ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str = "seq",
    axis_size: int,
    causal: bool = True,
    scale: Optional[float] = None,
):
    """Per-shard ring attention body (call inside shard_map).

    q, k, v: local shards [B, S_local, H(,kv), D]. Returns [B, S_local,
    H, D] in q's dtype.
    """
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    scale = scale if scale is not None else D ** -0.5
    idx = jax.lax.axis_index(axis_name)
    q_off = idx * Sq

    perm = ring_perm(axis_size)

    def body(t, carry):
        o, m, l, kb, vb = carry
        src = (idx - t) % axis_size          # original owner of (kb, vb)
        o, m, l = _accum_block(
            q, kb, vb, o, m, l,
            q_off=q_off, kv_off=src * Skv, causal=causal, scale=scale,
        )
        # rotate AFTER consuming: block t+1 arrives from the ring neighbor
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (o, m, l, kb, vb)

    o0 = jnp.zeros((B, H, Sq, D), jnp.float32)
    m0 = jnp.full((B, H, Sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Sq), jnp.float32)
    # n-1 rotations suffice: the last block is consumed without a send
    # (a final ppermute whose output nobody reads would still serialize
    # the loop on ICI traffic).
    o, m, l, kb, vb = jax.lax.fori_loop(
        0, axis_size - 1, body, (o0, m0, l0, k, v)
    )
    src_last = (idx - (axis_size - 1)) % axis_size
    o, _, l = _accum_block(
        q, kb, vb, o, m, l,
        q_off=q_off, kv_off=src_last * Skv, causal=causal, scale=scale,
    )
    out = jnp.where(l[..., None] > 0, o / jnp.maximum(l[..., None], 1e-30), 0.0)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)      # [B, Sq, H, D]


def seq_island(local_fn, mesh: Mesh, axis_name: str = "seq", **kwargs):
    """Shared shard_map wrapper for sequence-parallel attention islands
    ([B, S, H, D] tensors: batch over the data axes, sequence over
    `axis_name`, heads over `tensor`). Used by both the ring and the
    ulysses (ops/ulysses.py) modes so they cannot disagree on layout."""
    bspec = tuple(ax for ax in ("data", "fsdp", "expert")
                  if ax in mesh.shape)
    head_ax = "tensor" if "tensor" in mesh.shape else None
    spec = P(bspec if bspec else None, axis_name, head_ax, None)
    from ray_lightning_tpu.ops.dispatch import shard_map

    return shard_map(
        partial(local_fn, axis_name=axis_name,
                axis_size=mesh.shape[axis_name], **kwargs),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_replication=False,  # collective-permute varying-axes opt-out
    )


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    axis_name: str = "seq",
    causal: bool = True,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Sequence-parallel attention over `mesh`'s `axis_name` axis.

    Global [B, S, H, D] in/out; batch rides the data-parallel axes, heads
    ride `tensor`, sequence is split over `axis_name`. With axis size 1
    this degrades to plain blockwise attention on every device.
    """
    fn = seq_island(ring_attention_local, mesh, axis_name,
                    causal=causal, scale=scale)
    return fn(q, k, v)
