"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The second sequence-parallel scheme (DeepSpeed-Ulysses pattern),
complementing ring attention (ops/ring_attention.py):

  * ring: KV blocks rotate the `seq` ring; n-1 nearest-neighbor
    `ppermute`s; attention stays blockwise-local. Best at very long S
    (activation memory O(S/n)) and on torus topologies.
  * ulysses: ONE `all_to_all` converts the layout from sequence-sharded
    [B, S/n, H, D] to head-sharded [B, S, H/n, D], each device runs
    plain (flash) attention over the FULL sequence for its head group,
    and a second all_to_all restores the sequence sharding. Two
    collectives total regardless of n — cheaper than the ring when the
    full-S working set still fits one device and H % n == 0.

Both compose with the same mesh axes; the Llama family picks via
`LlamaConfig.seq_parallel_mode`.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_lightning_tpu.ops.attention import flash_attention
from ray_lightning_tpu.ops.ring_attention import seq_island


def ulysses_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str = "seq",
    axis_size: int,
    causal: bool = True,
    use_pallas: Optional[bool] = None,
):
    """Per-shard body (inside shard_map): q, k, v are [B, S/n, H(,kv), D]."""
    if axis_size == 1:
        return flash_attention(q, k, v, causal=causal, use_pallas=use_pallas)

    def to_heads(x):
        # [B, S/n, H, D] -> all_to_all over the head axis -> [B, S, H/n, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def to_seq(x):
        # inverse: [B, S, H/n, D] -> [B, S/n, H, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = flash_attention(qh, kh, vh, causal=causal, use_pallas=use_pallas)
    return to_seq(out)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    axis_name: str = "seq",
    causal: bool = True,
    use_pallas: Optional[bool] = None,
) -> jnp.ndarray:
    """Sequence-parallel attention via head/sequence all-to-all.

    Global [B, S, H, D] in/out, sequence split over `axis_name`.
    Requires H (and the KV head count) divisible by the axis size.
    """
    n = mesh.shape[axis_name]
    # heads are already split over `tensor` inside the island — the
    # all_to_all redistributes the LOCAL head count
    t = mesh.shape.get("tensor", 1)
    h_local, hkv_local = q.shape[2] // t, k.shape[2] // t
    if h_local % n != 0 or hkv_local % n != 0:
        raise ValueError(
            f"ulysses needs per-shard heads divisible by the seq axis: "
            f"H/tensor={h_local}, Hkv/tensor={hkv_local}, seq={n} — use "
            "ring attention for this shape"
        )
    fn = seq_island(ulysses_attention_local, mesh, axis_name,
                    causal=causal, use_pallas=use_pallas)
    return fn(q, k, v)
