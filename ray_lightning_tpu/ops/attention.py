"""Attention ops: masked SDPA reference + flash-attention dispatch +
paged decode attention for the serving engine.

The hot op of the flagship model. Three tiers:
  1. `dot_product_attention` — pure jnp reference (materializes the S×S
     score matrix); correct everywhere, used for tests and tiny shapes.
  2. `flash_attention` — tiled online-softmax kernel
     (ray_lightning_tpu.ops.pallas.flash) that never materializes scores;
     O(S) memory, MXU-shaped tiles. Falls back to (1) off-TPU or for
     shapes that don't tile.
  3. `paged_attention` — single-token decode attention consuming the
     serving engine's block-paged KV pool through per-slot block tables
     (ray_lightning_tpu.ops.pallas.paged_attention); the XLA reference
     path gathers a dense per-slot view first (identical semantics —
     that copy is exactly what the kernel retires, docs/SERVING.md).
  4. `paged_prefill` — the chunked causal twin for the serving
     engine's prefill lane (ray_lightning_tpu.ops.pallas.paged_prefill):
     a CH-token query chunk per group row against the same pool, which
     retires the prefill lane's per-group gathered view the same way.
(1)/(2) take [B, S, H, D] (batch, seq, heads, head_dim) and support GQA
by repeating KV heads (XLA turns the repeat into a broadcast, no HBM
copy); (3) takes one query token per slot, [C, H, D]; (4) takes the
group's chunk, [B, CH, H, D].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def make_causal_mask(q_len: int, kv_len: int, q_offset: int = 0) -> jnp.ndarray:
    """Boolean [q_len, kv_len] mask, True = attend. q_offset shifts the
    query positions (used by sequence-parallel shards / decoding)."""
    q_pos = jnp.arange(q_len)[:, None] + q_offset
    kv_pos = jnp.arange(kv_len)[None, :]
    return q_pos >= kv_pos


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[B, S, H_kv, D] -> [B, S, H_kv*n_rep, D] for GQA."""
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    x = jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, n_rep, d))
    return x.reshape(b, s, h * n_rep, d)


def dot_product_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    mask: jnp.ndarray | None = None,
    q_offset: int = 0,
    scale: float | None = None,
) -> jnp.ndarray:
    """Reference SDPA: [B, S, H, D] in, [B, S, H, D] out; f32 softmax."""
    if k.shape[2] != q.shape[2]:
        n_rep = q.shape[2] // k.shape[2]
        k = repeat_kv(k, n_rep)
        v = repeat_kv(v, n_rep)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    # [B, H, S, S]
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        cm = make_causal_mask(q.shape[1], k.shape[1], q_offset)
        scores = jnp.where(cm[None, None], scores, -jnp.inf)
    if mask is not None:
        # mask: [B, S_kv] padding mask or [B, 1, S_q, S_kv]
        if mask.ndim == 2:
            mask = mask[:, None, None, :]
        scores = jnp.where(mask, scores, -jnp.inf)
    # Rows with no visible key (fully-padded sequence, or padding ∩ causal
    # leaving nothing) would softmax over all -inf → NaN; emit zeros there.
    any_visible = jnp.isfinite(scores).any(axis=-1, keepdims=True)
    probs = jax.nn.softmax(
        jnp.where(any_visible, scores, 0.0), axis=-1
    ).astype(q.dtype)
    probs = jnp.where(any_visible, probs, 0.0).astype(q.dtype)
    # f32 accumulator over the S_kv extent (numcheck RLT801), one
    # rounding back to the compute dtype — matches the pallas kernel's
    # f32 VMEM accumulator, so the parity gap stays rounding-only
    return jnp.einsum(
        "bhqk,bkhd->bqhd", probs, v,
        preferred_element_type=jnp.float32).astype(q.dtype)


def flash_uses_pallas(q_shape, k_shape, use_pallas: bool | None = None,
                      masked: bool = False) -> bool:
    """Would `flash_attention` take the pallas kernel for these shapes
    and arguments? ONE predicate shared with the dispatch itself so
    callers that must know the outcome (the block-level remat annotation
    in models/llama.py: the pallas path's residuals are saved through the
    kernel's own `remat_opt` hoist, and naming its output again would
    double-save a [B, S, H·hd] tensor per layer) can never drift from
    what actually runs."""
    from ray_lightning_tpu.ops import dispatch

    if masked or not dispatch.use_pallas(use_pallas):
        return False
    from ray_lightning_tpu.ops.pallas.flash import shapes_supported

    return shapes_supported(q_shape, k_shape)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    mask: jnp.ndarray | None = None,
    q_offset: int = 0,
    use_pallas: bool | None = None,
) -> jnp.ndarray:
    """Tiled attention. Dispatches to the pallas TPU kernel when on TPU
    (or forced via RLT_PALLAS=1 with interpret mode on CPU) and the shape
    tiles cleanly; otherwise the XLA reference path (which XLA still fuses
    reasonably — flash matters at long S where the S×S scores don't fit)."""
    if flash_uses_pallas(q.shape, k.shape, use_pallas,
                         masked=mask is not None):
        from ray_lightning_tpu.ops.pallas.flash import flash_attention_pallas

        return flash_attention_pallas(q, k, v, causal=causal,
                                      q_offset=q_offset)
    return dot_product_attention(q, k, v, causal=causal, mask=mask,
                                 q_offset=q_offset)


# ---- paged decode attention (the serving engine's fused hot op) -----------


@jax.tree_util.register_pytree_node_class
class PagedDecodeView:
    """The decode lane's runtime view of the block-paged KV pool
    (serve/kv_cache.py layout; one entry per slot, all int32):

    ``tables [C, M]`` slot -> pool block ids (0 = reserved scratch);
    ``lengths [C]`` valid cache positions incl. the current token;
    ``write_block/write_offset [C]`` where THIS tick's K/V token lands
    (already scratch-redirected for slots not in the decode phase).

    ``use_pallas`` is STATIC pytree aux, not a leaf: it carries the
    serve engine's build-time dispatch decision through `Llama.apply`
    and the layer scan into `paged_attention`'s call site, so the
    compiled attention can never diverge from what
    `DecodeEngine.attention_path` reports (a trace-time backend
    re-probe could pick differently if, e.g., the jit traces after a
    `force_pallas` context has exited). None defers to the ambient
    dispatch policy."""

    def __init__(self, tables, lengths, write_block, write_offset,
                 use_pallas: bool | None = None):
        self.tables = tables
        self.lengths = lengths
        self.write_block = write_block
        self.write_offset = write_offset
        self.use_pallas = use_pallas

    def tree_flatten(self):
        return ((self.tables, self.lengths, self.write_block,
                 self.write_offset), self.use_pallas)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, use_pallas=aux)


def paged_attention_reference(
    q: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    tables: jnp.ndarray,
    lengths: jnp.ndarray,
    pad: jnp.ndarray | None = None,
    scale: float | None = None,
) -> jnp.ndarray:
    """XLA reference with the kernel's exact semantics: gather each
    slot's blocks into a dense [C, M*P, Hkv, hd] view (the copy the
    pallas kernel exists to retire), mask `pad <= kv_pos < length`, and
    run the shared masked-SDPA reference. Scratch-block garbage and
    table tails are masked to exact softmax zeros, so a longer table
    cannot perturb the visible reduction (the serving numerics
    contract, docs/SERVING.md)."""
    c, h, hd = q.shape
    _, p, hkv, _ = pool_k.shape
    m = tables.shape[1]
    k = pool_k[tables].reshape(c, m * p, hkv, hd)
    v = pool_v[tables].reshape(c, m * p, hkv, hd)
    kv_pos = jnp.arange(m * p)[None, :]
    mask = kv_pos < lengths[:, None]
    if pad is not None:
        mask = mask & (kv_pos >= pad[:, None])
    return dot_product_attention(q[:, None], k, v, causal=False,
                                 mask=mask, scale=scale)[:, 0]


@jax.tree_util.register_pytree_node_class
class PagedPrefillView:
    """The prefill lane's runtime view of the block-paged KV pool
    (serve/kv_cache.py layout; one entry per head-group row, all
    int32):

    ``tables [B, M]`` row -> pool block ids (0 = reserved scratch;
    vacant group rows carry an all-scratch table);
    ``write_block/write_offset [B, CH]`` where each of the chunk's CH
    K/V tokens lands (already scratch-redirected for vacant rows) —
    the chunk is scattered into OWNED pool blocks before attention
    runs (write-then-attend, the decode lane's ordering), so the dense
    per-group gathered view never exists on this path.

    ``use_pallas`` is STATIC pytree aux, not a leaf — the same
    baked-dispatch discipline as `PagedDecodeView`: it carries the
    serve engine's build-time decision through `Llama.apply` and the
    layer scan into `paged_prefill`'s call site, so the compiled
    attention can never diverge from what
    `DecodeEngine.prefill_path` reports. None defers to the ambient
    dispatch policy."""

    def __init__(self, tables, write_block, write_offset,
                 use_pallas: bool | None = None):
        self.tables = tables
        self.write_block = write_block
        self.write_offset = write_offset
        self.use_pallas = use_pallas

    def tree_flatten(self):
        return ((self.tables, self.write_block, self.write_offset),
                self.use_pallas)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, use_pallas=aux)


def paged_prefill_reference(
    q: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    tables: jnp.ndarray,
    pos,
    pad: jnp.ndarray | None = None,
    scale: float | None = None,
) -> jnp.ndarray:
    """XLA reference with the prefill kernel's exact semantics: gather
    each row's blocks into a dense [B, M*P, Hkv, hd] view (the copy the
    pallas kernel exists to retire), mask
    ``pad[b] <= kv_pos <= pos + j`` (causal against the chunk's cache
    positions), and run the shared masked-SDPA reference. Scratch-block
    garbage, table tails and future in-chunk positions are masked to
    exact softmax zeros; a fully-masked query row (a pad column) emits
    zeros (the serving numerics contract, docs/SERVING.md)."""
    b, ch, h, hd = q.shape
    _, p, hkv, _ = pool_k.shape
    m = tables.shape[1]
    k = pool_k[tables].reshape(b, m * p, hkv, hd)
    v = pool_v[tables].reshape(b, m * p, hkv, hd)
    kv_pos = jnp.arange(m * p)[None, None, :]
    q_pos = (pos + jnp.arange(ch))[None, :, None]
    mask = kv_pos <= q_pos
    if pad is not None:
        mask = mask & (kv_pos >= pad[:, None, None])
    else:
        mask = jnp.broadcast_to(mask, (b, ch, m * p))
    return dot_product_attention(q, k, v, causal=False,
                                 mask=mask[:, None], scale=scale)


def paged_prefill_uses_pallas(q_shape, pool_shape,
                              use_pallas: bool | None = None) -> bool:
    """Would `paged_prefill` take the pallas kernel for these shapes?
    ONE predicate shared with the dispatch itself (the
    `paged_attention_uses_pallas` discipline): the serving engine keys
    its fused-vs-reference PREFILL lane on this at build time, and the
    audit/plan legs (`serve/audit.py`) key the per-group gathered-view
    HBM charge on it — so what is charged can never drift from what
    runs."""
    from ray_lightning_tpu.ops import dispatch

    if not dispatch.use_pallas(use_pallas):
        return False
    from ray_lightning_tpu.ops.pallas.paged_prefill import (
        paged_prefill_shapes_supported,
    )

    return paged_prefill_shapes_supported(q_shape, pool_shape)


def paged_prefill(
    q: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    tables: jnp.ndarray,
    pos,
    pad: jnp.ndarray | None = None,
    scale: float | None = None,
    use_pallas: bool | None = None,
) -> jnp.ndarray:
    """Chunked causal prefill attention over the block-paged KV pool:
    q [B, CH, H, hd], pool [n_blocks, P, Hkv, hd], tables [B, M],
    pos scalar (chunk token j sits at cache position pos + j) ->
    [B, CH, H, hd]. Dispatches to the fused pallas kernel when on TPU
    (or forced, with interpret mode off-TPU) and the shapes tile;
    otherwise the gathering XLA reference path — identical semantics,
    but the dense per-group view is materialized (and charged by the
    serve planner)."""
    if paged_prefill_uses_pallas(q.shape, pool_k.shape, use_pallas):
        from ray_lightning_tpu.ops.pallas.paged_prefill import (
            paged_prefill_pallas,
        )

        return paged_prefill_pallas(q, pool_k, pool_v, tables, pos,
                                    pad=pad, scale=scale)
    return paged_prefill_reference(q, pool_k, pool_v, tables, pos,
                                   pad=pad, scale=scale)


def paged_attention_uses_pallas(q_shape, pool_shape,
                                use_pallas: bool | None = None) -> bool:
    """Would `paged_attention` take the pallas kernel for these shapes?
    ONE predicate shared with the dispatch itself (the
    `flash_uses_pallas` discipline): the serving engine keys its whole
    fused-vs-reference decode lane on this at build time, and the
    audit/plan legs (`serve/audit.py`) key the gathered-view HBM charge
    on it — so what is charged can never drift from what runs."""
    from ray_lightning_tpu.ops import dispatch

    if not dispatch.use_pallas(use_pallas):
        return False
    from ray_lightning_tpu.ops.pallas.paged_attention import (
        paged_shapes_supported,
    )

    return paged_shapes_supported(q_shape, pool_shape)


def paged_attention(
    q: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_v: jnp.ndarray,
    tables: jnp.ndarray,
    lengths: jnp.ndarray,
    pad: jnp.ndarray | None = None,
    scale: float | None = None,
    use_pallas: bool | None = None,
) -> jnp.ndarray:
    """Decode attention over the block-paged KV pool: q [C, H, hd],
    pool [n_blocks, P, Hkv, hd], tables [C, M], lengths [C] ->
    [C, H, hd]. Dispatches to the fused pallas kernel when on TPU (or
    forced, with interpret mode off-TPU) and the shapes tile; otherwise
    the gathering XLA reference path — identical semantics, but the
    dense per-slot view is materialized (and charged by the serve
    planner)."""
    if paged_attention_uses_pallas(q.shape, pool_k.shape, use_pallas):
        from ray_lightning_tpu.ops.pallas.paged_attention import (
            paged_attention_pallas,
        )

        return paged_attention_pallas(q, pool_k, pool_v, tables,
                                      lengths, pad=pad, scale=scale)
    return paged_attention_reference(q, pool_k, pool_v, tables, lengths,
                                     pad=pad, scale=scale)
