"""Attention ops: masked SDPA reference + flash-attention dispatch.

The hot op of the flagship model. Three tiers:
  1. `dot_product_attention` — pure jnp reference (materializes the S×S
     score matrix); correct everywhere, used for tests and tiny shapes.
  2. `flash_attention` — tiled online-softmax kernel
     (ray_lightning_tpu.ops.pallas.flash) that never materializes scores;
     O(S) memory, MXU-shaped tiles. Falls back to (1) off-TPU or for
     shapes that don't tile.
All take [B, S, H, D] (batch, seq, heads, head_dim) and support GQA by
repeating KV heads (XLA turns the repeat into a broadcast, no HBM copy).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def make_causal_mask(q_len: int, kv_len: int, q_offset: int = 0) -> jnp.ndarray:
    """Boolean [q_len, kv_len] mask, True = attend. q_offset shifts the
    query positions (used by sequence-parallel shards / decoding)."""
    q_pos = jnp.arange(q_len)[:, None] + q_offset
    kv_pos = jnp.arange(kv_len)[None, :]
    return q_pos >= kv_pos


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[B, S, H_kv, D] -> [B, S, H_kv*n_rep, D] for GQA."""
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    x = jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, n_rep, d))
    return x.reshape(b, s, h * n_rep, d)


def dot_product_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    mask: jnp.ndarray | None = None,
    q_offset: int = 0,
    scale: float | None = None,
) -> jnp.ndarray:
    """Reference SDPA: [B, S, H, D] in, [B, S, H, D] out; f32 softmax."""
    if k.shape[2] != q.shape[2]:
        n_rep = q.shape[2] // k.shape[2]
        k = repeat_kv(k, n_rep)
        v = repeat_kv(v, n_rep)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    # [B, H, S, S]
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        cm = make_causal_mask(q.shape[1], k.shape[1], q_offset)
        scores = jnp.where(cm[None, None], scores, -jnp.inf)
    if mask is not None:
        # mask: [B, S_kv] padding mask or [B, 1, S_q, S_kv]
        if mask.ndim == 2:
            mask = mask[:, None, None, :]
        scores = jnp.where(mask, scores, -jnp.inf)
    # Rows with no visible key (fully-padded sequence, or padding ∩ causal
    # leaving nothing) would softmax over all -inf → NaN; emit zeros there.
    any_visible = jnp.isfinite(scores).any(axis=-1, keepdims=True)
    probs = jax.nn.softmax(
        jnp.where(any_visible, scores, 0.0), axis=-1
    ).astype(q.dtype)
    probs = jnp.where(any_visible, probs, 0.0).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def flash_uses_pallas(q_shape, k_shape, use_pallas: bool | None = None,
                      masked: bool = False) -> bool:
    """Would `flash_attention` take the pallas kernel for these shapes
    and arguments? ONE predicate shared with the dispatch itself so
    callers that must know the outcome (the block-level remat annotation
    in models/llama.py: the pallas path's residuals are saved through the
    kernel's own `remat_opt` hoist, and naming its output again would
    double-save a [B, S, H·hd] tensor per layer) can never drift from
    what actually runs."""
    from ray_lightning_tpu.ops import dispatch

    if masked or not dispatch.use_pallas(use_pallas):
        return False
    from ray_lightning_tpu.ops.pallas.flash import shapes_supported

    return shapes_supported(q_shape, k_shape)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    mask: jnp.ndarray | None = None,
    q_offset: int = 0,
    use_pallas: bool | None = None,
) -> jnp.ndarray:
    """Tiled attention. Dispatches to the pallas TPU kernel when on TPU
    (or forced via RLT_PALLAS=1 with interpret mode on CPU) and the shape
    tiles cleanly; otherwise the XLA reference path (which XLA still fuses
    reasonably — flash matters at long S where the S×S scores don't fit)."""
    if flash_uses_pallas(q.shape, k.shape, use_pallas,
                         masked=mask is not None):
        from ray_lightning_tpu.ops.pallas.flash import flash_attention_pallas

        return flash_attention_pallas(q, k, v, causal=causal,
                                      q_offset=q_offset)
    return dot_product_attention(q, k, v, causal=causal, mask=mask,
                                 q_offset=q_offset)
