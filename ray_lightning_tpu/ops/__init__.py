"""TPU compute ops: attention, norms, rotary embeddings, pallas kernels.

The reference has no compute ops of its own (its models are MLPs and the
hot loop belongs to torch/NCCL — reference tests/utils.py:96-120). Here the
framework owns the compute path, so the hot ops are first-class: jax
reference implementations that XLA fuses well, with pallas TPU kernels for
the ones worth hand-tiling (flash attention, fused rmsnorm).
"""
from ray_lightning_tpu.ops.attention import (
    dot_product_attention,
    flash_attention,
    make_causal_mask,
)
from ray_lightning_tpu.ops.fused_ce import fused_cross_entropy
from ray_lightning_tpu.ops.norms import rms_norm
from ray_lightning_tpu.ops.pipeline import gpipe_apply, pipeline_param_spec
from ray_lightning_tpu.ops.ring_attention import (
    ring_attention,
    ring_attention_local,
)
from ray_lightning_tpu.ops.rope import apply_rope, rope_frequencies
from ray_lightning_tpu.ops.ulysses import (
    ulysses_attention,
    ulysses_attention_local,
)

__all__ = [
    "ulysses_attention",
    "ulysses_attention_local",
    "dot_product_attention",
    "flash_attention",
    "fused_cross_entropy",
    "gpipe_apply",
    "pipeline_param_spec",
    "make_causal_mask",
    "ring_attention",
    "ring_attention_local",
    "rms_norm",
    "apply_rope",
    "rope_frequencies",
]
