"""On-demand ``jax.profiler`` capture, driven from the fit loop.

A production incident is never reproduced with ``profiler_dir`` set from
the start — the capture has to be armable on a RUNNING job. Three
triggers, all host-side and cadence-guarded:

    step window   ``ProfileConfig(start_step=500, num_steps=5)`` —
                  deterministic capture of a known-bad region;
    marker file   touch ``<dir>/CAPTURE`` (or a configured path) on the
                  worker's filesystem; the loop polls it on the logging
                  cadence and captures the next ``num_steps`` steps;
    SIGUSR1       ``signal=True`` installs a handler that sets a flag
                  (async-signal-safe: no jax work in the handler); the
                  loop picks it up at the next batch boundary.

Rank-scoped (``ranks=(0,)`` by default): an 8-host capture of the same
SPMD program is 8x the bytes for no new information. CPU-safe: when
``jax.profiler`` cannot start on this backend the controller logs ONE
loud note and disarms — profiling must never be able to kill a fit.
"""
from __future__ import annotations

import dataclasses
import os
import signal as _signal
import threading
from typing import Any, Optional, Tuple

from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)

#: marker filename polled inside the profile dir when no explicit
#: marker_file is configured
DEFAULT_MARKER = "CAPTURE"


@dataclasses.dataclass
class ProfileConfig:
    """``Trainer(profile=ProfileConfig(...))`` — see module docstring."""

    dir: str = "rlt_profile"
    #: capture [start_step, start_step + num_steps) deterministically;
    #: None = no step-window trigger (marker/signal only)
    start_step: Optional[int] = None
    num_steps: int = 5
    #: path polled for the marker trigger; None derives <dir>/CAPTURE
    marker_file: Optional[str] = None
    #: install a SIGUSR1 handler as the third trigger
    signal: bool = False
    #: ranks that capture (the trace is identical SPMD work everywhere)
    ranks: Tuple[int, ...] = (0,)
    #: marker/signal polling cadence in steps (host stat() is cheap but
    #: the idiom is cadence-guarded like every other telemetry touch)
    poll_every_n_steps: int = 5

    @classmethod
    def coerce(cls, value: Any) -> Optional["ProfileConfig"]:
        if value is None or value is False:
            return None
        if value is True:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, str):
            return cls(dir=value)
        raise TypeError(
            f"profile= takes True, a directory string, or a "
            f"ProfileConfig; got {type(value).__name__}")


class ProfilerController:
    """Owns one capture lifecycle; the trainer calls ``on_step(step)``
    once per batch (host-side, no device touch)."""

    def __init__(self, config: ProfileConfig, rank: int = 0):
        self.config = config
        self.rank = rank
        self.active = rank in tuple(config.ranks)
        self.capturing = False
        self.captures = 0
        self.disabled_reason: Optional[str] = None
        self._stop_at: Optional[int] = None
        self._signal_flag = threading.Event()
        self._marker = config.marker_file or os.path.join(
            config.dir, DEFAULT_MARKER)
        if self.active and config.signal:
            try:
                _signal.signal(_signal.SIGUSR1,
                               lambda *_: self._signal_flag.set())
            except (ValueError, OSError):
                # non-main thread / platform without SIGUSR1: the other
                # triggers still work
                log.warning("profiler: could not install SIGUSR1 trigger; "
                            "step-window/marker triggers remain armed")

    # ---- trigger evaluation (host-side, cadence-guarded) -----------------

    def _should_start(self, step: int) -> bool:
        cfg = self.config
        if cfg.start_step is not None and step == cfg.start_step:
            return True
        if step % max(1, cfg.poll_every_n_steps) == 0:
            if self._signal_flag.is_set():
                self._signal_flag.clear()
                return True
            if os.path.exists(self._marker):
                try:
                    os.remove(self._marker)  # one marker = one capture
                except OSError:
                    pass
                return True
        return False

    def on_step(self, step: int) -> None:
        """Advance the capture state machine at one batch boundary."""
        if not self.active or self.disabled_reason:
            return
        if self.capturing:
            if self._stop_at is not None and step >= self._stop_at:
                self._stop(step)
            return
        if self._should_start(step):
            self._start(step)

    # ---- capture ---------------------------------------------------------

    def _start(self, step: int) -> None:
        import jax

        try:
            # makedirs inside the guard: an unwritable profile dir must
            # disarm the profiler, not abort the training run
            os.makedirs(self.config.dir, exist_ok=True)
            jax.profiler.start_trace(self.config.dir)
        except Exception as exc:  # noqa: BLE001 — never kill the fit
            self.disabled_reason = f"{type(exc).__name__}: {exc}"
            log.error(
                "profiler: jax.profiler.start_trace failed on this "
                "backend (%s) — capture DISABLED for this run; profiling "
                "is a no-op here, not an error in your job",
                self.disabled_reason)
            return
        self.capturing = True
        self._stop_at = step + max(1, self.config.num_steps)
        log.warning("profiler: capture armed at step %d for %d steps -> %s",
                    step, self.config.num_steps, self.config.dir)

    def _stop(self, step: int) -> None:
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception as exc:  # noqa: BLE001
            self.disabled_reason = f"{type(exc).__name__}: {exc}"
            log.error("profiler: stop_trace failed (%s); capture disabled",
                      self.disabled_reason)
        else:
            self.captures += 1
            log.warning("profiler: capture complete at step %d (XPlane "
                        "trace under %s)", step, self.config.dir)
        self.capturing = False
        self._stop_at = None

    def close(self) -> None:
        """Fit teardown: a capture left open (fit ended mid-window) is
        closed so the trace file finalizes."""
        if self.capturing:
            self._stop(self._stop_at or 0)
