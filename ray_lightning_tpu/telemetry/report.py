"""``python -m ray_lightning_tpu report|monitor`` — the measured side of
the analysis stack, and the first closed loop against it.

``report <run_dir>`` reads the per-rank span JSONL + goodput ledgers a
telemetry-enabled run left under ``<run_dir>/telemetry`` and prints:

  * the goodput classification (telemetry/goodput.py buckets, summing
    to supervised wall time),
  * per-rank phase totals and warm-window step-time stats,
  * with ``--preset/--topo``: a DRIFT section joining the measured
    timeline against tracecheck's per-topology prediction for that step
    (modeled compute window + exposed ICI vs measured step time, static
    ``overlap_hidden_fraction`` restated next to the measured numbers).
    When the run dir holds no measured spans — backend down, telemetry
    off — the drift section still emits, with a structured-skip
    placeholder in the measured slot, so consumers never see a shape
    change (the bench.py skip-line contract, applied to reports).

``monitor <run_dir>`` is the live view: last span + current phase per
rank and the partial goodput, one shot (or ``--follow``).
``monitor <run_dir> --serve [--follow]`` renders the live SERVING tick
stream instead — per-replica queue depth, decoding/prefilling slots,
pool headroom, decode token rate, preemption/growth-stall counters,
and the autoscale load signal, read from the per-tick metrics JSONL
(telemetry/metrics.py). For serving runs ``report`` grows an SLO
section: TTFT/TPOT/queue-wait p50/p95/p99 from the exactly-merged
histogram buckets with the bucket sketch printed (tails are
auditable), event counters, a per-replica timeline with restart
markers, and the `load_signal()` summary.

``monitor --smoke`` is the format.sh gate (docs/OBSERVABILITY.md):
  1. telemetry=off pin — two tiny fits, recorder off vs on, must train
     BITWISE-identically and lower byte-identical step programs;
  2. a 2-proc CPU-SPMD supervised run with an injected worker kill must
     produce a parseable goodput report whose buckets sum to supervised
     wall time (±5%) and whose backoff + replay classes are nonzero;
  3. the flagship llama3-8b drift section must emit (structured-skip
     measured placeholder on a box with no TPU) against tracecheck's
     predicted step composition.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

from ray_lightning_tpu.telemetry import goodput as gp
from ray_lightning_tpu.telemetry.spans import PH_STEP, read_spans

#: |measured/predicted - 1| beyond this flags drift (the cost model is
#: a roofline with MXU_EFFICIENCY derating — docs/STATIC_ANALYSIS.md)
DRIFT_THRESHOLD = 0.25


# ---------------------------------------------------------------- timeline


def telemetry_dir(run_dir: str) -> str:
    """Accept either the run dir or the telemetry dir itself."""
    if glob.glob(os.path.join(run_dir, "rank*.spans.jsonl")):
        return run_dir
    return os.path.join(run_dir, "telemetry")


def load_timeline(run_dir: str,
                  tail_bytes: Optional[int] = None) -> Dict[str, Any]:
    """Assemble the clock-aligned cross-rank view from the span files.
    Restarted attempts leave one pid-tagged file each per rank — they
    are merged in wall-clock order (totals accumulate; the "current"
    phase comes from the newest attempt). ``tail_bytes`` bounds each
    file's read — the cadence-polled `monitor --follow` path threads a
    bound here (RLT503); the one-shot report reads everything."""
    tdir = telemetry_dir(run_dir)
    ranks: Dict[int, Dict[str, Any]] = {}
    paths = sorted(glob.glob(os.path.join(tdir, "rank*.spans.jsonl")))
    parsed_files = []
    for path in paths:
        parsed = read_spans(path, tail_bytes=tail_bytes)
        rank = int(parsed["header"].get("rank", -1)) \
            if parsed["header"] else -1
        t0 = (parsed["header"] or {}).get("t0_wall") or 0.0
        parsed_files.append((rank, t0, path, parsed))
    parsed_files.sort(key=lambda e: (e[0], e[1]))
    for rank, t0, path, parsed in parsed_files:
        info = ranks.setdefault(rank, {
            "paths": [], "t0_wall": None, "phase_totals": {},
            "phase_counts": {}, "step_durs": [], "last_span": None,
            "dropped": 0, "attempts": 0,
        })
        info["paths"].append(path)
        info["attempts"] += 1
        info["t0_wall"] = t0  # newest attempt wins (sorted ascending)
        for span in parsed["spans"]:
            phase = span.get("phase", "?")
            if span.get("thread", "main") == "main":
                # "excl" is the nested-exclusive charge the recorder
                # persisted — summing raw durs would double-count a
                # compile inside an eval span
                info["phase_totals"][phase] = (
                    info["phase_totals"].get(phase, 0.0)
                    + float(span.get("excl", span.get("dur", 0.0))))
                info["phase_counts"][phase] = (
                    info["phase_counts"].get(phase, 0) + 1)
            if phase == PH_STEP:
                info["step_durs"].append(float(span.get("dur", 0.0)))
            info["last_span"] = span
        info["dropped"] += parsed["dropped"]
    return {"telemetry_dir": tdir, "ranks": ranks,
            "step_stats": _step_stats(ranks)}


def _step_stats(ranks: Dict[int, Dict[str, Any]]) -> Optional[dict]:
    """Warm-window step-time stats over rank 0's per-step spans; the
    first interval (cold step: lazy compile, cache population) is
    dropped — same convention as ThroughputMonitor."""
    r0 = ranks.get(0) or (next(iter(ranks.values())) if ranks else None)
    if not r0:
        return None
    durs = r0["step_durs"][1:] if len(r0["step_durs"]) > 1 \
        else r0["step_durs"]
    if not durs:
        return None
    durs = sorted(durs)
    return {
        "steps": len(durs),
        "mean_s": sum(durs) / len(durs),
        "p50_s": durs[len(durs) // 2],
        "max_s": durs[-1],
    }


# ------------------------------------------------------------------ drift


def predicted_step_composition(preset: str, topo_str: str,
                               overlap: str = "off") -> Dict[str, Any]:
    """tracecheck's prediction for one (preset, topology) pair: the
    modeled per-step compute window, exposed/hidden ICI time, and the
    static overlap fraction — the numbers a measured run is reconciled
    against. Degrades to {"error": ...} rather than raising (the drift
    section is advisory; an analysis bug must not fail the report)."""
    try:
        from ray_lightning_tpu.analysis.cli import resolve_trace_target
        from ray_lightning_tpu.analysis.costmodel import parse_topology
        from ray_lightning_tpu.analysis.tracecheck import audit_step

        topo = parse_topology(topo_str)
        built = resolve_trace_target(preset, topo, overlap=overlap)
        if built is None:
            return {"error": f"unknown preset {preset!r}"}
        module, strategy, batch, label = built
        report = audit_step(module, strategy, batch, topology=topo,
                            label=label)
        ov = report.overlap or {}
        compute_us = 0.0
        for sc in ov.get("per_scope", ()):
            compute_us += float(sc.get("compute_us_per_trip", 0.0)) \
                * float(sc.get("trips", 1))
        predicted: Dict[str, Any] = {
            "label": label,
            "topology": topo.name,
            "ici_time_us": round(report.ici_time_us, 1),
            "ici_exposed_us": round(report.ici_exposed_us, 1),
            "ici_hidden_us": round(report.ici_hidden_us, 1),
            "overlap_hidden_fraction": round(
                report.overlap_hidden_fraction, 4),
            "compute_us": round(compute_us, 1) if compute_us else None,
            "assumptions": (
                "roofline compute window (costmodel.compute_time_us, "
                "MXU-derated spec peak) over traced scan scopes + exposed "
                "ICI serialized with compute; host time not modeled"),
        }
        if compute_us:
            predicted["step_us"] = round(
                compute_us + report.ici_exposed_us, 1)
        else:
            predicted["step_us"] = None
        return predicted
    except Exception as exc:  # noqa: BLE001 — advisory section
        return {"error": f"{type(exc).__name__}: {str(exc)[:300]}"}


def build_drift(predicted: Dict[str, Any],
                timeline: Optional[Dict[str, Any]],
                threshold: float = DRIFT_THRESHOLD) -> Dict[str, Any]:
    """Join measured vs predicted; flags name what disagrees. With no
    measured spans the measured slot is the structured-skip placeholder
    — same keys, null values, a "skipped" reason — never a missing
    section."""
    drift: Dict[str, Any] = {"predicted": predicted, "threshold": threshold}
    stats = (timeline or {}).get("step_stats")
    if not stats:
        drift["measured"] = {
            "step_us": None, "steps": 0,
            "skipped": "no measured telemetry spans (backend down, "
                       "telemetry off, or the run never stepped)",
        }
        drift["flags"] = []
        drift["verdict"] = "not-measured"
        return drift
    # p50, not mean: a step span that crosses an epoch boundary carries
    # the eval epoch + checkpoint inside its interval and would skew a
    # mean by orders of magnitude; the median is the honest per-step
    # wall (the boundary outliers are already itemized as eval/ckpt
    # spans in their own right)
    measured_us = stats["p50_s"] * 1e6
    drift["measured"] = {"step_us": round(measured_us, 1),
                         "steps": stats["steps"],
                         "mean_us": round(stats["mean_s"] * 1e6, 1)}
    flags: List[str] = []
    pred_us = predicted.get("step_us")
    if pred_us:
        ratio = measured_us / pred_us
        drift["step_time_ratio"] = round(ratio, 3)
        if abs(ratio - 1.0) > threshold:
            direction = "slower" if ratio > 1 else "faster"
            flags.append(
                f"measured step {measured_us / 1e3:.2f} ms is "
                f"{ratio:.2f}x the modeled compute+exposed-ICI floor "
                f"({pred_us / 1e3:.2f} ms) — {direction} than the cost "
                "model beyond the threshold; the static "
                "overlap_hidden_fraction "
                f"({predicted.get('overlap_hidden_fraction')}) may not "
                "be realized on this hardware")
    elif predicted.get("error"):
        flags.append(f"prediction unavailable: {predicted['error']}")
    else:
        flags.append("cost model produced no compute window for this "
                     "step (no scanned scopes); only ICI time was "
                     "predicted — step-time drift not judged")
    drift["flags"] = flags
    drift["verdict"] = "drift" if (pred_us and flags) else "ok" \
        if pred_us else "partial-model"
    return drift


# ------------------------------------------------------------------ report


def add_report_parser(sub) -> None:
    p = sub.add_parser(
        "report",
        help="goodput + span-timeline report for a telemetry-enabled "
             "run dir; --preset/--topo adds the static-vs-measured "
             "drift section (docs/OBSERVABILITY.md)")
    p.add_argument("run_dir",
                   help="run dir (or its telemetry/ subdir) holding "
                        "rank*.spans.jsonl / goodput ledgers")
    p.add_argument("--preset", default=None,
                   help="tracecheck target for the drift section (e.g. "
                        "llama3-8b, or a bundled example name)")
    p.add_argument("--topo", default="v5p-64",
                   help="topology the prediction is priced for")
    p.add_argument("--overlap", choices=("off", "on", "serial"),
                   default="off")
    p.add_argument("--drift-threshold", type=float,
                   default=DRIFT_THRESHOLD)
    p.add_argument("--json", action="store_true", dest="as_json",
                   default=argparse.SUPPRESS)


def _pct(sorted_vals, q: float) -> float:
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(q * len(sorted_vals)))]


def build_serving_section(run_dir: str) -> Optional[Dict[str, Any]]:
    """Per-request serving latency attribution when this run dir holds
    serving telemetry (serve/driver.py): TTFT/TPOT percentiles from the
    per-request decode spans (or the driver's serving.json summary) +
    replica restarts + aggregate throughput. When the run recorded
    LIVE metrics (telemetry/metrics.py), the section grows the SLO
    view: p99s computed from the exactly-merged histogram buckets, the
    bucket sketches so tails are auditable, preemption / growth-stall
    counts, queue-depth stats, the per-replica timeline (restart
    markers = extra metrics files per replica), and the autoscale load
    signal. None when the run served nothing — training runs keep
    their report unchanged."""
    from ray_lightning_tpu.telemetry.metrics import (
        aggregate_from_parsed, load_signal_from_parsed,
        newest_from_parsed, read_all_metrics,
    )
    from ray_lightning_tpu.telemetry.spans import PH_DECODE, read_spans

    tdir = telemetry_dir(run_dir)
    base = run_dir if tdir != run_dir else os.path.dirname(run_dir)
    summary = None
    spath = os.path.join(base, "serving.json")
    if os.path.exists(spath):
        try:
            with open(spath) as f:
                summary = json.load(f)
        except (OSError, json.JSONDecodeError):
            summary = None
    per_req: Dict[str, dict] = dict((summary or {}).get("meta", {}))
    if not per_req:
        # fall back to the span files: decode spans carry the request
        # meta (rid, ttft_s, tpot_s) at completion. Replayed-prefix and
        # inflight-tagged spans carry neither ttft_s nor tpot_s, so a
        # preempted request's discarded prefix can never double-count
        # into the latency percentiles here.
        for path in sorted(glob.glob(
                os.path.join(tdir, "rank*.spans.jsonl"))):
            try:
                parsed = read_spans(path)
            except OSError:
                continue
            for span in parsed["spans"]:
                meta = span.get("meta") or {}
                if span.get("phase") == PH_DECODE and "ttft_s" in meta:
                    per_req[meta.get("rid", f"?{len(per_req)}")] = meta
    parsed_metrics = read_all_metrics(tdir)  # ONE parse pass for both
    metrics_agg = aggregate_from_parsed(parsed_metrics)
    if not per_req and not metrics_agg:
        return None
    section: Dict[str, Any] = {"requests": len(per_req)}
    if per_req:
        ttfts = sorted(float(m.get("ttft_s", 0.0))
                       for m in per_req.values())
        tpots = sorted(float(m.get("tpot_s", 0.0))
                       for m in per_req.values())
        section.update({
            "ttft_p50_s": round(_pct(ttfts, 0.50), 4),
            "ttft_p95_s": round(_pct(ttfts, 0.95), 4),
            "tpot_p50_s": round(_pct(tpots, 0.50), 4),
            "tpot_p95_s": round(_pct(tpots, 0.95), 4),
        })
    if summary:
        stats = summary.get("stats", {})
        for key in ("decode_tokens_per_s", "slot_occupancy",
                    "warmup_cold_s", "warmup_respawn_s"):
            if stats.get(key) is not None:
                section[key] = stats[key]
        restarts = summary.get("restarts", {})
        if restarts:
            section["replica_restarts"] = restarts
    if metrics_agg:
        lat = metrics_agg.get("latency") or {}
        for name, key in (("ttft_s", "ttft"), ("tpot_s", "tpot"),
                          ("queue_wait_s", "queue_wait")):
            block = lat.get(name)
            if not block:
                continue
            # bucket-derived quantiles override the sample-derived
            # p50/p95 when present: they merge exactly across replicas
            # and attempts, and they come with an auditable sketch
            section[f"{key}_p50_s"] = block["p50"]
            section[f"{key}_p95_s"] = block["p95"]
            section[f"{key}_p99_s"] = block["p99"]
            section[f"{key}_sketch"] = block["sketch"]
            section[f"{key}_n"] = block["n"]
        counters = metrics_agg.get("counters") or {}
        section["counters"] = counters
        if "queue_depth" in metrics_agg:
            section["queue_depth"] = metrics_agg["queue_depth"]
        # restart markers: each respawned attempt opened its own
        # uid-tagged metrics file, so files - 1 = restarts observed
        section["timeline"] = {
            rep: {"attempts": info["files"],
                  "restart_markers": info["files"] - 1,
                  "ticks": info["ticks"],
                  "last_tick_t": info["last_tick_t"]}
            for rep, info in sorted(
                (metrics_agg.get("replicas") or {}).items())}
        section["load_signal"] = load_signal_from_parsed(
            newest_from_parsed(parsed_metrics), where=tdir)
    autoscale = build_autoscale_section(base, tdir)
    if autoscale:
        section["autoscale"] = autoscale
    return section


def build_autoscale_section(base: str, tdir: str,
                            tail_bytes: Optional[int] = None
                            ) -> Optional[Dict[str, Any]]:
    """The controller's decision ledger, summarized
    (``<run_dir>/autoscale.jsonl``, docs/AUTOSCALE.md): decision/event
    counts, spawn retries, the final replica count, the last decision
    with its reason, plus the driver-stream scale/deferral counters
    (``driver*.metrics.jsonl``). None when the run never ran a
    controller — plain serving reports stay unchanged."""
    from ray_lightning_tpu.autoscale.controller import read_ledger
    from ray_lightning_tpu.telemetry.metrics import (
        driver_metrics_paths, read_metrics,
    )

    entries = read_ledger(base, tail_bytes=tail_bytes)
    if not entries:
        return None

    def _acted(e: dict) -> bool:
        # an event is anything that CHANGED the replica set — a partial
        # scale-up (outcome.ok False but replicas added before the
        # budget ran out) must still show in the timeline, or the
        # report would contradict final_replicas (review finding)
        out = e.get("outcome") or {}
        return bool(out.get("added") or out.get("removed"))

    events = [e for e in entries if _acted(e)]
    last = entries[-1]
    section: Dict[str, Any] = {
        "decisions": len(entries),
        "scale_ups": sum(1 for e in events
                         if e["decision"]["action"] == "scale_up"),
        "scale_downs": sum(1 for e in events
                           if e["decision"]["action"] == "scale_down"),
        "spawn_retries": sum(
            int((e.get("outcome") or {}).get("retries") or 0)
            for e in entries),
        "final_replicas": last.get("replicas"),
        "last_decision": {
            "now": last.get("now"),
            **(last.get("decision") or {}),
        },
        "events": [{"now": e.get("now"),
                    "action": e["decision"]["action"],
                    "target": e["decision"]["target"],
                    **({} if (e.get("outcome") or {}).get("ok")
                       else {"partial": True})}
                   for e in events],
    }
    counters: Dict[str, int] = {}
    for path in driver_metrics_paths(tdir):
        try:
            parsed = read_metrics(path, tail_bytes=tail_bytes)
        except OSError:
            continue
        for name, v in parsed["counters"].items():
            counters[name] = counters.get(name, 0) + int(v)
    if counters:
        section["driver_counters"] = counters
        if "submit_deferrals" in counters:
            section["submit_deferrals"] = counters["submit_deferrals"]
    return section


#: evidence stream name -> (where, glob/file) — the detection table the
#: structured partial report names missing streams from. A run dir
#: that holds only a SUBSET (a run killed before the first span flush,
#: an autoscale-only dir) degrades to a partial report naming the gap,
#: never a traceback (test-pinned, docs/OBSERVABILITY.md).
EVIDENCE_STREAMS = (
    ("spans", "telemetry", "rank*.spans.jsonl"),
    ("goodput", "telemetry", "goodput.json"),
    ("metrics", "telemetry", "*.metrics.jsonl"),
    ("flight", "both", "*flight.json"),
    ("autoscale", "run", "autoscale.jsonl"),
    ("reshard", "both", "reshards.jsonl"),
    ("incidents", "run", "incidents.jsonl"),
    ("serving", "run", "serving.json"),
)


def detect_streams(run_dir: str, tdir: str) -> Dict[str, List[str]]:
    """Which evidence streams this run dir actually holds — the
    report's honesty header: a partial report SAYS what is missing
    instead of silently rendering empty sections."""
    base = run_dir if tdir != run_dir else os.path.dirname(run_dir)
    present: List[str] = []
    missing: List[str] = []
    for name, where, pattern in EVIDENCE_STREAMS:
        dirs = {"telemetry": (tdir,), "run": (base,),
                "both": (base, tdir)}[where]
        found = any(glob.glob(os.path.join(d, pattern)) for d in dirs)
        (present if found else missing).append(name)
    return {"present": present, "missing": missing}


def build_incidents_section(run_dir: str,
                            tail_bytes: Optional[int] = None
                            ) -> Optional[Dict[str, Any]]:
    """The incident ledger, summarized (telemetry/incidents.py,
    docs/OBSERVABILITY.md "watch rules & incidents"). None when the
    run never ran a watch (or nothing fired and no ledger exists)."""
    from ray_lightning_tpu.telemetry.incidents import read_incidents

    tdir = telemetry_dir(run_dir)
    base = run_dir if tdir != run_dir else os.path.dirname(run_dir)
    parsed = read_incidents(base, tail_bytes=tail_bytes)
    if not parsed["incidents"] and not parsed["header"]:
        return None
    by_rule: Dict[str, int] = {}
    by_sev: Dict[str, int] = {}
    for inc in parsed["incidents"]:
        by_rule[inc.get("rule", "?")] = \
            by_rule.get(inc.get("rule", "?"), 0) + 1
        by_sev[inc.get("severity", "?")] = \
            by_sev.get(inc.get("severity", "?"), 0) + 1
    section: Dict[str, Any] = {
        "count": len(parsed["incidents"]),
        "by_rule": by_rule,
        "by_severity": by_sev,
        "unparseable_lines": parsed["unparseable_lines"],
    }
    if parsed["incidents"]:
        last = parsed["incidents"][-1]
        section["last"] = {
            "rule": last.get("rule"),
            "severity": last.get("severity"),
            "wall": last.get("wall"),
            "evidence": {k: (last.get("evidence") or {}).get(k)
                         for k in ("metric", "value", "op",
                                   "threshold")},
            "actions": sorted(last.get("actions") or {}),
            "excerpt_events": len(last.get("timeline_excerpt") or []),
        }
    return section


def build_report(run_dir: str, preset: Optional[str] = None,
                 topo: str = "v5p-64", overlap: str = "off",
                 threshold: float = DRIFT_THRESHOLD) -> Dict[str, Any]:
    timeline = load_timeline(run_dir)
    out: Dict[str, Any] = {
        "run_dir": run_dir,
        "telemetry_dir": timeline["telemetry_dir"],
        "ranks": sorted(timeline["ranks"]),
        "step_stats": timeline["step_stats"],
        "phase_totals": {
            str(r): v["phase_totals"]
            for r, v in sorted(timeline["ranks"].items())},
        "goodput": gp.read_goodput(timeline["telemetry_dir"]),
        "streams": detect_streams(run_dir, timeline["telemetry_dir"]),
    }
    serving = build_serving_section(run_dir)
    if serving:
        out["serving"] = serving
    incidents = build_incidents_section(run_dir)
    if incidents:
        out["incidents"] = incidents
    if preset:
        predicted = predicted_step_composition(preset, topo, overlap)
        out["drift"] = build_drift(predicted, timeline, threshold)
    return out


def _print_report(out: Dict[str, Any]) -> None:
    print(f"telemetry report: {out['run_dir']}")
    streams = out.get("streams") or {}
    if streams:
        missing = streams.get("missing") or []
        print(f"streams: {', '.join(streams.get('present') or ['none'])}"
              + (f" (missing: {', '.join(missing)})" if missing
                 else ""))
    inc = out.get("incidents")
    if inc:
        by_rule = ", ".join(f"{r}x{n}" for r, n in
                            sorted(inc["by_rule"].items()))
        print(f"incidents: {inc['count']} ({by_rule})")
        last = inc.get("last") or {}
        if last:
            ev = last.get("evidence") or {}
            print(f"  last: [{last.get('severity')}] "
                  f"{last.get('rule')} — {ev.get('metric')} = "
                  f"{ev.get('value')} {ev.get('op')} "
                  f"{ev.get('threshold')}; "
                  f"{last.get('excerpt_events')} excerpt event(s), "
                  f"actions: {', '.join(last.get('actions') or []) or 'none'}")
    g = out.get("goodput")
    if g:
        print(f"goodput: {g['goodput_fraction']:.1%} of "
              f"{g['wall_s']:.1f}s wall productive "
              f"({g['events']['restarts']} restart(s), "
              f"{g['events']['preemptions']} preemption(s), "
              f"{g['events']['rollbacks']} rollback(s))")
        for b, v in g["buckets"].items():
            if v:
                print(f"  {b:<20} {v:8.2f}s  "
                      f"{v / g['wall_s']:6.1%}")
    else:
        print("goodput: no assembled goodput.json (run was not "
              "supervised, or is still in flight)")
    sv = out.get("serving")
    if sv:
        if "ttft_p99_s" in sv:
            # the SLO line: quantiles from the exactly-merged histogram
            # buckets (p99 included), auditable against the sketch
            print(f"serving: {sv['requests']} request(s), TTFT p50 "
                  f"{sv['ttft_p50_s'] * 1e3:.1f} / p95 "
                  f"{sv['ttft_p95_s'] * 1e3:.1f} / p99 "
                  f"{sv['ttft_p99_s'] * 1e3:.1f} ms, TPOT p50 "
                  f"{sv['tpot_p50_s'] * 1e3:.1f} / p99 "
                  f"{sv['tpot_p99_s'] * 1e3:.1f} ms (from merged "
                  f"buckets, n={sv.get('ttft_n')})")
            for key, label in (("ttft_sketch", "ttft"),
                               ("queue_wait_sketch", "queue_wait")):
                sk = sv.get(key)
                if sk:
                    buckets = " ".join(
                        f"<={le * 1e3:.1f}ms:{c}" for le, c in sk)
                    print(f"  {label} buckets: {buckets}")
        elif "ttft_p50_s" in sv:
            print(f"serving: {sv['requests']} request(s), TTFT p50 "
                  f"{sv['ttft_p50_s'] * 1e3:.1f} ms / p95 "
                  f"{sv['ttft_p95_s'] * 1e3:.1f} ms, TPOT p50 "
                  f"{sv['tpot_p50_s'] * 1e3:.1f} ms")
        else:
            print(f"serving: {sv['requests']} request(s)")
        extras = ", ".join(
            f"{k}={sv[k]}" for k in ("decode_tokens_per_s",
                                     "slot_occupancy",
                                     "replica_restarts") if k in sv)
        if extras:
            print(f"  {extras}")
        counters = sv.get("counters")
        if counters:
            qd = sv.get("queue_depth") or {}
            print(f"  events: admissions={counters.get('admissions', 0)}"
                  f" preemptions={counters.get('preemptions', 0)}"
                  f" growth_stalls={counters.get('growth_stalls', 0)}"
                  f" deferrals={counters.get('admission_deferrals', 0)}"
                  + (f"; queue_depth p50={qd.get('p50')}"
                     f" max={qd.get('max')}" if qd else ""))
        for rep, tl in (sv.get("timeline") or {}).items():
            marker = (f", {tl['restart_markers']} restart(s)"
                      if tl.get("restart_markers") else "")
            print(f"  replica {rep}: {tl['ticks']} tick(s) over "
                  f"{tl['attempts']} attempt(s){marker}")
        sig = sv.get("load_signal")
        if sig and sig.get("available"):
            print(f"  load signal: queue_depth now "
                  f"{sig['queue_depth_now']:.0f} / p50 "
                  f"{sig['queue_depth_p50']:.0f}, occupancy "
                  f"{sig['occupancy']:.2f}, pressure "
                  f"{sig['pressure'] if sig['pressure'] is not None else '—'}")
        asc = sv.get("autoscale")
        if asc:
            print(f"  autoscale: {asc['decisions']} decision(s) -> "
                  f"{asc['scale_ups']} up / {asc['scale_downs']} down"
                  f" ({asc['spawn_retries']} spawn retr{'y' if asc['spawn_retries'] == 1 else 'ies'}), "
                  f"final replicas {asc['final_replicas']}")
            for e in asc.get("events") or []:
                print(f"    t={e['now']:g}: {e['action']} -> "
                      f"{e['target']}")
            ld = asc.get("last_decision") or {}
            if ld.get("reason"):
                print(f"    last: {ld.get('action')} — "
                      f"{ld['reason']}")
            if asc.get("submit_deferrals"):
                print(f"    submit deferrals: "
                      f"{asc['submit_deferrals']}")
    ss = out.get("step_stats")
    if ss:
        print(f"warm step time: mean {ss['mean_s'] * 1e3:.2f} ms / "
              f"p50 {ss['p50_s'] * 1e3:.2f} ms over {ss['steps']} steps")
    for rank, totals in (out.get("phase_totals") or {}).items():
        hot = ", ".join(f"{k}={v:.2f}s" for k, v in sorted(
            totals.items(), key=lambda kv: -kv[1])[:5])
        print(f"  rank {rank}: {hot or 'no spans'}")
    drift = out.get("drift")
    if drift:
        pred = drift["predicted"]
        print(f"drift vs tracecheck ({pred.get('label', '?')} on "
              f"{pred.get('topology', '?')}):")
        meas = drift["measured"]
        if meas.get("skipped"):
            print(f"  measured: SKIPPED — {meas['skipped']}")
        else:
            print(f"  measured step {meas['step_us'] / 1e3:.2f} ms over "
                  f"{meas['steps']} warm steps")
        if pred.get("step_us"):
            print(f"  predicted step floor "
                  f"{pred['step_us'] / 1e3:.2f} ms (compute "
                  f"{(pred.get('compute_us') or 0) / 1e3:.2f} ms + "
                  f"exposed ICI {pred['ici_exposed_us'] / 1e3:.2f} ms; "
                  f"static overlap_hidden_fraction "
                  f"{pred['overlap_hidden_fraction']})")
        for flag in drift["flags"]:
            print(f"  DRIFT: {flag}")
        print(f"  verdict: {drift['verdict']}")


def run_report(args) -> int:
    if not os.path.isdir(args.run_dir):
        print(f"error: {args.run_dir} is not a directory",
              file=sys.stderr)
        return 2
    out = build_report(args.run_dir, preset=args.preset, topo=args.topo,
                       overlap=args.overlap,
                       threshold=args.drift_threshold)
    if getattr(args, "as_json", False):
        print(json.dumps(out))
    else:
        _print_report(out)
    return 0


# ----------------------------------------------------------------- monitor


def add_monitor_parser(sub) -> None:
    p = sub.add_parser(
        "monitor",
        help="live per-rank phase view of a telemetry-enabled run; "
             "--smoke is the format.sh observability gate")
    p.add_argument("run_dir", nargs="?", default=None)
    p.add_argument("--follow", action="store_true",
                   help="refresh every --interval seconds until ^C")
    p.add_argument("--interval", type=float, default=5.0)
    p.add_argument("--serve", action="store_true",
                   help="render the live SERVING tick stream instead "
                        "of the training phase view: per-replica queue "
                        "depth, slot/pool state, token rates, and the "
                        "autoscale load signal from the per-tick "
                        "metrics JSONL (docs/OBSERVABILITY.md "
                        "'serving metrics')")
    p.add_argument("--smoke", action="store_true",
                   help="gate mode: telemetry=off byte-identical pin, "
                        "2-proc fault-injected goodput report (buckets "
                        "sum to wall, lost classes nonzero), flagship "
                        "drift section emits")
    p.add_argument("--flagship-topo", default="v5p-64",
                   help="topology for the smoke's flagship drift leg")
    p.add_argument("--processes", type=int, default=2)
    p.add_argument("--timeout", type=float, default=600.0,
                   help="per-attempt wall budget for the smoke's "
                        "supervised leg")
    p.add_argument("--json", action="store_true", dest="as_json",
                   default=argparse.SUPPRESS)


#: per-ledger read bound for the cadence-polled monitor views — the
#: live view needs the newest spans/ticks, never the whole run history
#: (RLT503; one-shot `report` still reads everything)
MONITOR_TAIL_BYTES = 1 << 20


def _monitor_once(run_dir: str,
                  tail_bytes: Optional[int] = None) -> Dict[str, Any]:
    timeline = load_timeline(run_dir, tail_bytes=tail_bytes)
    now = time.time()
    view: Dict[str, Any] = {"run_dir": run_dir, "ranks": {}}
    for rank, info in sorted(timeline["ranks"].items()):
        last = info.get("last_span") or {}
        age = None
        if info.get("t0_wall") is not None and last:
            age = now - (info["t0_wall"] + last.get("t", 0.0)
                         + last.get("dur", 0.0))
        view["ranks"][str(rank)] = {
            "phase": last.get("phase"),
            "step": last.get("step"),
            "last_span_age_s": round(age, 1) if age is not None else None,
            "dropped": info["dropped"],
        }
    view["goodput"] = gp.read_goodput(timeline["telemetry_dir"])
    view["step_stats"] = timeline["step_stats"]
    inc = build_incidents_section(run_dir, tail_bytes=tail_bytes)
    if inc:
        view["incidents"] = inc["count"]
    return view


def _monitor_serve_once(run_dir: str,
                        tail_bytes: Optional[int] = None
                        ) -> Dict[str, Any]:
    """One sample of the live serving view: the newest metrics file per
    replica, its latest flushed tick, a token rate over the recent
    window, and the load signal — everything `monitor --serve` renders.
    Reads only flushed JSONL, so the view lags live state by at most
    one flush cadence."""
    from ray_lightning_tpu.telemetry.metrics import (
        load_signal_from_parsed, newest_metrics_per_replica,
    )

    tdir = telemetry_dir(run_dir)
    view: Dict[str, Any] = {"run_dir": run_dir, "replicas": {}}
    # ONE parse pass serves both the per-replica view and the load
    # signal — a --follow refresh re-reads each file once, not twice,
    # and reads only each ledger's tail (RLT503)
    newest = newest_metrics_per_replica(tdir, tail_bytes=tail_bytes)
    now = time.time()
    for rep, entry in sorted(newest.items()):
        parsed = entry["parsed"]
        ticks = parsed["ticks"]
        last = ticks[-1] if ticks else {}
        g = dict(last.get("g") or {})
        c = dict(last.get("c") or {})
        rate = None
        if len(ticks) >= 2:
            # decode rate over the flushed window: counter delta / time
            first = ticks[max(0, len(ticks) - 64)]
            dt = float(last.get("t", 0.0)) - float(first.get("t", 0.0))
            dtok = (int((last.get("c") or {}).get("decode_tokens", 0))
                    - int((first.get("c") or {}).get("decode_tokens",
                                                     0)))
            if dt > 0:
                rate = dtok / dt
        age = None
        if ticks and entry["t0"]:
            age = now - (entry["t0"] + float(last.get("t", 0.0)))
        view["replicas"][rep] = {
            "tick": last.get("tick"),
            "age_s": round(age, 1) if age is not None else None,
            "queue_depth": g.get("queue_depth"),
            "decoding": g.get("decoding_slots"),
            "prefilling": g.get("prefilling_slots"),
            "blocks_free": g.get("blocks_free"),
            "decode_tokens_per_s": round(rate, 1) if rate else None,
            "preemptions": c.get("preemptions", 0),
            "growth_stalls": c.get("growth_stalls", 0),
            "compile_count": g.get("compile_count"),
        }
    view["load_signal"] = load_signal_from_parsed(newest, where=tdir)
    base = run_dir if tdir != run_dir else os.path.dirname(run_dir)
    asc = build_autoscale_section(base, tdir, tail_bytes=tail_bytes)
    if asc:
        view["autoscale"] = asc
    inc = build_incidents_section(run_dir, tail_bytes=tail_bytes)
    if inc:
        view["incidents"] = inc["count"]
    return view


def _print_serve_view(view: Dict[str, Any]) -> None:
    print(f"-- {time.strftime('%H:%M:%S')} {view['run_dir']} (serving)")
    for rep, r in view["replicas"].items():
        rate = (f" {r['decode_tokens_per_s']} tok/s"
                if r.get("decode_tokens_per_s") else "")
        print(f"  replica {rep}: tick {r['tick']} "
              f"({r['age_s']}s ago) queue={r['queue_depth']} "
              f"decoding={r['decoding']} prefilling={r['prefilling']} "
              f"blocks_free={r['blocks_free']}{rate} "
              f"preempt={r['preemptions']} "
              f"stalls={r['growth_stalls']}")
    if not view["replicas"]:
        print("  (no metrics files yet)")
    sig = view.get("load_signal") or {}
    if sig.get("available"):
        pressure = sig.get("pressure")
        print(f"  load: queue now {sig['queue_depth_now']:.0f} / p50 "
              f"{sig['queue_depth_p50']:.0f} / max "
              f"{sig['queue_depth_max']:.0f}, occupancy "
              f"{sig['occupancy']:.2f}"
              + (f", pressure {pressure:.2f}"
                 if pressure is not None else ""))
    asc = view.get("autoscale")
    if asc:
        ld = asc.get("last_decision") or {}
        print(f"  autoscale: replicas {asc['final_replicas']}, "
              f"{asc['decisions']} decision(s) "
              f"({asc['scale_ups']} up / {asc['scale_downs']} down); "
              f"last: {ld.get('action')} — "
              f"{(ld.get('reason') or '')[:70]}")
    if view.get("incidents"):
        print(f"  incidents: {view['incidents']} (see `report` / "
              "incidents.jsonl)")


def run_monitor(args) -> int:
    if args.smoke:
        return _run_smoke(args)
    if not args.run_dir:
        print("error: pass a run dir or --smoke", file=sys.stderr)
        return 2
    as_json = getattr(args, "as_json", False)
    # --follow polls on a cadence: every ledger read is tail-bounded
    # (the one-shot view reads everything — it runs once)
    tail = MONITOR_TAIL_BYTES if args.follow else None
    if getattr(args, "serve", False):
        while True:
            view = _monitor_serve_once(args.run_dir, tail_bytes=tail)
            if as_json:
                print(json.dumps(view), flush=True)
            else:
                _print_serve_view(view)
            if not args.follow:
                return 0
            time.sleep(max(0.2, args.interval))
    while True:
        view = _monitor_once(args.run_dir, tail_bytes=tail)
        if as_json:
            print(json.dumps(view), flush=True)
        else:
            ss = view.get("step_stats")
            extra = (f"  warm step {ss['mean_s'] * 1e3:.1f} ms"
                     if ss else "")
            if view.get("incidents"):
                extra += f"  [{view['incidents']} incident(s)]"
            print(f"-- {time.strftime('%H:%M:%S')} {args.run_dir}{extra}")
            for rank, info in view["ranks"].items():
                print(f"  rank {rank}: phase={info['phase']} "
                      f"step={info['step']} "
                      f"last span {info['last_span_age_s']}s ago")
            if not view["ranks"]:
                print("  (no span files yet)")
        if not args.follow:
            return 0
        time.sleep(max(0.2, args.interval))


# ------------------------------------------------------------------ smoke


def _smoke_off_pin(out: Dict[str, Any]) -> bool:
    """Leg 1: telemetry=off vs on must train bitwise-identically AND
    lower byte-identical step programs — telemetry is host-side
    bookkeeping, never program content."""
    import tempfile

    import jax
    import numpy as np

    from ray_lightning_tpu import DataLoader, Trainer
    from ray_lightning_tpu.models.mlp import MLPClassifier

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = rng.integers(0, 4, size=(64,))

    def _fit(telemetry):
        trainer = Trainer(max_epochs=1, max_steps=4, seed=0,
                          enable_checkpointing=False,
                          enable_progress_bar=False,
                          default_root_dir=tempfile.mkdtemp(
                              prefix="rlt_offpin_"),
                          telemetry=telemetry)
        module = MLPClassifier(features=(16,), num_classes=4, lr=1e-2)
        trainer.fit(module, DataLoader({"x": x, "y": y}, batch_size=16))
        lowered = trainer._train_step._jitted.lower(
            trainer.state, trainer._place_train_batch(
                {"x": x[:16], "y": y[:16]})[1], trainer._base_rng)
        return trainer.state.params, lowered.as_text()

    params_off, text_off = _fit(False)
    params_on, text_on = _fit(True)
    identical = all(
        bool(jax.numpy.array_equal(a, b))
        for a, b in zip(jax.tree.leaves(params_off),
                        jax.tree.leaves(params_on)))
    out["off_pin"] = {
        "params_bitwise_identical": identical,
        "program_byte_identical": text_off == text_on,
        "ok": identical and text_off == text_on,
    }
    return out["off_pin"]["ok"]


def _smoke_goodput_leg(args, out: Dict[str, Any]) -> bool:
    """Leg 2: 2-proc supervised CPU-SPMD fit, injected worker kill,
    telemetry on — the goodput report must be parseable, sum to wall
    within 5%, and show nonzero backoff + replay."""
    import tempfile

    from ray_lightning_tpu.resilience.cli import (
        _smoke_data, _smoke_module, _smoke_trainer,
    )
    from ray_lightning_tpu.resilience.policy import RetryPolicy
    from ray_lightning_tpu.resilience.supervisor import (
        ResilienceConfig, fit_supervised,
    )

    base = tempfile.mkdtemp(prefix="rlt_monitor_smoke_")
    cfg = ResilienceConfig(
        checkpoint_dir=os.path.join(base, "ckpts"),
        policy=RetryPolicy(max_restarts=2, backoff_base_s=0.5,
                           jitter=0.0),
        # save every 5 steps: a kill at step 3 resumes BEHIND the dead
        # attempt's frontier, so the replay bucket is provably nonzero
        save_every_n_steps=5,
        heartbeat_interval_s=1.0,
        stall_timeout_s=0.0,
        faults="kill:rank=1,step=3",
    )
    leg: Dict[str, Any] = {"checkpoint_dir": base}
    out["goodput_leg"] = leg
    try:
        supervised = fit_supervised(
            _smoke_module, _smoke_trainer, _smoke_data, args.processes,
            resilience=cfg, platform="cpu",
            num_cpu_devices_per_process=1, return_weights=False,
            timeout=args.timeout)
    except Exception as exc:  # noqa: BLE001 — the gate reports, not raises
        leg["ok"] = False
        leg["error"] = f"{type(exc).__name__}: {str(exc)[:300]}"
        return False
    report = supervised.goodput
    leg["restarts"] = supervised.restarts
    leg["goodput"] = report
    if not report:
        leg["ok"] = False
        leg["error"] = "supervisor assembled no goodput report"
        return False
    problems = []
    if supervised.restarts < 1:
        problems.append("injected kill never fired (0 restarts)")
    if not gp.buckets_consistent(report, tolerance=0.05):
        problems.append(
            f"buckets sum {report['buckets_sum_s']}s != wall "
            f"{report['wall_s']}s within 5%")
    buckets = report["buckets"]
    for cls in gp.LOST_CLASSES:
        if buckets.get(cls, 0.0) <= 0.0:
            problems.append(f"lost-time class {cls} is zero — the "
                            "restart's cost went unattributed")
    leg["ok"] = not problems
    if problems:
        leg["error"] = "; ".join(problems)
    return leg["ok"]


def _smoke_flagship_drift(args, out: Dict[str, Any]) -> bool:
    """Leg 3: the flagship drift section must emit — predicted step
    composition from tracecheck, measured slot a structured-skip
    placeholder on a box with no TPU telemetry run to join."""
    predicted = predicted_step_composition("llama3-8b",
                                           args.flagship_topo)
    drift = build_drift(predicted, timeline=None)
    out["flagship_drift"] = drift
    ok = ("error" not in predicted
          and predicted.get("ici_time_us", 0) > 0
          and isinstance(drift.get("measured"), dict)
          and "skipped" in drift["measured"]
          and drift.get("verdict") == "not-measured")
    out["flagship_drift_ok"] = ok
    return ok


def _run_smoke(args) -> int:
    out: Dict[str, Any] = {"gate": "monitor --smoke"}
    ok = True
    legs = (("off_pin", lambda: _smoke_off_pin(out)),
            ("goodput", lambda: _smoke_goodput_leg(args, out)),
            ("flagship_drift", lambda: _smoke_flagship_drift(args, out)))
    for name, leg in legs:
        try:
            ok = leg() and ok
        except Exception as exc:  # noqa: BLE001 — a crashed leg is a
            # failed gate with a named cause, never a bare traceback
            ok = False
            out.setdefault("errors", []).append(
                f"{name}: {type(exc).__name__}: {str(exc)[:300]}")
    out["ok"] = ok
    print(json.dumps(out) if getattr(args, "as_json", False)
          else _smoke_text(out))
    return 0 if ok else 1


def _smoke_text(out: Dict[str, Any]) -> str:
    lines = [f"monitor --smoke: {'ok' if out['ok'] else 'FAILED'}"]
    op = out.get("off_pin") or {}
    lines.append(f"  off-pin: {'ok' if op.get('ok') else 'FAILED'} "
                 f"(params identical={op.get('params_bitwise_identical')}"
                 f", program identical={op.get('program_byte_identical')})")
    leg = out.get("goodput_leg") or {}
    g = leg.get("goodput") or {}
    lines.append(
        f"  goodput: {'ok' if leg.get('ok') else 'FAILED'} "
        f"(restarts={leg.get('restarts')}, "
        f"wall={g.get('wall_s')}s, sum={g.get('buckets_sum_s')}s, "
        f"backoff={((g.get('buckets') or {}).get('backoff_s'))}s, "
        f"replay={((g.get('buckets') or {}).get('rollback_replay_s'))}s)"
        + (f" — {leg.get('error')}" if leg.get("error") else ""))
    lines.append(f"  flagship drift: "
                 f"{'ok' if out.get('flagship_drift_ok') else 'FAILED'} "
                 f"(verdict="
                 f"{(out.get('flagship_drift') or {}).get('verdict')})")
    for err in out.get("errors", ()):
        lines.append(f"  error: {err}")
    return "\n".join(lines)
