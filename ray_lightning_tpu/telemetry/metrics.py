"""Live serving metrics: counters, gauges, mergeable histograms, a
bounded tick time-series, and the replica flight recorder.

`spans.py` answers "where did host wall-clock go, per completed region";
this module answers the question serving autoscale actually asks:
**what is the load right now, and what was it over the last N ticks?**
The serving hot loop (serve/scheduler.py `tick()`) emits per-tick
gauges (queue depth, decoding/prefilling slot counts, pool pressure)
and monotonic counters (admissions, preemptions, growth stalls); the
completion path observes latency histograms (queue_wait / TTFT / TPOT).
All of it lands in one `MetricsRegistry` per replica, flushed to
uid-tagged JSONL on the engine's tick cadence — the same RLT501
discipline as the span recorder: a bounded ring in memory, file I/O
only every `flush_every_n_ticks`, never per tick.

Three properties are load-bearing and test-pinned
(tests/test_serve_metrics.py):

* **zero overhead when off** — `NULL_METRICS` is the off switch; the
  engine's compiled step never depends on the registry (metrics off or
  on lowers a byte-identical program), every recorded value is plain
  host numpy/python (no jax arrays, no new host syncs);
* **exact merge** — histograms use a FIXED log-bucket layout
  (`HIST_LO * HIST_GROWTH**i`), so merging across replicas, attempts,
  and files is integer bucket-count addition: order-independent, and
  quantiles computed from merged buckets are deterministic — the
  run-level TTFT p99 is the same number no matter which replica's file
  is read first;
* **bounded memory** — the tick ring is a `deque(maxlen=...)`;
  overwrites of unflushed samples are counted (`_dropped` lines), never
  silently lost.

The **flight recorder** is the crash-time sibling: a bounded deque of
recent ticks + scheduler events, atomically persisted to a per-replica
file on a cadence, which the DRIVER finalizes into ``flight.json``
(stamped with the resilience classification) when a replica dies — a
SIGKILLed worker cannot write a postmortem, so the last
cadence-persisted ring IS the postmortem (docs/OBSERVABILITY.md
"flight recorder").
"""
from __future__ import annotations

import collections
import itertools
import json
import math
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ray_lightning_tpu.analysis.lockwatch import san_lock

METRICS_VERSION = "rlt-metrics-v1"
FLIGHT_VERSION = "rlt-flight-v1"

# ---- the fixed histogram layout -------------------------------------------
# Every histogram in the system shares one bucket geometry so merge is
# ALWAYS legal bucket-count addition. Quarter-octave buckets: boundary
# i sits at HIST_LO * 2**(i/4) — ~19% resolution per bucket, spanning
# 0.1 ms .. ~28 min in 96 buckets. Bucket 0 is the underflow bucket
# (values < HIST_LO, including 0), bucket n_buckets+1 the overflow.

HIST_LO = 1e-4
HIST_GROWTH = 2.0 ** 0.25
HIST_BUCKETS = 96


class Histogram:
    """Fixed-log-bucket histogram with EXACT merge semantics.

    Counts are integers in a sparse dict keyed by bucket index; merge
    is integer addition, so cross-replica aggregation is associative,
    commutative, and lossless — p50/p95/p99 computed from merged
    buckets are deterministic regardless of merge order (test-pinned).
    ``min``/``max``/``sum`` merge exactly too (min of mins, max of
    maxes, sum of sums).
    """

    __slots__ = ("lo", "growth", "n_buckets", "counts", "n", "sum",
                 "min", "max", "_inv_log_g")

    def __init__(self, lo: float = HIST_LO, growth: float = HIST_GROWTH,
                 n_buckets: int = HIST_BUCKETS):
        if lo <= 0 or growth <= 1 or n_buckets < 1:
            raise ValueError(
                f"histogram layout lo={lo} growth={growth} "
                f"n_buckets={n_buckets}")
        self.lo = lo
        self.growth = growth
        self.n_buckets = n_buckets
        self.counts: Dict[int, int] = {}
        self.n = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._inv_log_g = 1.0 / math.log(growth)

    # ---- layout ----------------------------------------------------------

    def layout(self) -> dict:
        return {"lo": self.lo, "growth": self.growth,
                "n_buckets": self.n_buckets}

    def same_layout(self, other: "Histogram") -> bool:
        return (self.lo == other.lo and self.growth == other.growth
                and self.n_buckets == other.n_buckets)

    def bucket_index(self, value: float) -> int:
        """0 = underflow (< lo, incl. 0/negative); 1..n_buckets = the
        log buckets; n_buckets + 1 = overflow."""
        if value < self.lo:
            return 0
        i = int(math.floor(math.log(value / self.lo) * self._inv_log_g))
        return min(i + 1, self.n_buckets + 1)

    def bucket_upper(self, idx: int) -> float:
        """Inclusive-upper boundary of bucket ``idx`` — the value a
        quantile read from this bucket reports (conservative: the true
        sample is <= this)."""
        if idx <= 0:
            return self.lo
        if idx > self.n_buckets:
            return self.max if self.max is not None else math.inf
        return self.lo * self.growth ** idx

    # ---- recording / merging ---------------------------------------------

    def observe(self, value: float) -> None:
        value = float(value)
        idx = self.bucket_index(value)
        self.counts[idx] = self.counts.get(idx, 0) + 1
        self.n += 1
        self.sum += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)

    def merge(self, other: "Histogram") -> "Histogram":
        """In-place exact merge; layouts must match."""
        if not self.same_layout(other):
            raise ValueError(
                f"histogram layout mismatch: {self.layout()} vs "
                f"{other.layout()} — merge would be lossy")
        for idx, c in other.counts.items():
            self.counts[idx] = self.counts.get(idx, 0) + c
        self.n += other.n
        self.sum += other.sum
        for attr, pick in (("min", min), ("max", max)):
            a, b = getattr(self, attr), getattr(other, attr)
            setattr(self, attr, b if a is None else
                    (a if b is None else pick(a, b)))
        return self

    # ---- reading ---------------------------------------------------------

    def quantile(self, q: float) -> Optional[float]:
        """The bucket-upper-bound quantile: the smallest bucket boundary
        B such that at least ``ceil(q * n)`` observations are <= B.
        Computed from counts only — exact under merge."""
        if self.n == 0:
            return None
        target = max(1, math.ceil(min(max(q, 0.0), 1.0) * self.n))
        cum = 0
        bound = self.bucket_upper(self.n_buckets + 1)
        for idx in sorted(self.counts):
            cum += self.counts[idx]
            if cum >= target:
                bound = self.bucket_upper(idx)
                break
        # a bucket's upper edge can exceed the true maximum; ``max``
        # merges exactly (max of maxes), so the clamp stays
        # order-independent
        return min(bound, self.max) if self.max is not None else bound

    def mean(self) -> Optional[float]:
        return self.sum / self.n if self.n else None

    def sketch(self) -> List[Tuple[float, int]]:
        """The auditable tail: nonzero ``(bucket_upper, count)`` pairs,
        ascending — what `report` prints so a p99 is checkable against
        its own buckets rather than taken on faith."""
        return [(self.bucket_upper(idx), self.counts[idx])
                for idx in sorted(self.counts)]

    def to_dict(self) -> dict:
        d = {"lo": self.lo, "growth": self.growth,
             "n_buckets": self.n_buckets, "n": self.n,
             "sum": round(self.sum, 9),
             "counts": {str(k): v for k, v in sorted(self.counts.items())}}
        if self.min is not None:
            d["min"] = self.min
            d["max"] = self.max
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Histogram":
        h = cls(lo=d.get("lo", HIST_LO),
                growth=d.get("growth", HIST_GROWTH),
                n_buckets=d.get("n_buckets", HIST_BUCKETS))
        h.counts = {int(k): int(v)
                    for k, v in (d.get("counts") or {}).items()}
        h.n = int(d.get("n", sum(h.counts.values())))
        h.sum = float(d.get("sum", 0.0))
        h.min = d.get("min")
        h.max = d.get("max")
        return h


def merge_histograms(hists: Iterable[Histogram]) -> Optional[Histogram]:
    """Exact merge of any number of same-layout histograms (None when
    the iterable is empty). Order-independent by construction."""
    out: Optional[Histogram] = None
    for h in hists:
        if out is None:
            out = Histogram(lo=h.lo, growth=h.growth,
                            n_buckets=h.n_buckets)
        out.merge(h)
    return out


#: per-process registry/flight sequence — same discipline as the span
#: recorder's: a respawned attempt or a second registry in one process
#: gets its OWN files, never truncates an earlier stream
_FILE_SEQ = itertools.count()


class MetricsRegistry:
    """One replica's live metrics: counters + gauges sampled into a
    bounded per-tick ring, latency histograms, cadenced JSONL flush.

    ``directory=None`` records in memory only (unit tests, the bench's
    in-process serving leg). With a directory, ``flush()`` appends the
    ring's unflushed tick samples and a cumulative histogram snapshot
    to ``<directory>/replica<r>.<uid>.metrics.jsonl``; ``tick_end()``
    calls it every ``flush_every_n_ticks`` — never per tick (RLT501).

    Thread-safe for the same reason the span recorder is: the driver's
    queue-pump thread may read while the serve loop writes.
    """

    enabled = True

    def __init__(self, directory: Optional[str] = None, replica: int = 0,
                 ring_size: int = 2048, flush_every_n_ticks: int = 32,
                 prefix: str = "replica"):
        self.directory = directory
        self.replica = replica
        #: file-name prefix: "replica" streams feed the load signal /
        #: aggregation globs; other prefixes ("driver" — the autoscale
        #: session's scale/deferral counters) are read by their own
        #: consumers and deliberately stay OUT of the replica rollups
        self.prefix = prefix
        self.flush_every_n_ticks = max(1, flush_every_n_ticks)
        self._lock = san_lock("telemetry.metrics.recorder")
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Histogram] = {}
        self._ring: collections.deque = collections.deque(maxlen=ring_size)
        self._ticks = 0
        self._dropped = 0
        self._dropped_total = 0
        self.t0_perf = time.perf_counter()
        self.t0_wall = time.time()
        self.uid = f"{os.getpid()}-{next(_FILE_SEQ)}"
        self._path: Optional[str] = None
        if directory is not None:
            os.makedirs(directory, exist_ok=True)
            self._path = os.path.join(
                directory, f"{prefix}{replica}.{self.uid}.metrics.jsonl")
            with open(self._path, "w") as f:
                f.write(json.dumps({
                    "version": METRICS_VERSION, "replica": replica,
                    "t0_wall": self.t0_wall, "pid": os.getpid(),
                    "uid": self.uid,
                    "hist": {"lo": HIST_LO, "growth": HIST_GROWTH,
                             "n_buckets": HIST_BUCKETS},
                }) + "\n")

    # ---- recording (all plain python/numpy scalars — never jax) ----------

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + int(n)

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.observe(float(value))

    def tick_end(self) -> None:
        """Close one scheduler tick: snapshot every gauge + cumulative
        counter into the ring as one sample, flush on the cadence."""
        with self._lock:
            self._ticks += 1
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
                self._dropped_total += 1
            self._ring.append({
                "tick": self._ticks,
                "t": round(time.perf_counter() - self.t0_perf, 6),
                "g": dict(self._gauges),
                "c": dict(self._counters),
            })
            due = self._ticks % self.flush_every_n_ticks == 0
        if due:
            self.flush()

    # ---- reading ---------------------------------------------------------

    @property
    def ticks(self) -> int:
        return self._ticks

    @property
    def dropped(self) -> int:
        return self._dropped_total

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def histogram(self, name: str) -> Optional[Histogram]:
        with self._lock:
            return self._hists.get(name)

    def histograms(self) -> Dict[str, Histogram]:
        with self._lock:
            return dict(self._hists)

    def ring(self) -> List[dict]:
        """The in-memory tick window (newest last) — what `monitor
        --serve` and `load_signal()` read for the rolling view."""
        with self._lock:
            return list(self._ring)

    # ---- flush -----------------------------------------------------------

    def flush(self) -> int:
        """Append unflushed tick samples + a cumulative histogram
        snapshot line. Reader contract: the LAST ``hists`` line in the
        file is the current state (cumulative, so a torn earlier line
        costs nothing)."""
        if self._path is None:
            return 0
        with self._lock:
            batch = list(self._ring)
            self._ring.clear()
            dropped, self._dropped = self._dropped, 0
            hists = {name: h.to_dict() for name, h in self._hists.items()}
        if not batch and not dropped and not hists:
            return 0
        with open(self._path, "a") as f:
            for entry in batch:
                f.write(json.dumps(entry) + "\n")
            if dropped:
                f.write(json.dumps({"_dropped": dropped}) + "\n")
            if hists:
                f.write(json.dumps({"hists": hists}) + "\n")
        return len(batch)

    def close(self) -> None:
        self.flush()


class NullMetrics:
    """metrics=off: the same surface, every call a no-op, `enabled`
    False so hot-path call sites can skip even the cheap host-side
    value computation."""

    enabled = False
    directory = None
    replica = 0
    ticks = 0
    dropped = 0
    uid = "null"

    def count(self, name: str, n: int = 1) -> None: ...
    def gauge(self, name: str, value: float) -> None: ...
    def observe(self, name: str, value: float) -> None: ...
    def tick_end(self) -> None: ...

    def counters(self) -> Dict[str, int]:
        return {}

    def gauges(self) -> Dict[str, float]:
        return {}

    def histogram(self, name: str) -> Optional[Histogram]:
        return None

    def histograms(self) -> Dict[str, Histogram]:
        return {}

    def ring(self) -> List[dict]:
        return []

    def flush(self) -> int:
        return 0

    def close(self) -> None: ...


#: the shared off-switch instance call sites default to
NULL_METRICS = NullMetrics()


def read_metrics(path: str,
                 tail_bytes: Optional[int] = None) -> Dict[str, Any]:
    """Parse one replica's metrics JSONL: ``{"header": {...}, "ticks":
    [...], "hists": {name: Histogram}, "counters": {...}, "gauges":
    {...}, "dropped": n}``. ``counters``/``gauges`` are the newest tick
    sample's (cumulative counters — the file's final word). Unparseable
    lines are counted, not fatal: a SIGKILL mid-flush must still report
    what landed. ``tail_bytes`` bounds the read to the header + the
    file's last N bytes (RLT503 — the newest ticks and the LAST
    cumulative ``hists`` snapshot both live at the end, so the live
    views this serves lose nothing)."""
    from ray_lightning_tpu.telemetry.spans import ledger_tail_lines

    header: Dict[str, Any] = {}
    ticks: List[dict] = []
    hists: Dict[str, Histogram] = {}
    dropped = 0
    bad = 0
    first, body = ledger_tail_lines(path, tail_bytes)
    for i, line in enumerate([first] + body):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            bad += 1
            continue
        if not isinstance(obj, dict):
            bad += 1
            continue
        if i == 0 and obj.get("version") == METRICS_VERSION:
            header = obj
            continue
        if "_dropped" in obj:
            dropped += int(obj["_dropped"])
            continue
        if "hists" in obj:
            # cumulative snapshots: the last one wins
            hists = {name: Histogram.from_dict(d)
                     for name, d in obj["hists"].items()}
            continue
        if "tick" in obj:
            ticks.append(obj)
    last = ticks[-1] if ticks else {}
    return {"header": header, "ticks": ticks, "hists": hists,
            "counters": dict(last.get("c") or {}),
            "gauges": dict(last.get("g") or {}),
            "dropped": dropped, "unparseable_lines": bad}


def metrics_paths(directory: str) -> List[str]:
    """Every replica metrics file under a telemetry dir, sorted —
    respawned attempts contribute one file each."""
    import glob as _glob

    return sorted(_glob.glob(
        os.path.join(directory, "replica*.metrics.jsonl")))


def driver_metrics_paths(directory: str) -> List[str]:
    """The autoscale session's driver-level metrics stream(s)
    (prefix="driver": scale events, submit deferrals, live-replica
    gauges) — kept out of the replica rollups above by file name."""
    import glob as _glob

    return sorted(_glob.glob(
        os.path.join(directory, "driver*.metrics.jsonl")))


# ---- cross-file aggregation (report / monitor / the load signal) ----------


def quantile_block(hist: Histogram) -> dict:
    """p50/p95/p99 + count/sum + the bucket sketch for one merged
    histogram — quantiles from BUCKETS, never samples, so the numbers
    are identical no matter which replica's file merged first."""
    return {
        "n": hist.n,
        "p50": hist.quantile(0.50),
        "p95": hist.quantile(0.95),
        "p99": hist.quantile(0.99),
        "mean": hist.mean(),
        "max": hist.max,
        "sketch": [[round(le, 6), c] for le, c in hist.sketch()],
    }


def read_all_metrics(directory: str,
                     tail_bytes: Optional[int] = None
                     ) -> List[Dict[str, Any]]:
    """Parse every replica metrics JSONL under ``directory`` once —
    the shared substrate of `aggregate_from_parsed` and
    `newest_from_parsed`, so one report/summary pass never re-reads a
    file. ``tail_bytes`` bounds each file's read (cadence-polled
    callers: the load signal, `monitor --follow`, watch evaluation —
    RLT503)."""
    out: List[Dict[str, Any]] = []
    for path in metrics_paths(directory):
        try:
            out.append(read_metrics(path, tail_bytes=tail_bytes))
        except OSError:
            continue
    return out


def _header_t0(parsed: Dict[str, Any]) -> float:
    return float(parsed["header"].get("t0_wall") or 0.0)


def aggregate_metrics_dir(directory: str) -> Optional[Dict[str, Any]]:
    """`aggregate_from_parsed` over a directory (one parse pass)."""
    return aggregate_from_parsed(read_all_metrics(directory))


def aggregate_from_parsed(
        parsed_list: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Merge parsed replica metrics files into one run-level view:
    summed counters, exactly-merged latency histograms
    (`quantile_block` each), per-replica tick/attempt counts, and
    queue-depth/occupancy series stats. None when the list is empty.
    "Last" values (``last_tick_t``, ``blocks_free_last``) come from the
    NEWEST attempt by header ``t0_wall`` — never from whichever file
    happened to sort last lexically (pids don't sort by age)."""
    if not parsed_list:
        return None
    counters: Dict[str, int] = {}
    hist_parts: Dict[str, List[Histogram]] = {}
    replicas: Dict[str, dict] = {}
    newest_t0: Dict[str, float] = {}
    qd_series: List[float] = []
    occ_series: List[float] = []
    blocks_free_last: Optional[float] = None
    blocks_free_t0 = -1.0
    dropped = 0
    for parsed in parsed_list:
        rep = str(parsed["header"].get("replica", "?"))
        t0 = _header_t0(parsed)
        info = replicas.setdefault(
            rep, {"files": 0, "ticks": 0, "last_tick_t": None})
        info["files"] += 1
        info["ticks"] += len(parsed["ticks"])
        dropped += parsed["dropped"]
        for name, v in parsed["counters"].items():
            counters[name] = counters.get(name, 0) + int(v)
        for name, h in parsed["hists"].items():
            hist_parts.setdefault(name, []).append(h)
        for sample in parsed["ticks"]:
            g = sample.get("g") or {}
            if "queue_depth" in g:
                qd_series.append(float(g["queue_depth"]))
            if "slot_occupancy" in g:
                occ_series.append(float(g["slot_occupancy"]))
        if parsed["ticks"] and t0 >= newest_t0.get(rep, -1.0):
            newest_t0[rep] = t0
            info["last_tick_t"] = parsed["ticks"][-1].get("t")
            g = parsed["gauges"]
            if "blocks_free" in g and t0 >= blocks_free_t0:
                blocks_free_t0 = t0
                blocks_free_last = float(g["blocks_free"])
    out: Dict[str, Any] = {
        "replicas": replicas,
        "counters": counters,
        "latency": {name: quantile_block(h) for name, h in
                    ((n, merge_histograms(parts)) for n, parts in
                     sorted(hist_parts.items())) if h is not None},
        "dropped_tick_samples": dropped,
    }
    if qd_series:
        s = sorted(qd_series)
        out["queue_depth"] = {
            "p50": s[len(s) // 2], "max": s[-1],
            "mean": sum(s) / len(s), "ticks": len(s)}
    if occ_series:
        out["slot_occupancy_mean"] = sum(occ_series) / len(occ_series)
    if blocks_free_last is not None:
        out["blocks_free_last"] = blocks_free_last
    return out


#: how many of each replica's NEWEST tick samples the load signal
#: averages over — live pressure, not run-lifetime means
LOAD_SIGNAL_WINDOW = 64


def newest_from_parsed(
        parsed_list: List[Dict[str, Any]]) -> Dict[str, dict]:
    """The NEWEST parsed metrics file per replica (by header t0_wall —
    respawned attempts supersede), as ``{replica: {"t0": t0_wall,
    "parsed": ...}}``."""
    newest: Dict[str, dict] = {}
    for parsed in parsed_list:
        rep = str(parsed["header"].get("replica", "?"))
        t0 = _header_t0(parsed)
        prev = newest.get(rep)
        if prev is None or t0 >= prev["t0"]:
            newest[rep] = {"t0": t0, "parsed": parsed}
    return newest


def newest_metrics_per_replica(directory: str,
                               tail_bytes: Optional[int] = None
                               ) -> Dict[str, dict]:
    """`newest_from_parsed` over a directory — the substrate of
    `load_signal_from_dir` and `monitor --serve`; callers that also
    aggregate should `read_all_metrics` once and use the
    ``_from_parsed`` forms so no file is parsed twice."""
    return newest_from_parsed(
        read_all_metrics(directory, tail_bytes=tail_bytes))


def signal_tail_bytes(window: int) -> int:
    """The per-file read bound a ``window``-tick signal needs: the
    newest ``window`` samples plus the trailing hists/gauge lines, with
    generous slack per line. The load signal only ever summarizes the
    window, so bounding the READ to it is lossless — and keeps every
    cadence-polled signal read O(window), not O(run length) (RLT503)."""
    return max(64 * 1024, int(window) * 1024)


def load_signal_from_dir(directory: str,
                         window: int = LOAD_SIGNAL_WINDOW,
                         tail_bytes: Optional[int] = None) -> dict:
    """The queue-depth/occupancy oracle summary over the newest metrics
    file per replica — `serve.driver.load_signal` is the documented
    run-dir-level wrapper (docs/OBSERVABILITY.md "load signal"). Reads
    are tail-bounded by default (`signal_tail_bytes(window)`): the
    signal is a rolling-window summary, so a cadence-polled read never
    needs the whole ledger."""
    if tail_bytes is None:
        tail_bytes = signal_tail_bytes(window)
    return load_signal_from_parsed(
        newest_metrics_per_replica(directory, tail_bytes=tail_bytes),
        window=window, where=directory)


def load_signal_from_parsed(newest_per_replica: Dict[str, dict],
                            window: int = LOAD_SIGNAL_WINDOW,
                            where: str = "this run") -> dict:
    """`load_signal_from_dir` over an already-parsed
    `newest_metrics_per_replica` map — callers that just read the files
    for their own view (monitor --serve) reuse the parse."""
    if not newest_per_replica:
        return {"available": False,
                "reason": "no serve metrics recorded under "
                          f"{where} (metrics off, or nothing "
                          "served)"}
    qd_window: List[float] = []
    occ_window: List[float] = []
    qd_now = 0.0
    total_slots = 0.0
    blocks_free_fraction: Optional[float] = None
    per_replica: Dict[str, dict] = {}
    retired: List[str] = []
    # per-traffic-class pooling (scheduler with SLOConfig armed emits
    # queue_depth_<class> gauges + sheds_<class> counters; absent on a
    # priority-off run, so the signal shape stays historical there)
    cls_qd_window: Dict[str, List[float]] = {}
    cls_qd_now: Dict[str, float] = {}
    cls_sheds: Dict[str, float] = {}
    for rep, entry in sorted(newest_per_replica.items()):
        parsed = entry["parsed"]
        g_last = parsed["gauges"]
        if g_last.get("retired"):
            # a scale-down stamped this replica retired at drain
            # completion (serve/driver.py): its file stays on disk but
            # its stale window must not dilute the LIVE pressure — a
            # retired replica's trailing zeros would halve the pooled
            # p50 and talk the controller out of a needed scale-up
            retired.append(rep)
            continue
        recent = parsed["ticks"][-window:]
        qd = [float((s.get("g") or {}).get("queue_depth", 0.0))
              for s in recent]
        occ = [float((s.get("g") or {}).get("slot_occupancy", 0.0))
               for s in recent]
        qd_window.extend(qd)
        occ_window.extend(occ)
        qd_now += float(g_last.get("queue_depth", 0.0))
        for name, v in g_last.items():
            if name.startswith("queue_depth_"):
                cls = name[len("queue_depth_"):]
                cls_qd_now[cls] = cls_qd_now.get(cls, 0.0) + float(v)
                cls_qd_window.setdefault(cls, []).extend(
                    float((s.get("g") or {}).get(name, 0.0))
                    for s in recent)
        for name, v in parsed["counters"].items():
            if name.startswith("sheds_"):
                cls = name[len("sheds_"):]
                cls_sheds[cls] = cls_sheds.get(cls, 0.0) + float(v)
        total_slots += (g_last.get("decoding_slots", 0.0)
                        + g_last.get("prefilling_slots", 0.0)
                        + g_last.get("free_slots", 0.0))
        bf, biu = g_last.get("blocks_free"), g_last.get("blocks_in_use")
        if bf is not None and biu is not None and (bf + biu) > 0:
            frac = bf / (bf + biu)
            blocks_free_fraction = (frac if blocks_free_fraction is None
                                    else min(blocks_free_fraction, frac))
        per_replica[rep] = {
            "queue_depth": g_last.get("queue_depth"),
            "occupancy": (sum(occ) / len(occ)) if occ else None,
            "ticks": len(parsed["ticks"]),
        }
    if not per_replica:
        return {"available": False,
                "reason": "every replica reporting under "
                          f"{where} is retired (scaled away)",
                "replicas_retired": len(retired)}
    qd_sorted = sorted(qd_window) or [0.0]
    qd_p50 = qd_sorted[len(qd_sorted) // 2]
    signal: Dict[str, Any] = {
        "available": True,
        "replicas_reporting": len(per_replica),
        "queue_depth_now": qd_now,
        "queue_depth_p50": qd_p50,
        "queue_depth_max": qd_sorted[-1],
        "occupancy": (sum(occ_window) / len(occ_window))
        if occ_window else 0.0,
        "total_slots": total_slots,
        "pressure": qd_p50 / total_slots if total_slots else None,
        "window_ticks": len(qd_window),
        "replicas": per_replica,
    }
    if retired:
        signal["replicas_retired"] = len(retired)
    if blocks_free_fraction is not None:
        signal["blocks_free_fraction"] = blocks_free_fraction
    # flat per-class fields (watch selectors + autoscale read these by
    # name: load.pressure_latency_critical etc.) — present only when a
    # traffic-aware scheduler reported per-class gauges
    for cls in sorted(cls_qd_now):
        win = sorted(cls_qd_window.get(cls) or [0.0])
        p50 = win[len(win) // 2]
        signal[f"queue_depth_now_{cls}"] = cls_qd_now[cls]
        signal[f"pressure_{cls}"] = (p50 / total_slots
                                     if total_slots else None)
    for cls in sorted(cls_sheds):
        signal[f"sheds_{cls}"] = cls_sheds[cls]
    return signal


# ---- flight recorder -------------------------------------------------------


class FlightRecorder:
    """A bounded deque of recent ticks + scheduler events, atomically
    persisted on a cadence — the black box a dead replica leaves
    behind.

    The worker CANNOT write at death (SIGKILL gives no handler), so the
    recorder persists its ring every ``persist_every`` events via
    write-to-tmp + ``os.replace`` — the file on disk is always a
    complete, parseable JSON document at most one cadence behind the
    crash. The driver finalizes it into the run-level ``flight.json``
    with the resilience classification stamped on
    (`finalize_flight`)."""

    enabled = True

    def __init__(self, path: Optional[str] = None, replica: int = 0,
                 maxlen: int = 256, persist_every: int = 16):
        self.path = path
        self.replica = replica
        self.persist_every = max(1, persist_every)
        self.events: collections.deque = collections.deque(maxlen=maxlen)
        self._since_persist = 0
        self._lock = san_lock("telemetry.metrics.flight")
        self.t0_perf = time.perf_counter()
        self.t0_wall = time.time()
        self.uid = f"{os.getpid()}-{next(_FILE_SEQ)}"
        if path is not None:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            # persist the EMPTY ring immediately: the per-replica path
            # is shared across respawned attempts, and a respawn that
            # dies before its first cadence must leave THIS attempt's
            # (empty) ring — never a stale predecessor's events for the
            # driver to stamp the new death onto
            self.persist()

    def record(self, kind: str, **fields: Any) -> None:
        with self._lock:
            entry = {"t": round(time.perf_counter() - self.t0_perf, 6),
                     "kind": kind}
            entry.update(fields)
            self.events.append(entry)
            self._since_persist += 1
            due = (self.path is not None
                   and self._since_persist >= self.persist_every)
            if due:
                self._since_persist = 0
        if due:
            self.persist()

    def persist(self) -> None:
        """Atomic rewrite: the on-disk document is always complete."""
        if self.path is None:
            return
        with self._lock:
            doc = {"version": FLIGHT_VERSION, "replica": self.replica,
                   "pid": os.getpid(), "uid": self.uid,
                   "t0_wall": self.t0_wall,
                   "events": list(self.events)}
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, self.path)

    def close(self) -> None:
        self.persist()


class NullFlightRecorder:
    """flight=off: same surface, no ring, no I/O."""

    enabled = False
    path = None
    replica = 0
    events: collections.deque = collections.deque(maxlen=1)

    def record(self, kind: str, **fields: Any) -> None: ...
    def persist(self) -> None: ...
    def close(self) -> None: ...


NULL_FLIGHT = NullFlightRecorder()


def flight_path(directory: str, replica: int) -> str:
    """Where replica ``replica``'s live flight ring persists."""
    return os.path.join(directory, f"replica{replica}.flight.json")


def read_flight(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if doc.get("version") != FLIGHT_VERSION:
        return None
    return doc


#: serializes the read-modify-write of the run-level flight.json: the
#: driver finalizes deaths from one thread PER REPLICA, and two
#: replicas dying together (node OOM kills both) must append two
#: dumps, not race each other's rewrite
_FLIGHT_OUT_LOCK = san_lock("telemetry.metrics.flight_out")


def finalize_flight(telemetry_dir: str, replica: int, death: dict,
                    out_path: str) -> Optional[dict]:
    """Driver-side postmortem assembly: read the dead replica's last
    persisted flight ring, stamp the resilience classification
    (``death`` — kind/cause/detail/restartable + restart count), and
    append the dump to the run-level ``flight.json``. Returns the dump
    (None when the replica never persisted a ring — e.g. it died before
    its first cadence; the death stamp is still appended so the
    postmortem names the gap instead of hiding it). Thread-safe: the
    append is serialized and the tmp file is uniquely named, so
    concurrent replica deaths each land their dump."""
    ring = read_flight(flight_path(telemetry_dir, replica))
    dump: Dict[str, Any] = {
        "replica": replica,
        "death": dict(death),
        "dumped_at_wall": time.time(),
    }
    if ring is not None:
        dump["uid"] = ring.get("uid")
        dump["t0_wall"] = ring.get("t0_wall")
        dump["events"] = ring.get("events", [])
    else:
        dump["events"] = []
        dump["note"] = ("no persisted flight ring — the replica died "
                        "before its first persist cadence")
    with _FLIGHT_OUT_LOCK:
        doc = {"version": FLIGHT_VERSION, "dumps": []}
        try:
            with open(out_path) as f:
                prev = json.load(f)
            if prev.get("version") == FLIGHT_VERSION and \
                    isinstance(prev.get("dumps"), list):
                doc = prev
        except (OSError, json.JSONDecodeError):
            pass
        doc["dumps"].append(dump)
        tmp = f"{out_path}.tmp.{os.getpid()}-{next(_FILE_SEQ)}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2)
        os.replace(tmp, out_path)
    return dump
