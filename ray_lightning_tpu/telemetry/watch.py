"""Declarative SLO watch rules over the persisted evidence streams.

Production operators do not watch means — they watch TAILS and budgets
(the Gemma-on-TPU serving comparison in PAPERS.md is explicit: SLO
p99s, not averages, are the product metric). This module turns that
into a declarative layer over data the run ALREADY persists:

    WatchRule(name="ttft_p99", metric="serving.ttft_p99_s", op=">",
              threshold=2.0, sustain=2, severity="page")

A `WatchEngine` evaluates its rules on the monitor/report cadence —
every evaluation is a pure function over the on-disk ledgers
(tail-bounded reads, RLT503 discipline), so watch costs the run ZERO
instrumentation when off and zero program change when on (the compiled
train/decode step is byte-identical either way, test-pinned like
telemetry=off). A breach that sustains fires ONCE per episode and
lands a self-documenting record in ``<run_dir>/incidents.jsonl``
(telemetry/incidents.py): rule, firing window, metric evidence, a
timeline excerpt of the surrounding events, and the evidence-capture
actions (profiler ``CAPTURE`` marker + forced flight persist).

Metric selectors (docs/OBSERVABILITY.md "rule grammar"):

    serving.<hist>_p<q>_s    bucket-exact quantile of a merged latency
                             histogram (hist in ttft/tpot/queue_wait,
                             q in 50/95/99)
    load.<field>             the autoscale load signal (pressure,
                             queue_depth_p50, queue_depth_now,
                             occupancy)
    goodput.<bucket|fraction> the assembled goodput report
    guard.<counter>          trainguard counters from the newest
                             checkpoint meta (streak, skipped_steps)
    restarts.count           attempts observed minus one (goodput
                             ledgers) plus serving replica deaths
                             (flight.json dumps)

A selector that cannot be evaluated (stream missing, run too young)
yields None and the rule neither fires nor clears — no signal is never
treated as a good signal (the ``available: False != zero load``
discipline, applied to SLOs).
"""
from __future__ import annotations

import argparse
import dataclasses
import glob
import json
import os
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

#: default per-ledger read bound for one watch evaluation — the watch
#: polls on a cadence, so every read is tail-bounded (RLT503)
WATCH_TAIL_BYTES = 256 * 1024

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}


@dataclasses.dataclass(frozen=True)
class WatchRule:
    """One declarative rule. ``sustain`` breaches must be observed
    before the rule fires; with ``window`` > 0 the sustain count is a
    BURN-RATE window — >= ``sustain`` breaches anywhere in the last
    ``window`` evaluations fire (K-in-window, the same shape the
    trainguard escalation uses), instead of strictly consecutive."""

    name: str
    metric: str
    op: str
    threshold: float
    sustain: int = 1
    window: int = 0
    severity: str = "page"        # "page" | "warn"
    description: str = ""

    def __post_init__(self):
        if self.op not in _OPS:
            raise ValueError(f"rule {self.name}: unknown op {self.op!r}"
                             f" (one of {sorted(_OPS)})")
        if self.sustain < 1:
            raise ValueError(f"rule {self.name}: sustain must be >= 1")
        if self.window and self.window < self.sustain:
            raise ValueError(
                f"rule {self.name}: window {self.window} < sustain "
                f"{self.sustain} could never fire")
        if self.severity not in ("page", "warn"):
            raise ValueError(
                f"rule {self.name}: severity {self.severity!r}")

    def breached(self, value: float) -> bool:
        return _OPS[self.op](value, self.threshold)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


#: the built-in rule set (docs/OBSERVABILITY.md "built-in rules") —
#: thresholds are conservative defaults; pass your own rules to tune
BUILTIN_RULES: Tuple[WatchRule, ...] = (
    WatchRule("ttft_p99", "serving.ttft_p99_s", ">", 2.0, sustain=1,
              severity="page",
              description="steady-state TTFT tail blew its SLO bound "
                          "— queueing/prefill latency grew on the "
                          "serving hot path"),
    WatchRule("goodput_fraction", "goodput.goodput_fraction", "<", 0.5,
              sustain=1, severity="warn",
              description="less than half the supervised wall made "
                          "forward progress — see the goodput buckets "
                          "for where the rest went"),
    WatchRule("queue_pressure", "load.pressure", ">", 2.0, sustain=3,
              severity="warn",
              description="sustained queue depth beyond capacity — "
                          "demand is queueing faster than replicas "
                          "drain it (autoscale clamped, or at "
                          "max_replicas)"),
    WatchRule("guard_anomaly_streak", "guard.streak", ">=", 3,
              sustain=1, severity="page",
              description="consecutive in-jit anomalies — the "
                          "trainguard is skipping updates back to "
                          "back; escalation/rollback is imminent"),
    WatchRule("restart_rate", "restarts.count", ">=", 3, sustain=1,
              severity="warn",
              description="repeated attempt/replica deaths — the "
                          "retry budget is being spent; see the "
                          "classified failures"),
)


def class_slo_rules(slo, sustain: int = 1) -> Tuple[WatchRule, ...]:
    """Per-traffic-class SLO rules from a `serve.scheduler.SLOConfig`
    (duck-typed: anything with ``.classes`` / ``.shed_classes``): one
    TTFT-p95 and one TPOT-p95 rule per class against that class's own
    merged histogram (``serving.ttft_<class>_p95_s`` — class-keyed
    hists exist only when the scheduler runs with the SLOConfig
    armed), plus one shed-visibility rule per shed class
    (``load.sheds_<class>``). A breach in ONE class fires a
    class-named incident instead of being averaged into the pooled
    tail; latency_critical breaches page, the rest warn
    (docs/SERVING.md "traffic & SLO classes")."""
    rules: list = []
    for cls in sorted(slo.classes):
        spec = slo.classes[cls]
        sev = "page" if cls == "latency_critical" else "warn"
        rules.append(WatchRule(
            f"slo_ttft_{cls}", f"serving.ttft_{cls}_p95_s", ">",
            spec.ttft_p95_s, sustain=sustain, severity=sev,
            description=f"{cls} TTFT p95 above its per-class SLO "
                        f"target ({spec.ttft_p95_s:g}s) — this "
                        "class's admission latency breached, whatever "
                        "the pooled tail says"))
        rules.append(WatchRule(
            f"slo_tpot_{cls}", f"serving.tpot_{cls}_p95_s", ">",
            spec.tpot_p95_s, sustain=sustain, severity=sev,
            description=f"{cls} TPOT p95 above its per-class SLO "
                        f"target ({spec.tpot_p95_s:g}s) — decode "
                        "progress for this class is being crowded "
                        "out"))
    for cls in slo.shed_classes:
        rules.append(WatchRule(
            f"shed_{cls}", f"load.sheds_{cls}", ">=", 1, sustain=1,
            severity="warn",
            description=f"overload shed {cls} work (typed records "
                        "with retry-after hints, never silence) — "
                        "expected under a protective burst, but a "
                        "paper trail the run must carry"))
    return tuple(rules)


@dataclasses.dataclass
class WatchConfig:
    """``watch=`` coercion target (supervisor / controller / CLI)."""

    rules: Tuple[WatchRule, ...] = BUILTIN_RULES
    #: +-N merged timeline events carried in each incident record
    excerpt_events: int = 8
    #: actuate the evidence hooks on a breach (profiler CAPTURE marker
    #: + forced flight persist) — off leaves pure record-keeping
    capture: bool = True
    #: where the profiler marker drops; None derives
    #: ``<run_dir>/rlt_profile`` (the profiler's default dir)
    profile_dir: Optional[str] = None
    #: per-ledger read bound for one evaluation (RLT503)
    tail_bytes: int = WATCH_TAIL_BYTES

    @classmethod
    def coerce(cls, value: Any) -> Optional["WatchConfig"]:
        if value is None or value is False:
            return None
        if value is True:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, (tuple, list)) and all(
                isinstance(r, WatchRule) for r in value):
            return cls(rules=tuple(value))
        raise TypeError(
            "watch= takes True, a WatchConfig, or a sequence of "
            f"WatchRule; got {type(value).__name__}")


# ---- metric surfaces -------------------------------------------------------


class MetricSurfaces:
    """One evaluation's view of the persisted metric surfaces. Each
    surface is read lazily, ONCE per evaluation, with a tail bound —
    construct a fresh instance per poll. ``value()`` returns None when
    a selector cannot be evaluated; ``evidence()`` returns the raw
    inputs behind a value so an incident is auditable against its own
    data."""

    def __init__(self, run_dir: str,
                 tail_bytes: int = WATCH_TAIL_BYTES,
                 telemetry_dir: Optional[str] = None):
        self.run_dir = run_dir
        self.tail_bytes = tail_bytes
        #: explicit telemetry dir for runs whose spans/goodput/metrics
        #: live outside <run_dir>/telemetry (TelemetryConfig(dir=...))
        self.telemetry_dir = telemetry_dir
        self._cache: Dict[str, Any] = {}

    # -- lazy surface loaders (each file parsed at most once) --------------

    def _tdir(self) -> str:
        if self.telemetry_dir is not None:
            return self.telemetry_dir
        from ray_lightning_tpu.telemetry.report import telemetry_dir

        return telemetry_dir(self.run_dir)

    def _metrics(self) -> list:
        if "metrics" not in self._cache:
            from ray_lightning_tpu.telemetry.metrics import (
                read_all_metrics,
            )

            self._cache["metrics"] = read_all_metrics(
                self._tdir(), tail_bytes=self.tail_bytes)
        return self._cache["metrics"]

    def _hists(self) -> dict:
        if "hists" not in self._cache:
            from ray_lightning_tpu.telemetry.metrics import (
                merge_histograms,
            )

            parts: Dict[str, list] = {}
            for parsed in self._metrics():
                for name, h in parsed["hists"].items():
                    parts.setdefault(name, []).append(h)
            self._cache["hists"] = {
                name: merge_histograms(hs)
                for name, hs in parts.items()}
        return self._cache["hists"]

    def _load(self) -> dict:
        if "load" not in self._cache:
            from ray_lightning_tpu.telemetry.metrics import (
                load_signal_from_parsed, newest_from_parsed,
            )

            self._cache["load"] = load_signal_from_parsed(
                newest_from_parsed(self._metrics()),
                where=self.run_dir)
        return self._cache["load"]

    def _goodput(self) -> Optional[dict]:
        if "goodput" not in self._cache:
            from ray_lightning_tpu.telemetry.goodput import read_goodput

            self._cache["goodput"] = read_goodput(self._tdir())
        return self._cache["goodput"]

    def _guard(self) -> Optional[dict]:
        """Trainguard counters from the NEWEST checkpoint meta under
        the run dir (the trainer stamps them at every save — persisted
        data, no live trainer needed)."""
        if "guard" not in self._cache:
            newest: Optional[dict] = None
            newest_step = -1
            for meta_path in glob.glob(
                    os.path.join(self.run_dir, "*", "meta.json")):
                try:
                    with open(meta_path) as f:
                        meta = json.load(f)
                except (OSError, json.JSONDecodeError):
                    continue
                g = meta.get("guard")
                if not isinstance(g, dict):
                    continue
                step = int(meta.get("global_step", -1) or -1)
                if step > newest_step:
                    newest_step = step
                    newest = {**g, "global_step": step,
                              "blessed": meta.get("blessed")}
            self._cache["guard"] = newest
        return self._cache["guard"]

    def _restarts(self) -> dict:
        """Attempt/replica deaths from persisted evidence: per-rank
        goodput attempt ledgers beyond the first are restarts — the
        MAX across ranks, because a SIGKILLed rank writes no ledger
        for its dying attempt while its surviving peers do — cross-
        checked against the assembled goodput report's restart count
        when one exists; every run-level flight.json dump is a
        classified serving replica death."""
        if "restarts" not in self._cache:
            by_rank: Dict[str, int] = {}
            for path in glob.glob(os.path.join(
                    self._tdir(), "ledger.rank*.json")):
                rank = os.path.basename(path).split(".")[1]
                by_rank[rank] = by_rank.get(rank, 0) + 1
            attempts = max(by_rank.values(), default=0)
            g = self._goodput() or {}
            reported = int((g.get("events") or {}).get("restarts", 0)
                           or 0)
            if reported:
                # a SIGKILLed group can lose the dying attempt's
                # ledgers wholesale; the assembled report's restart
                # count is the floor on how many attempts there were
                attempts = max(attempts, reported + 1)
            dumps = 0
            fpath = os.path.join(self.run_dir, "flight.json")
            if os.path.exists(fpath):
                try:
                    with open(fpath) as f:
                        doc = json.load(f)
                    dumps = len(doc.get("dumps") or [])
                except (OSError, json.JSONDecodeError):
                    pass
            self._cache["restarts"] = {
                "attempts": attempts,
                "replica_deaths": dumps,
                "count": max(attempts - 1, reported, 0) + dumps,
            }
        return self._cache["restarts"]

    # -- the selector grammar ---------------------------------------------

    def value(self, selector: str) -> Optional[float]:
        group, _, field = selector.partition(".")
        if group == "serving":
            # <hist>_p<q>_s: bucket-exact quantile of the merged
            # histogram (the ONLY way a cross-replica p99 is computed
            # anywhere in the repo)
            name, _, tail = field.rpartition("_p")
            q = tail[:-2] if tail.endswith("_s") else tail
            h = self._hists().get(f"{name}_s")
            if h is None or not q.isdigit():
                return None
            return h.quantile(int(q) / 100.0)
        if group == "load":
            sig = self._load()
            if not sig.get("available"):
                return None
            v = sig.get(field)
            return float(v) if isinstance(v, (int, float)) else None
        if group == "goodput":
            g = self._goodput()
            if not g:
                return None
            if field == "goodput_fraction":
                return float(g.get("goodput_fraction", 0.0))
            v = (g.get("buckets") or {}).get(field)
            if v is None:
                v = (g.get("events") or {}).get(field)
            return float(v) if isinstance(v, (int, float)) else None
        if group == "guard":
            g = self._guard()
            if g is None:
                return None
            v = g.get(field)
            return float(v) if isinstance(v, (int, float)) else None
        if group == "restarts":
            v = self._restarts().get(field)
            return float(v) if isinstance(v, (int, float)) else None
        return None

    def evidence(self, selector: str) -> Dict[str, Any]:
        """The raw surface behind a selector, compactly — what the
        incident record carries next to the value."""
        group, _, field = selector.partition(".")
        if group == "serving":
            name = field.rpartition("_p")[0]
            h = self._hists().get(f"{name}_s")
            if h is None:
                return {}
            return {"histogram": f"{name}_s", "n": h.n,
                    "sketch": [[round(le, 6), c]
                               for le, c in h.sketch()]}
        if group == "load":
            sig = self._load()
            keys = ["available", "pressure", "queue_depth_now",
                    "queue_depth_p50", "occupancy", "total_slots",
                    "replicas_reporting"]
            if field not in keys:
                keys.append(field)  # class-scoped selectors carry
                #                     their own flat field as evidence
            return {"load_signal": {
                k: sig[k] for k in keys if k in sig}}
        if group == "goodput":
            g = self._goodput() or {}
            return {"goodput": {k: g[k] for k in
                                ("wall_s", "goodput_fraction",
                                 "buckets", "events") if k in g}}
        if group == "guard":
            g = self._guard()
            return {"guard": g} if g else {}
        if group == "restarts":
            return {"restarts": self._restarts()}
        return {}


# ---- the engine ------------------------------------------------------------


class _RuleState:
    __slots__ = ("history", "firing", "fired")

    def __init__(self):
        #: (engine poll index, breached) per evaluation that produced a
        #: value — the incident's firing window quotes these verbatim,
        #: so the record names the polls that actually sustained it
        self.history: List[Tuple[int, bool]] = []
        self.firing = False
        self.fired = 0


class WatchEngine:
    """Stateful evaluator: construct once, ``poll()`` on the monitor/
    report cadence. A rule fires once per breach EPISODE — it re-arms
    only after an evaluation observes the metric back in bounds (a
    cumulative p99 that stays high keeps the episode open: one
    incident, not one per poll)."""

    def __init__(self, run_dir: str,
                 config: Optional[WatchConfig] = None,
                 driver: Any = None,
                 clock: Callable[[], float] = time.time,
                 telemetry_dir: Optional[str] = None):
        self.run_dir = run_dir
        self.config = config or WatchConfig()
        self.driver = driver
        self._clock = clock
        #: where spans/goodput/metrics actually live when the run uses
        #: TelemetryConfig(dir=...) instead of <run_dir>/telemetry
        self.telemetry_dir = telemetry_dir
        self._state: Dict[str, _RuleState] = {
            r.name: _RuleState() for r in self.config.rules}
        self.polls = 0
        self.incidents: List[Dict[str, Any]] = []

    @property
    def fired(self) -> int:
        return len(self.incidents)

    def _should_fire(self, rule: WatchRule, st: _RuleState) -> bool:
        if rule.window:
            recent = st.history[-rule.window:]
            return sum(b for _, b in recent) >= rule.sustain
        streak = 0
        for _, b in reversed(st.history):
            if not b:
                break
            streak += 1
        return streak >= rule.sustain

    def poll(self, now: Optional[float] = None,
             driver: Any = None) -> List[Dict[str, Any]]:
        """One evaluation over the persisted surfaces. Returns the
        incidents fired by THIS poll (already appended to the
        ledger)."""
        now = self._clock() if now is None else now
        drv = driver if driver is not None else self.driver
        surfaces = MetricSurfaces(self.run_dir,
                                  tail_bytes=self.config.tail_bytes,
                                  telemetry_dir=self.telemetry_dir)
        fired: List[Dict[str, Any]] = []
        self.polls += 1
        for rule in self.config.rules:
            st = self._state[rule.name]
            value = surfaces.value(rule.metric)
            if value is None:
                # no signal is not a good signal — hold state
                continue
            breach = rule.breached(value)
            st.history.append((self.polls, breach))
            bound = max(rule.window, rule.sustain, 8)
            if len(st.history) > bound:
                del st.history[:-bound]
            if not breach:
                st.firing = False
                continue
            if st.firing or not self._should_fire(rule, st):
                continue
            st.firing = True
            st.fired += 1
            fired.append(self._fire(rule, st, value, now, surfaces,
                                    drv))
        self.incidents.extend(fired)
        return fired

    def _fire(self, rule: WatchRule, st: _RuleState, value: float,
              now: float, surfaces: MetricSurfaces,
              driver: Any) -> Dict[str, Any]:
        from ray_lightning_tpu.telemetry.incidents import (
            append_incident, build_incident, capture_evidence,
        )
        from ray_lightning_tpu.telemetry.timeline import (
            load_timeline_events, timeline_excerpt,
        )

        span = max(rule.window, rule.sustain, 1)
        window = [{"poll": p, "breached": b}
                  for p, b in st.history[-span:]]
        incident = build_incident(
            rule, value, now, window,
            evidence=surfaces.evidence(rule.metric))
        if self.config.capture:
            incident["actions"] = capture_evidence(
                self.run_dir, profile_dir=self.config.profile_dir,
                driver=driver)
        try:
            # tail-bounded: the excerpt wants the events AROUND the
            # breach (i.e. the newest), never a week of history —
            # the RLT503 discipline holds on the firing path too
            timeline = load_timeline_events(
                self.run_dir, tail_bytes=self.config.tail_bytes,
                telemetry_dir=self.telemetry_dir)
            incident["timeline_excerpt"] = timeline_excerpt(
                timeline["events"], now,
                n=self.config.excerpt_events)
        except Exception as exc:  # noqa: BLE001 — the record must land
            incident["timeline_excerpt"] = []
            incident["timeline_error"] = (
                f"{type(exc).__name__}: {str(exc)[:160]}")
        append_incident(self.run_dir, incident)
        return incident

    def summary(self) -> Dict[str, Any]:
        return {
            "polls": self.polls,
            "incidents": len(self.incidents),
            "rules": {r.name: {"fired": self._state[r.name].fired,
                               "firing": self._state[r.name].firing}
                      for r in self.config.rules},
        }


# ---- CLI -------------------------------------------------------------------


def add_watch_parser(sub) -> None:
    p = sub.add_parser(
        "watch",
        help="evaluate the declarative SLO watch rules over a run "
             "dir's persisted evidence; breaches land in "
             "incidents.jsonl with metric evidence + a timeline "
             "excerpt (docs/OBSERVABILITY.md 'watch rules & "
             "incidents'); --smoke is the format.sh gate")
    p.add_argument("run_dir", nargs="?", default=None)
    p.add_argument("--follow", action="store_true",
                   help="re-evaluate every --interval seconds until ^C")
    p.add_argument("--interval", type=float, default=15.0)
    p.add_argument("--ttft-max", type=float, default=None,
                   help="override the built-in ttft_p99 threshold "
                        "(seconds)")
    p.add_argument("--no-capture", action="store_true",
                   help="record incidents without actuating the "
                        "evidence hooks (no CAPTURE marker, no forced "
                        "flight persist)")
    p.add_argument("--smoke", action="store_true",
                   help="gate mode: injected serving latency stall "
                        "fires the ttft rule exactly once with a "
                        "parseable incident (evidence + excerpt + one "
                        "marker capture), and the run's unified "
                        "timeline exports valid Chrome-trace JSON "
                        "with >= 4 sources")
    p.add_argument("--json", action="store_true", dest="as_json",
                   default=argparse.SUPPRESS)


def _cli_rules(args) -> Tuple[WatchRule, ...]:
    rules = list(BUILTIN_RULES)
    if args.ttft_max is not None:
        rules = [dataclasses.replace(r, threshold=args.ttft_max)
                 if r.name == "ttft_p99" else r for r in rules]
    return tuple(rules)


def run_watch(args) -> int:
    if args.smoke:
        return _run_smoke(args)
    if not args.run_dir:
        print("error: pass a run dir or --smoke", file=sys.stderr)
        return 2
    if not os.path.isdir(args.run_dir):
        print(f"error: {args.run_dir} is not a directory",
              file=sys.stderr)
        return 2
    engine = WatchEngine(args.run_dir, WatchConfig(
        rules=_cli_rules(args), capture=not args.no_capture))
    as_json = getattr(args, "as_json", False)
    while True:
        fired = engine.poll()
        view = {"run_dir": args.run_dir, **engine.summary(),
                "fired_now": [i["rule"] for i in fired]}
        if as_json:
            print(json.dumps(view), flush=True)
        else:
            state = ", ".join(
                f"{name}{'!' if st['firing'] else ''}"
                for name, st in view["rules"].items())
            print(f"-- watch poll {view['polls']}: "
                  f"{len(fired)} new incident(s), "
                  f"{view['incidents']} total [{state}]")
            for inc in fired:
                ev = inc["evidence"]
                print(f"   {inc['severity'].upper()} {inc['rule']}: "
                      f"{ev['metric']} = {ev['value']:.4g} {ev['op']} "
                      f"{ev['threshold']:.4g}")
        if not args.follow:
            return 0
        time.sleep(max(0.2, args.interval))


# ---- the smoke gate --------------------------------------------------------


def _smoke_serving_run(run_dir: str, stall_s: float = 0.25):
    """A scripted serving session with one INJECTED latency stall:
    requests r0..r5 serve normally, then a late request's prefill
    window absorbs a host sleep — its measured TTFT is ~``stall_s``
    where its peers' are milliseconds, so a ttft_p99 rule with a
    threshold between the two fires deterministically. Driven under an
    autoscale controller (fabricated hold signal) so the run dir also
    carries an autoscale ledger for the timeline leg."""
    from ray_lightning_tpu.autoscale import (
        AutoscaleController, ControllerConfig, PolicyConfig,
    )
    from ray_lightning_tpu.serve.cli import _references, _tiny_setup
    from ray_lightning_tpu.serve.driver import (
        ReplicaGroupConfig, ServeDriver,
    )
    from ray_lightning_tpu.serve.engine import EngineConfig

    cfg, model, params, prompts, reqs = _tiny_setup(8, 8)
    refs = _references(model, params, prompts, reqs)
    ecfg = EngineConfig(capacity=4, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4)
    drv = ServeDriver(cfg, params, ReplicaGroupConfig(
        n_replicas=1, backend="inline", engine=ecfg, run_dir=run_dir,
        metrics_flush_every_n_ticks=2))
    drv.start()
    ctl = AutoscaleController(drv, ControllerConfig(
        policy=PolicyConfig(min_replicas=1, max_replicas=1)),
        run_dir=run_dir)
    # healthy phase: most requests flow normally
    for req in reqs[:-1]:
        drv.submit(req)
    tick = 0
    while drv.busy():
        drv.tick()
        tick += 1
        if tick % 4 == 0:
            ctl.step(now=float(tick))
    # the stall: submit the last request, admit it (first tick), then
    # stall the host mid-prefill — its admission->first-token wall
    # (TTFT) absorbs the sleep, exactly how a wedged device tunnel or
    # an interactive-priority stall shows up in production
    drv.submit(reqs[-1])
    drv.tick()
    time.sleep(stall_s)
    while drv.busy():
        drv.tick()
        tick += 1
    ctl.step(now=float(tick))
    result = drv.stop()
    return result, refs, reqs


def _run_smoke(args) -> int:
    import tempfile

    from ray_lightning_tpu.telemetry.incidents import read_incidents
    from ray_lightning_tpu.telemetry.timeline import (
        load_timeline_events, to_chrome_trace, validate_chrome_trace,
    )

    out: Dict[str, Any] = {"gate": "watch --smoke"}
    failures: List[str] = []
    stall_s = 0.25
    with tempfile.TemporaryDirectory(prefix="rlt-watch-") as tmp:
        run_dir = os.path.join(tmp, "run")
        result, refs, reqs = _smoke_serving_run(run_dir,
                                                stall_s=stall_s)
        import numpy as np

        bad = [rid for rid, ref in refs.items()
               if not np.array_equal(
                   np.asarray(result.outputs.get(rid, [])), ref)]
        if bad:
            failures.append(f"stalled run diverged from generate(): "
                            f"{bad}")
        # ---- leg 1: the rule must fire exactly once -------------------
        rules = tuple(
            dataclasses.replace(r, threshold=stall_s / 2)
            if r.name == "ttft_p99" else r for r in BUILTIN_RULES)
        engine = WatchEngine(run_dir, WatchConfig(rules=rules))
        first = engine.poll()
        second = engine.poll()   # episode stays open: no second fire
        third = engine.poll()
        parsed = read_incidents(run_dir)
        ttft_incidents = [i for i in parsed["incidents"]
                          if i.get("rule") == "ttft_p99"]
        out["watch"] = {
            "fired_first_poll": [i["rule"] for i in first],
            "fired_later_polls": [i["rule"] for i in second + third],
            "ledger_incidents": len(parsed["incidents"]),
            "ttft_incidents": len(ttft_incidents),
            "unparseable_lines": parsed["unparseable_lines"],
        }
        if [i["rule"] for i in first] != ["ttft_p99"]:
            failures.append(
                f"first poll fired {[i['rule'] for i in first]} — "
                "want exactly the injected ttft_p99 breach")
        if second or third:
            failures.append(
                "a sustained breach re-fired on later polls "
                f"({[i['rule'] for i in second + third]}) — one "
                "episode must be one incident")
        if len(ttft_incidents) != 1 or parsed["unparseable_lines"]:
            failures.append(
                f"incidents.jsonl holds {len(ttft_incidents)} ttft "
                f"record(s) ({parsed['unparseable_lines']} "
                "unparseable) — want exactly one, parseable")
        # ---- leg 2: the incident record contract ----------------------
        if ttft_incidents:
            inc = ttft_incidents[0]
            ev = inc.get("evidence") or {}
            if not (ev.get("value") and ev["value"] > stall_s / 2
                    and ev.get("sketch")):
                failures.append(
                    f"incident evidence is not auditable: {ev}")
            if not inc.get("timeline_excerpt"):
                failures.append(
                    "incident carries no timeline excerpt")
            actions = inc.get("actions") or {}
            marker = actions.get("profiler_marker")
            out["incident"] = {
                "value": ev.get("value"),
                "excerpt_events": len(inc.get("timeline_excerpt")
                                      or []),
                "actions": actions,
            }
            if not marker or not os.path.exists(marker):
                failures.append(
                    "evidence capture did not drop the profiler "
                    f"CAPTURE marker (actions={actions})")
        # ---- leg 3: unified timeline + Chrome export ------------------
        timeline = load_timeline_events(run_dir)
        doc = to_chrome_trace(timeline["events"])
        problems = validate_chrome_trace(doc)
        non_meta = [ev for ev in doc["traceEvents"]
                    if ev.get("ph") != "M"]
        cats = {ev["cat"] for ev in non_meta}
        ts_list = [ev["ts"] for ev in non_meta
                   if not (ev.get("args") or {}).get("unaligned")]
        out["timeline"] = {
            "events": len(non_meta),
            "sources": sorted(cats),
            "garbage_lines": timeline["garbage_lines"],
            "unaligned": timeline["unaligned"],
            "chrome_valid": not problems,
        }
        if problems:
            failures.append(
                f"chrome trace failed validation: {problems[:3]}")
        if len(cats) < 4:
            failures.append(
                f"trace carries {sorted(cats)} — want >= 4 distinct "
                "source subsystems in one pane")
        if ts_list != sorted(ts_list):
            failures.append(
                "aligned trace events are not ordered by aligned time")
    out["ok"] = not failures
    if failures:
        out["failures"] = failures
    print(json.dumps(out) if getattr(args, "as_json", False)
          else _smoke_text(out))
    if failures:
        for f in failures:
            print(f"watch --smoke FAILED: {f}", file=sys.stderr)
        return 1
    return 0


def _smoke_text(out: Dict[str, Any]) -> str:
    lines = [f"watch --smoke: {'ok' if out['ok'] else 'FAILED'}"]
    w = out.get("watch") or {}
    lines.append(
        f"  rule fire: {'ok' if w.get('ttft_incidents') == 1 else 'FAILED'} "
        f"(first poll {w.get('fired_first_poll')}, later "
        f"{w.get('fired_later_polls')}, ledger "
        f"{w.get('ledger_incidents')} incident(s))")
    inc = out.get("incident") or {}
    if inc:
        lines.append(
            f"  incident: ttft_p99 {inc.get('value'):.3f}s, "
            f"{inc.get('excerpt_events')} excerpt event(s), actions "
            f"{sorted((inc.get('actions') or {}))}")
    tl = out.get("timeline") or {}
    lines.append(
        f"  timeline: {'ok' if tl.get('chrome_valid') and len(tl.get('sources') or []) >= 4 else 'FAILED'} "
        f"({tl.get('events')} event(s) from {tl.get('sources')})")
    for f in out.get("failures", ()):
        lines.append(f"  FAILED: {f}")
    return "\n".join(lines)
