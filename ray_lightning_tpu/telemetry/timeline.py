"""One pane of glass: every evidence ledger a run leaves behind, merged
into a single causally-ordered event stream.

The framework persists seven disjoint evidence streams — telemetry
spans, goodput attempt ledgers, per-replica serving metrics JSONL,
flight rings, the autoscale decision ledger, the reshard ledger, and
incident records — each with its own schema and reader. Diagnosing
"why did TTFT p99 spike at 14:32" used to mean hand-joining five files
by eye. This module gives them ONE vocabulary:

    Event(wall, source, kind, rank/replica, dur_s, step, payload,
          aligned)

``wall`` is epoch seconds reconstructed from each ledger's
clock-alignment header (``t0_wall`` stamped at recorder construction +
the entry's monotonic offset — the same pair spans.py has always
carried; PR 14 stamped autoscale.jsonl and reshards.jsonl the same
way). A legacy headerless ledger still ingests — its events are tagged
``aligned=False`` and sort after the aligned stream on their raw
offsets instead of crashing the merge.

Everything here is a pure function over files the hot paths already
write: assembling a timeline costs the RUN nothing (zero new host
syncs, no program change — the watch/incident layer rides the same
guarantee, test-pinned like telemetry=off).

Surfaces:

  * ``load_timeline_events(run_dir)`` — the merged, ordered stream plus
    per-source counts and a garbage-line tally;
  * ``to_chrome_trace(events)`` — Chrome trace-event JSON
    (``chrome://tracing`` / Perfetto opens a full supervised run —
    compile, steps, ckpt stalls, restarts, reshards, replica deaths,
    scale decisions, request lifecycles — as one trace);
  * ``python -m ray_lightning_tpu timeline <run_dir> [--chrome out]`` —
    text rendering or the trace export (docs/OBSERVABILITY.md
    "unified timeline").
"""
from __future__ import annotations

import argparse
import dataclasses
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

#: every source subsystem the merger knows; an adapter contributes at
#: most one of these (the acceptance gate wants >= 4 present in a full
#: serving-run trace)
TIMELINE_SOURCES = (
    "spans",        # telemetry/spans.py rank*.spans.jsonl
    "goodput",      # telemetry/goodput.py ledger.rank*.json attempts
    "metrics",      # telemetry/metrics.py replica*/driver*.metrics.jsonl
    "flight",       # flight rings + the run-level flight.json postmortems
    "autoscale",    # autoscale/controller.py autoscale.jsonl
    "reshard",      # resilience/supervisor.py reshards.jsonl
    "incident",     # telemetry/incidents.py incidents.jsonl
)


@dataclasses.dataclass
class Event:
    """One timeline event. ``wall`` is epoch seconds when the source
    ledger carried a clock-alignment header (``aligned=True``);
    otherwise ``wall`` is the entry's RAW monotonic offset and the
    event is tagged unaligned — present, ordered among its peers, but
    not placed on the shared wall-clock axis."""

    wall: float
    source: str
    kind: str
    aligned: bool = True
    rank: Optional[int] = None
    replica: Optional[int] = None
    dur_s: Optional[float] = None
    step: Optional[int] = None
    payload: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        d: Dict[str, Any] = {"wall": round(self.wall, 6),
                             "source": self.source, "kind": self.kind}
        if not self.aligned:
            d["aligned"] = False
        for k in ("rank", "replica", "dur_s", "step"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        if self.payload:
            d["payload"] = self.payload
        return d


def _safe_float(v: Any, default: float = 0.0) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return default


def _jsonl_entries(path: str, tail_bytes: Optional[int] = None
                   ) -> Tuple[Dict[str, Any], List[dict], int]:
    """(header, entries, garbage_lines) for one JSONL ledger, on the
    shared `ledger_tail_lines` substrate (the first line is the
    clock-alignment header slot a tail-bounded read must never lose).
    The header is the first line when it carries a ``version`` field;
    garbage lines are counted, never fatal — a ledger torn by a kill
    mid-append must still contribute its readable prefix."""
    from ray_lightning_tpu.telemetry.spans import ledger_tail_lines

    header: Dict[str, Any] = {}
    entries: List[dict] = []
    bad = 0
    try:
        first, body = ledger_tail_lines(path, tail_bytes)
    except OSError:
        return header, entries, bad
    for i, line in enumerate([first] + body):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            bad += 1
            continue
        if not isinstance(obj, dict):
            bad += 1
            continue
        if i == 0 and "version" in obj:
            header = obj
            continue
        entries.append(obj)
    return header, entries, bad


# ---- per-ledger adapters ---------------------------------------------------
# Each returns (events, garbage_line_count). Adapters never raise on a
# malformed ledger: a partial run dir is the NORMAL input here.


def _events_from_spans(tdir: str, tail_bytes: Optional[int] = None
                       ) -> Tuple[List[Event], int]:
    from ray_lightning_tpu.telemetry.spans import read_spans

    events: List[Event] = []
    bad = 0
    for path in sorted(glob.glob(os.path.join(tdir,
                                              "rank*.spans.jsonl"))):
        try:
            parsed = read_spans(path, tail_bytes=tail_bytes)
        except OSError:
            continue
        bad += parsed.get("unparseable_lines", 0)
        header = parsed.get("header") or {}
        t0 = header.get("t0_wall")
        rank = header.get("rank")
        aligned = t0 is not None
        for span in parsed["spans"]:
            t = _safe_float(span.get("t"))
            payload = {}
            if span.get("thread") not in (None, "main"):
                payload["thread"] = span["thread"]
            if span.get("meta"):
                payload.update(span["meta"])
            events.append(Event(
                wall=(t0 + t) if aligned else t,
                source="spans", kind=str(span.get("phase", "?")),
                aligned=aligned,
                rank=rank if rank is not None else None,
                dur_s=span.get("dur"), step=span.get("step"),
                payload=payload))
    return events, bad


def _events_from_goodput(tdir: str) -> Tuple[List[Event], int]:
    from ray_lightning_tpu.telemetry.goodput import read_ledgers

    events: List[Event] = []
    try:
        ledgers = read_ledgers(tdir, rank=None)
    except OSError:
        return events, 0
    for led in ledgers:
        t0 = led.get("t0_wall")
        events.append(Event(
            wall=_safe_float(t0), source="goodput", kind="attempt",
            aligned=t0 is not None, rank=led.get("rank"),
            dur_s=led.get("wall_s"),
            payload={"start_step": led.get("start_step"),
                     "end_step": led.get("end_step"),
                     "completed": led.get("completed"),
                     "launch_s": led.get("launch_s")}))
    return events, 0


def _events_from_metrics(tdir: str, tail_bytes: Optional[int] = None
                         ) -> Tuple[List[Event], int]:
    from ray_lightning_tpu.telemetry.metrics import read_metrics

    events: List[Event] = []
    bad = 0
    paths = sorted(glob.glob(os.path.join(tdir, "*.metrics.jsonl")))
    for path in paths:
        try:
            parsed = read_metrics(path, tail_bytes=tail_bytes)
        except OSError:
            continue
        bad += parsed.get("unparseable_lines", 0)
        header = parsed.get("header") or {}
        t0 = header.get("t0_wall")
        aligned = t0 is not None
        replica = header.get("replica")
        driver = os.path.basename(path).startswith("driver")
        for sample in parsed["ticks"]:
            t = _safe_float(sample.get("t"))
            g = sample.get("g") or {}
            payload = {k: g[k] for k in
                       ("queue_depth", "decoding_slots", "free_slots",
                        "blocks_free", "slot_occupancy",
                        "replicas_live", "pending_requests")
                       if k in g}
            events.append(Event(
                wall=(t0 + t) if aligned else t, source="metrics",
                kind="driver_tick" if driver else "tick",
                aligned=aligned,
                replica=None if driver else replica,
                step=sample.get("tick"), payload=payload))
    return events, bad


def _events_from_flight(run_dir: str, tdir: str) -> Tuple[List[Event],
                                                          int]:
    from ray_lightning_tpu.telemetry.metrics import read_flight

    events: List[Event] = []
    bad = 0

    def _ring_events(doc: dict, replica: Optional[int]) -> None:
        t0 = doc.get("t0_wall")
        aligned = t0 is not None
        for ev in doc.get("events") or []:
            if not isinstance(ev, dict):
                continue
            t = _safe_float(ev.get("t"))
            payload = {k: v for k, v in ev.items()
                       if k not in ("t", "kind")}
            events.append(Event(
                wall=(t0 + t) if aligned else t, source="flight",
                kind=str(ev.get("kind", "?")), aligned=aligned,
                replica=replica, payload=payload))

    for path in sorted(glob.glob(os.path.join(tdir, "*.flight.json"))):
        doc = read_flight(path)
        if doc is None:
            bad += 1
            continue
        _ring_events(doc, doc.get("replica"))
    # the run-level postmortem file: per-death dumps, each its own ring
    # plus the classified death stamp
    out_path = os.path.join(run_dir, "flight.json")
    if os.path.exists(out_path):
        try:
            with open(out_path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            doc = None
            bad += 1
        if isinstance(doc, dict):
            for dump in doc.get("dumps") or []:
                if not isinstance(dump, dict):
                    continue
                _ring_events(dump, dump.get("replica"))
                at = dump.get("dumped_at_wall")
                events.append(Event(
                    wall=_safe_float(at), source="flight",
                    kind="death", aligned=at is not None,
                    replica=dump.get("replica"),
                    payload=dict(dump.get("death") or {})))
    return events, bad


def _events_from_autoscale(run_dir: str,
                           tail_bytes: Optional[int] = None
                           ) -> Tuple[List[Event], int]:
    path = os.path.join(run_dir, "autoscale.jsonl")
    if not os.path.exists(path):
        return [], 0
    header, entries, bad = _jsonl_entries(path, tail_bytes)
    t0 = header.get("t0_wall")
    events: List[Event] = []
    for e in entries:
        decision = e.get("decision") or {}
        outcome = e.get("outcome") or {}
        signal = e.get("signal") or {}
        payload: Dict[str, Any] = {
            "target": decision.get("target"),
            "reason": (decision.get("reason") or "")[:160],
            "replicas": e.get("replicas"),
            "now": e.get("now"),
        }
        if not outcome.get("ok", True):
            payload["outcome_ok"] = False
        if signal.get("pressure") is not None:
            payload["pressure"] = signal["pressure"]
        cap = e.get("capacity")
        if cap:
            payload["capacity"] = cap.get("worlds")
            payload["capacity_source"] = cap.get("source")
        # "t" is the entry's monotonic offset from the header's t0_perf
        # (stamped by the controller since PR 14); a legacy ledger has
        # neither, so its entries ride the policy's own "now" clock —
        # internally ordered, not wall-placeable
        t = e.get("t")
        aligned = t0 is not None and t is not None
        events.append(Event(
            wall=(t0 + _safe_float(t)) if aligned
            else _safe_float(e.get("now")),
            source="autoscale",
            kind=str(decision.get("action", "?")), aligned=aligned,
            dur_s=e.get("duration_s"), payload=payload))
    return events, bad


def _events_from_reshards(run_dir: str, tdir: str,
                          tail_bytes: Optional[int] = None
                          ) -> Tuple[List[Event], int]:
    events: List[Event] = []
    bad = 0
    for base in dict.fromkeys((run_dir, tdir)):
        path = os.path.join(base, "reshards.jsonl")
        if not os.path.exists(path):
            continue
        _header, entries, b = _jsonl_entries(path, tail_bytes)
        bad += b
        for e in entries:
            # reshard entries carry an epoch "at" stamp of their own;
            # the header is the uniform-schema stamp, not a decoder key
            at = e.get("at")
            events.append(Event(
                wall=_safe_float(at), source="reshard",
                kind=str(e.get("reason", "?")), aligned=at is not None,
                payload={k: e[k] for k in
                         ("from_world", "to_world", "attempt",
                          "capacity", "capacity_source")
                         if k in e}))
    return events, bad


def _events_from_incidents(run_dir: str,
                           tail_bytes: Optional[int] = None
                           ) -> Tuple[List[Event], int]:
    from ray_lightning_tpu.telemetry.incidents import read_incidents

    parsed = read_incidents(run_dir, tail_bytes=tail_bytes)
    events: List[Event] = []
    for inc in parsed["incidents"]:
        wall = inc.get("wall")
        events.append(Event(
            wall=_safe_float(wall), source="incident",
            kind=str(inc.get("rule", "?")), aligned=wall is not None,
            payload={"severity": inc.get("severity"),
                     "value": (inc.get("evidence") or {}).get("value"),
                     "threshold": (inc.get("evidence")
                                   or {}).get("threshold")}))
    return events, parsed["unparseable_lines"]


# ---- the merge -------------------------------------------------------------


def _telemetry_dir(run_dir: str) -> str:
    from ray_lightning_tpu.telemetry.report import telemetry_dir

    return telemetry_dir(run_dir)


def load_timeline_events(run_dir: str,
                         tail_bytes: Optional[int] = None,
                         telemetry_dir: Optional[str] = None
                         ) -> Dict[str, Any]:
    """Assemble the unified timeline for ``run_dir``. Returns
    ``{"events": [Event...], "sources": {source: count}, "unaligned":
    n, "garbage_lines": n}``. Events are ordered by aligned wall time
    (unaligned events sort within their source on their raw offsets,
    after the aligned stream — the merge never GUESSES a headerless
    ledger's epoch). A partial run dir — only one ledger, or none —
    returns the partial stream, never raises. ``tail_bytes`` bounds
    every per-file read (RLT503 — cadence-polled callers like the
    watch engine's excerpt pass one; the one-shot CLI reads
    everything); ``telemetry_dir`` overrides the
    ``<run_dir>/telemetry`` convention for TelemetryConfig(dir=...)
    runs."""
    tdir = telemetry_dir or _telemetry_dir(run_dir)
    # run-level ledgers (autoscale/reshards/incidents/flight.json) sit
    # BESIDE the telemetry dir; accept either dir as the argument
    base = run_dir if tdir != run_dir else os.path.dirname(run_dir)
    collected: List[Tuple[List[Event], int]] = [
        _events_from_spans(tdir, tail_bytes),
        _events_from_goodput(tdir),
        _events_from_metrics(tdir, tail_bytes),
        _events_from_flight(base, tdir),
        _events_from_autoscale(base, tail_bytes),
        _events_from_reshards(base, tdir, tail_bytes),
        _events_from_incidents(base, tail_bytes),
    ]
    events: List[Event] = []
    garbage = 0
    for evs, bad in collected:
        events.extend(evs)
        garbage += bad
    aligned = sorted((e for e in events if e.aligned),
                     key=lambda e: e.wall)
    unaligned = sorted((e for e in events if not e.aligned),
                       key=lambda e: (e.source, e.wall))
    ordered = aligned + unaligned
    sources: Dict[str, int] = {}
    for e in ordered:
        sources[e.source] = sources.get(e.source, 0) + 1
    return {"run_dir": run_dir, "telemetry_dir": tdir,
            "events": ordered, "sources": sources,
            "unaligned": len(unaligned), "garbage_lines": garbage}


def timeline_excerpt(events: List[Event], wall: float,
                     n: int = 8) -> List[dict]:
    """The +-``n`` aligned events surrounding ``wall`` — the context an
    incident record carries so a breach self-documents
    (docs/OBSERVABILITY.md "incident capture")."""
    aligned = [e for e in events if e.aligned]
    if not aligned:
        return []
    lo = 0
    for i, e in enumerate(aligned):
        if e.wall <= wall:
            lo = i
        else:
            break
    window = aligned[max(0, lo - n):lo + n + 1]
    return [e.to_dict() for e in window]


# ---- Chrome trace export ---------------------------------------------------

#: sources whose events render as duration ("X") slices when they carry
#: a dur_s; everything else is an instant ("i")
_TRACK_OF_SOURCE = {s: i for i, s in enumerate(TIMELINE_SOURCES)}


def _lane(e: Event) -> Tuple[int, str]:
    """(tid, lane label) for one event — per-rank/replica lanes inside
    each source's process group."""
    if e.rank is not None:
        return int(e.rank) + 1000, f"rank {e.rank}"
    if e.replica is not None:
        return int(e.replica), f"replica {e.replica}"
    return -1, "driver"


def to_chrome_trace(events: List[Event]) -> Dict[str, Any]:
    """Chrome trace-event JSON (the ``traceEvents`` array format —
    chrome://tracing, Perfetto, speedscope all open it). Aligned events
    are placed on one microsecond axis anchored at the earliest aligned
    wall; unaligned events land in a dedicated ``unaligned`` process
    group on their raw offsets, flagged in ``args``."""
    aligned = [e for e in events if e.aligned]
    t0 = min((e.wall for e in aligned), default=0.0)
    trace: List[dict] = []
    seen_pids: Dict[int, str] = {}
    seen_tids: set = set()
    unaligned_pid = len(TIMELINE_SOURCES)
    for e in events:
        pid = (_TRACK_OF_SOURCE.get(e.source, unaligned_pid)
               if e.aligned else unaligned_pid)
        pname = e.source if e.aligned else "unaligned"
        if pid not in seen_pids:
            seen_pids[pid] = pname
            trace.append({"ph": "M", "name": "process_name",
                          "pid": pid, "tid": 0,
                          "args": {"name": pname}})
        tid, lane = _lane(e)
        if (pid, tid) not in seen_tids:
            seen_tids.add((pid, tid))
            trace.append({"ph": "M", "name": "thread_name",
                          "pid": pid, "tid": tid,
                          "args": {"name": lane}})
        ts = (e.wall - t0) * 1e6 if e.aligned else e.wall * 1e6
        args: Dict[str, Any] = dict(e.payload)
        if e.step is not None:
            args["step"] = e.step
        if not e.aligned:
            args["unaligned"] = True
            args["source"] = e.source
        entry: Dict[str, Any] = {
            "name": e.kind, "cat": e.source, "pid": pid, "tid": tid,
            "ts": round(max(0.0, ts), 3), "args": args,
        }
        if e.dur_s is not None and e.dur_s > 0:
            # span-shaped entries stamp their START offset, so ts is
            # already the slice's left edge
            entry["ph"] = "X"
            entry["dur"] = round(e.dur_s * 1e6, 3)
        else:
            entry["ph"] = "i"
            entry["s"] = "g"
        trace.append(entry)
    return {"traceEvents": trace, "displayTimeUnit": "ms",
            "otherData": {"tool": "ray_lightning_tpu timeline",
                          "t0_wall": t0}}


def validate_chrome_trace(doc: Any) -> List[str]:
    """Structural validation against the trace-event contract the
    export promises (what the adapter tests and the smoke gate
    assert): a ``traceEvents`` list whose every entry carries
    name/ph/pid/tid and a numeric non-negative ``ts``, duration events
    a numeric ``dur``. Returns problem strings (empty = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        return ["no traceEvents list"]
    for i, ev in enumerate(doc["traceEvents"]):
        if not isinstance(ev, dict):
            problems.append(f"event {i} is not an object")
            continue
        for key in ("name", "ph", "pid", "tid"):
            if key not in ev:
                problems.append(f"event {i} missing {key!r}")
        if ev.get("ph") == "M":
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"event {i} bad ts {ts!r}")
        if ev.get("ph") == "X" and not isinstance(
                ev.get("dur"), (int, float)):
            problems.append(f"event {i} duration without dur")
    return problems


# ---- rendering + CLI -------------------------------------------------------


def _fmt_wall(e: Event, t0: float) -> str:
    if not e.aligned:
        return f"   +{e.wall:10.3f}?"
    import time as _time

    frac = e.wall - int(e.wall)
    return (_time.strftime("%H:%M:%S", _time.localtime(e.wall))
            + f".{int(frac * 1000):03d} +{e.wall - t0:8.3f}")


def render_text(timeline: Dict[str, Any], limit: int = 0,
                sources: Optional[List[str]] = None) -> str:
    events: List[Event] = timeline["events"]
    if sources:
        events = [e for e in events if e.source in sources]
    total = len(events)
    if limit and total > limit:
        events = events[-limit:]
    aligned_walls = [e.wall for e in events if e.aligned]
    t0 = min(aligned_walls, default=0.0)
    lines = [f"timeline: {timeline['run_dir']} — {total} event(s) from "
             f"{len(timeline['sources'])} source(s) "
             f"({', '.join(f'{s}:{n}' for s, n in sorted(timeline['sources'].items()))})"]
    if timeline["garbage_lines"]:
        lines.append(f"  {timeline['garbage_lines']} unparseable "
                     "ledger line(s) skipped")
    if timeline["unaligned"]:
        lines.append(f"  {timeline['unaligned']} event(s) from "
                     "headerless ledgers are tagged unaligned ('?' "
                     "offsets — not on the shared wall axis)")
    if limit and total > limit:
        lines.append(f"  (showing the last {limit})")
    for e in events:
        who = (f"rank {e.rank}" if e.rank is not None
               else f"replica {e.replica}" if e.replica is not None
               else "-")
        dur = f" dur={e.dur_s * 1e3:.1f}ms" if e.dur_s else ""
        step = f" step={e.step}" if e.step is not None else ""
        extra = ""
        if e.payload:
            bits = [f"{k}={v}" for k, v in list(e.payload.items())[:4]]
            extra = "  " + " ".join(bits)
        lines.append(f"  {_fmt_wall(e, t0)}  {e.source:<9} {who:<10} "
                     f"{e.kind}{dur}{step}{extra}")
    return "\n".join(lines)


def add_timeline_parser(sub) -> None:
    p = sub.add_parser(
        "timeline",
        help="merge every evidence ledger under a run dir into one "
             "causally-ordered event stream; --chrome exports "
             "Chrome-trace/Perfetto JSON (docs/OBSERVABILITY.md "
             "'unified timeline')")
    p.add_argument("run_dir", help="run dir (or its telemetry/ subdir)")
    p.add_argument("--chrome", metavar="OUT", default=None,
                   help="write Chrome trace-event JSON here ('-' for "
                        "stdout) instead of the text rendering")
    p.add_argument("--source", action="append", default=None,
                   choices=TIMELINE_SOURCES,
                   help="restrict to these sources (repeatable)")
    p.add_argument("--limit", type=int, default=200,
                   help="text mode: show only the last N events "
                        "(0 = all)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   default=argparse.SUPPRESS)


def run_timeline(args) -> int:
    if not os.path.isdir(args.run_dir):
        print(f"error: {args.run_dir} is not a directory",
              file=sys.stderr)
        return 2
    timeline = load_timeline_events(args.run_dir)
    events: List[Event] = timeline["events"]
    if args.source:
        events = [e for e in events if e.source in args.source]
    if args.chrome:
        doc = to_chrome_trace(events)
        if args.chrome == "-":
            json.dump(doc, sys.stdout)
            print()
        else:
            with open(args.chrome, "w") as f:
                json.dump(doc, f)
            print(f"wrote {len(doc['traceEvents'])} trace event(s) "
                  f"from {len(timeline['sources'])} source(s) to "
                  f"{args.chrome}")
        return 0
    if getattr(args, "as_json", False):
        print(json.dumps({
            "run_dir": timeline["run_dir"],
            "sources": timeline["sources"],
            "unaligned": timeline["unaligned"],
            "garbage_lines": timeline["garbage_lines"],
            "events": [e.to_dict() for e in events],
        }))
        return 0
    print(render_text({**timeline, "events": events},
                      limit=args.limit))
    return 0
