"""Telemetry: span timeline, goodput accounting, on-demand profiling,
and the static-vs-measured reconciliation report (docs/OBSERVABILITY.md).

  spans.py     host-side span recorder (bounded ring -> per-rank JSONL);
               zero new host syncs, byte-identical program when off
  metrics.py   live serving metrics: counters/gauges, mergeable
               log-bucket histograms, bounded per-tick time-series,
               and the replica flight recorder (docs/OBSERVABILITY.md
               "serving metrics")
  goodput.py   productive/compile/data-wait/ckpt-stall/backoff/replay
               wall-time classification, worker ledgers + driver assembly
  profiler.py  Trainer(profile=ProfileConfig(...)): step-window /
               marker-file / SIGUSR1 jax.profiler capture, rank-scoped
  report.py    `python -m ray_lightning_tpu report|monitor` — timeline,
               goodput, and the drift join against tracecheck
  timeline.py  unified run timeline: every evidence ledger merged into
               one causally-ordered Event stream + Chrome-trace export
               (docs/OBSERVABILITY.md "unified timeline")
  watch.py     declarative SLO watch rules evaluated over the persisted
               surfaces (ttft_p99, goodput, queue pressure, guard
               streaks, restart rate)
  incidents.py automatic incident capture: a rule breach appends a
               self-documenting record (evidence + timeline excerpt)
               to <run_dir>/incidents.jsonl and actuates the profiler
               marker + flight-persist evidence hooks
"""
from ray_lightning_tpu.telemetry.goodput import (  # noqa: F401
    GOODPUT_BUCKETS,
    GOODPUT_SCHEMA,
    assemble_goodput,
    buckets_consistent,
    read_goodput,
    worker_ledger,
    write_goodput,
    write_ledger,
)
from ray_lightning_tpu.telemetry.metrics import (  # noqa: F401
    NULL_FLIGHT,
    NULL_METRICS,
    FlightRecorder,
    Histogram,
    MetricsRegistry,
    NullMetrics,
    merge_histograms,
    read_flight,
    read_metrics,
)
from ray_lightning_tpu.telemetry.profiler import (  # noqa: F401
    ProfileConfig,
    ProfilerController,
)
from ray_lightning_tpu.telemetry.incidents import (  # noqa: F401
    append_incident,
    capture_evidence,
    read_incidents,
)
from ray_lightning_tpu.telemetry.spans import (  # noqa: F401
    NULL_RECORDER,
    PHASES,
    NullRecorder,
    TelemetryRecorder,
    ledger_tail_lines,
    read_spans,
)
from ray_lightning_tpu.telemetry.timeline import (  # noqa: F401
    Event,
    load_timeline_events,
    to_chrome_trace,
)
from ray_lightning_tpu.telemetry.watch import (  # noqa: F401
    BUILTIN_RULES,
    WatchConfig,
    WatchEngine,
    WatchRule,
)

__all__ = [
    "GOODPUT_BUCKETS", "GOODPUT_SCHEMA", "assemble_goodput",
    "buckets_consistent", "read_goodput", "worker_ledger",
    "write_goodput", "write_ledger", "ProfileConfig",
    "ProfilerController", "NULL_RECORDER", "PHASES", "NullRecorder",
    "TelemetryRecorder", "TelemetryConfig", "ledger_tail_lines",
    "read_spans",
    "NULL_FLIGHT", "NULL_METRICS", "FlightRecorder", "Histogram",
    "MetricsRegistry", "NullMetrics", "merge_histograms", "read_flight",
    "read_metrics",
    "Event", "load_timeline_events", "to_chrome_trace",
    "BUILTIN_RULES", "WatchConfig", "WatchEngine", "WatchRule",
    "append_incident", "capture_evidence", "read_incidents",
]


import dataclasses as _dc
import os as _os
from typing import Any as _Any, Optional as _Optional


@_dc.dataclass
class TelemetryConfig:
    """``Trainer(telemetry=...)`` — True for defaults, a directory
    string, or this. ``dir=None`` derives ``<root_dir>/telemetry``."""

    dir: _Optional[str] = None
    ring_size: int = 4096
    #: span-file + ledger flush cadence in steps (rides the trainer's
    #: logging cadence when larger)
    flush_every_n_steps: int = 50

    @classmethod
    def coerce(cls, value: _Any) -> _Optional["TelemetryConfig"]:
        if value is None or value is False:
            return None
        if value is True:
            return cls()
        if isinstance(value, cls):
            return value
        if isinstance(value, str):
            return cls(dir=value)
        raise TypeError(
            f"telemetry= takes True, a directory string, or a "
            f"TelemetryConfig; got {type(value).__name__}")

    def resolved_dir(self, root_dir: str) -> str:
        return self.dir or _os.path.join(root_dir, "telemetry")
