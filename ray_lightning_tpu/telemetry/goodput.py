"""Goodput accounting: classify supervised wall time into named buckets.

"Goodput" is the fraction of total wall-clock a run spent making forward
progress (TorchTitan treats this as a first-class production metric; so
does every TPU-fleet postmortem). Everything else gets a named bucket:

    productive_s        the fit loop was free to dispatch steps
    compile_s           trace + lower + XLA compile (AOT or lazy)
    data_wait_s         the consumer blocked on the prefetch queue
    ckpt_stall_s        the training thread blocked on checkpoint I/O
    eval_s              validation/test epochs
    metrics_fetch_s     cadenced lazy metric fetches (host syncs)
    launch_s            worker spawn -> fit start (imports, jax init,
                        distributed rendezvous), per attempt
    backoff_s           supervisor restart backoff sleeps (driver)
    rollback_replay_s   stepping time spent RE-training steps an earlier
                        attempt had already trained (restart/rollback
                        resume point behind the previous attempt's end)
    reshard_s           elastic world-size changes (docs/ELASTIC.md):
                        the cross-topology checkpoint restore after the
                        supervisor shrank/grew the job
    other_s             driver-side residual (classification, teardown,
                        pump overhead) — wall minus everything above

Two layers produce these:

  worker side   ``worker_ledger`` — the trainer snapshots its recorder's
                phase totals at fit end (and on the exception path) into
                ``<telemetry_dir>/ledger.rank<r>.<pid>.json``. Within a
                ledger, productive_s is wall minus the measured stall
                buckets, so a ledger's buckets sum to its wall EXACTLY.
  driver side   ``assemble_goodput`` — the supervisor stitches the rank-0
                ledgers of every attempt together with its own backoff /
                attempt wall accounting, reclassifies replayed steps'
                share of productive time into rollback_replay_s, and
                closes the books against total supervised wall with
                ``other_s``. Buckets sum to wall within float noise by
                construction; the ±5% smoke tolerance absorbs cross-
                process clock slop.

The report schema (``GOODPUT_SCHEMA``) also rides every bench JSON line
(backend-down safe: a structured skip line still carries it), so
downstream recorders never see a shape change when the chip vanishes.
"""
from __future__ import annotations

import glob
import json
import os
import time
from typing import Any, Dict, List, Optional

#: every bucket the report carries, in display order; their sum is
#: wall_s (within tolerance — docs/OBSERVABILITY.md "goodput")
GOODPUT_BUCKETS = (
    "productive_s", "compile_s", "data_wait_s", "ckpt_stall_s", "eval_s",
    "metrics_fetch_s", "launch_s", "backoff_s", "rollback_replay_s",
    "reshard_s", "other_s",
)

#: the lost-time classes a fault-injected smoke run must show nonzero
LOST_CLASSES = ("backoff_s", "rollback_replay_s")

#: schema stub attached to bench lines even when nothing was measured
GOODPUT_SCHEMA = {"buckets": list(GOODPUT_BUCKETS),
                  "headline": "goodput_fraction"}

LEDGER_VERSION = "rlt-ledger-v1"

#: recorder phases folded into each worker-side ledger bucket; phases
#: outside this map (producer-thread h2d, per-step spans) inform the
#: timeline but are overlapped with compute, so they never enter the
#: wall-exclusive budget
_PHASE_TO_BUCKET = {
    "compile": "compile_s",
    "data_wait": "data_wait_s",
    "ckpt_stall": "ckpt_stall_s",
    "eval": "eval_s",
    "metrics_fetch": "metrics_fetch_s",
    # elastic world-size changes (docs/ELASTIC.md): the worker-side
    # cross-topology checkpoint restore after a shrink/grow — named so
    # an elastic event is visible in `report`, not laundered into
    # productive time
    "reshard": "reshard_s",
}


def worker_ledger(recorder, wall_s: float, *, rank: int,
                  start_step: int, end_step: int,
                  launch_s: float = 0.0,
                  completed: bool = True,
                  extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One attempt's worker-side accounting. ``wall_s`` is the fit wall
    (perf_counter), ``launch_s`` the pre-fit spawn/init time when known
    (runtime session start -> fit start). productive_s closes the books:
    wall minus the measured stalls, floored at zero."""
    totals = recorder.phase_totals()
    buckets = {b: 0.0 for b in GOODPUT_BUCKETS}
    for phase, bucket in _PHASE_TO_BUCKET.items():
        buckets[bucket] = float(totals.get(phase, 0.0))
    stalls = sum(buckets.values())
    buckets["productive_s"] = max(0.0, wall_s - stalls)
    ledger = {
        "version": LEDGER_VERSION,
        "rank": rank,
        "wall_s": float(wall_s),
        "launch_s": float(launch_s),
        "start_step": int(start_step),
        "end_step": int(end_step),
        "completed": bool(completed),
        "t0_wall": time.time() - wall_s,
        "buckets": buckets,
    }
    if extra:
        ledger["extra"] = extra
    return ledger


def write_ledger(directory: str, ledger: Dict[str, Any],
                 uid: Optional[str] = None) -> str:
    """Atomic per-attempt ledger write: rank- and uid-tagged filename
    (the recorder's pid+sequence token) so restarted attempts AND
    same-process re-fits never clobber each other, tmp+replace so a
    kill mid-write leaves no torn JSON."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(
        directory,
        f"ledger.rank{ledger['rank']}.{uid or os.getpid()}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(ledger, f)
    os.replace(tmp, path)
    return path


def read_ledgers(directory: str, rank: Optional[int] = 0) -> List[dict]:
    """All parseable attempt ledgers (``rank=None`` for every rank),
    ordered by their wall start — attempt order on one machine, and
    NTP-close enough across hosts."""
    out: List[dict] = []
    pattern = (f"ledger.rank{rank}.*.json" if rank is not None
               else "ledger.rank*.json")
    for path in glob.glob(os.path.join(directory, pattern)):
        try:
            with open(path) as f:
                ledger = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if ledger.get("version") == LEDGER_VERSION:
            out.append(ledger)
    out.sort(key=lambda w: w.get("t0_wall", 0.0))
    return out


def assemble_goodput(telemetry_dir: str, wall_s: float,
                     backoff_s: float = 0.0,
                     restarts: int = 0, rollbacks: int = 0,
                     preemptions: int = 0) -> Dict[str, Any]:
    """Driver-side assembly over the rank-0 attempt ledgers.

    Replay attribution: attempt k resumed at ``start_step``; any steps
    below the max ``end_step`` an earlier attempt reached were already
    trained once, so their share of attempt k's productive time is
    reclassified as ``rollback_replay_s`` (restart, preemption, and
    trainguard rollback resume all replay through the same mechanism;
    the report's ``events`` field says which classes occurred).
    """
    ledgers = read_ledgers(telemetry_dir, rank=0)
    buckets = {b: 0.0 for b in GOODPUT_BUCKETS}
    buckets["backoff_s"] = float(backoff_s)
    max_end = None
    attempts = []
    for led in ledgers:
        lb = led.get("buckets", {})
        for b in GOODPUT_BUCKETS:
            if b in ("backoff_s", "rollback_replay_s", "other_s",
                     "launch_s"):
                continue
            buckets[b] += float(lb.get(b, 0.0))
        buckets["launch_s"] += float(led.get("launch_s", 0.0))
        start = int(led.get("start_step", 0))
        end = int(led.get("end_step", start))
        steps = max(0, end - start)
        replay_steps = 0
        if max_end is not None and start < max_end:
            replay_steps = min(steps, max_end - start)
        if replay_steps and steps:
            replay_s = float(lb.get("productive_s", 0.0)) * (
                replay_steps / steps)
            buckets["rollback_replay_s"] += replay_s
            buckets["productive_s"] -= replay_s
        max_end = end if max_end is None else max(max_end, end)
        attempts.append({"start_step": start, "end_step": end,
                         "wall_s": led.get("wall_s"),
                         "replay_steps": replay_steps,
                         "completed": led.get("completed")})
    accounted = sum(buckets.values())
    buckets["other_s"] = float(wall_s) - accounted
    total = sum(buckets.values())  # == wall_s by construction
    return {
        "wall_s": float(wall_s),
        "goodput_fraction": (buckets["productive_s"] / wall_s
                             if wall_s > 0 else 0.0),
        "buckets": {b: round(v, 4) for b, v in buckets.items()},
        "buckets_sum_s": round(total, 4),
        "attempts": attempts,
        "events": {"restarts": restarts, "preemptions": preemptions,
                   "rollbacks": rollbacks},
        "ledgers": len(ledgers),
        "schema": GOODPUT_SCHEMA,
    }


def write_goodput(telemetry_dir: str, report: Dict[str, Any]) -> str:
    os.makedirs(telemetry_dir, exist_ok=True)
    path = os.path.join(telemetry_dir, "goodput.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=2)
    os.replace(tmp, path)
    return path


def read_goodput(telemetry_dir: str) -> Optional[Dict[str, Any]]:
    try:
        with open(os.path.join(telemetry_dir, "goodput.json")) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def buckets_consistent(report: Dict[str, Any],
                       tolerance: float = 0.05) -> bool:
    """The pinned invariant: bucket sum within ``tolerance`` of wall."""
    wall = float(report.get("wall_s", 0.0))
    total = sum(float(v) for v in report.get("buckets", {}).values())
    if wall <= 0:
        return False
    return abs(total - wall) <= tolerance * wall
