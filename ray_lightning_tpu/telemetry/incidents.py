"""Automatic incident capture: a watch-rule breach that documents
itself.

A production breach observed by a human at `monitor --follow` is a
lucky breach. This module makes the unlucky ones self-documenting: when
`telemetry/watch.py` fires a rule, the breach lands in
``<run_dir>/incidents.jsonl`` as one append-only JSON record carrying

  * the rule (name, metric, op, threshold, severity),
  * the firing window (the evaluations that sustained the breach),
  * metric evidence (the value and the raw surface it was read from —
    e.g. the TTFT histogram sketch, the load-signal snapshot, the
    goodput buckets),
  * a timeline excerpt — the +-N merged events surrounding the breach
    (telemetry/timeline.py), so "what else was happening" rides along,
  * the evidence-capture actions taken.

Evidence capture actuates the hooks the system already has, instead of
inventing new instrumentation: it drops the profiler's ``CAPTURE``
marker file (telemetry/profiler.py polls it on the logging cadence —
the next N steps get a real XPlane trace) and forces a flight-recorder
persist through the serving driver's seam
(`ServeDriver.force_flight_persist`), so the breach window's final
ticks are on disk even if the process dies next. Both are host-side
file operations: watch/incidents never touch the compiled program
(watch off OR on — byte-identical lowered step, test-pinned).

The ledger opens with the same clock-alignment header every other
stream carries (``t0_wall`` + monotonic origin), and each record is
wall-stamped, so the timeline merger ingests incidents as first-class
events (docs/OBSERVABILITY.md "watch rules & incidents").
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ray_lightning_tpu.analysis.lockwatch import san_lock

INCIDENTS_NAME = "incidents.jsonl"
INCIDENTS_VERSION = "rlt-incidents-v1"

#: serializes header-write + append: a supervisor poll and a controller
#: poll sharing one run dir must interleave whole lines
_APPEND_LOCK = san_lock("telemetry.incidents.append")


def incidents_path(run_dir: str) -> str:
    return os.path.join(run_dir, INCIDENTS_NAME)


def build_incident(rule, value: float, now_wall: float,
                   window: List[dict],
                   evidence: Optional[Dict[str, Any]] = None,
                   excerpt: Optional[List[dict]] = None) -> Dict[str, Any]:
    """One incident record (docs/OBSERVABILITY.md "incident record
    contract"). ``rule`` is a `watch.WatchRule`; ``window`` the
    evaluations that sustained the breach (newest last)."""
    ev: Dict[str, Any] = {"metric": rule.metric, "op": rule.op,
                          "threshold": rule.threshold, "value": value}
    if evidence:
        ev.update(evidence)
    return {
        "rule": rule.name,
        "severity": rule.severity,
        "wall": round(now_wall, 6),
        "window": window,
        "evidence": ev,
        "description": rule.description,
    }


def append_incident(run_dir: str, incident: Dict[str, Any]) -> str:
    """Append one record to ``<run_dir>/incidents.jsonl``; writes the
    clock-alignment header first when creating the ledger."""
    os.makedirs(run_dir, exist_ok=True)
    path = incidents_path(run_dir)
    with _APPEND_LOCK:
        header = not os.path.exists(path) or \
            os.path.getsize(path) == 0
        with open(path, "a") as f:
            if header:
                f.write(json.dumps({
                    "version": INCIDENTS_VERSION,
                    "t0_wall": time.time(),
                    "t0_perf": time.perf_counter(),
                    "pid": os.getpid(),
                }) + "\n")
            f.write(json.dumps(incident) + "\n")
    return path


def read_incidents(run_dir: str,
                   tail_bytes: Optional[int] = None) -> Dict[str, Any]:
    """Parse the incident ledger: ``{"header": {...}, "incidents":
    [...], "unparseable_lines": n}``. Missing file = no incidents;
    garbage lines are counted, never fatal. ``tail_bytes`` bounds the
    read for cadence-polled callers (RLT503)."""
    from ray_lightning_tpu.telemetry.spans import ledger_tail_lines

    path = incidents_path(run_dir)
    header: Dict[str, Any] = {}
    incidents: List[dict] = []
    bad = 0
    try:
        first, body = ledger_tail_lines(path, tail_bytes)
    except OSError:
        return {"header": header, "incidents": incidents,
                "unparseable_lines": bad}
    for i, line in enumerate([first] + body):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            bad += 1
            continue
        if not isinstance(obj, dict):
            bad += 1
            continue
        if i == 0 and obj.get("version") == INCIDENTS_VERSION:
            header = obj
            continue
        incidents.append(obj)
    return {"header": header, "incidents": incidents,
            "unparseable_lines": bad}


def capture_evidence(run_dir: str, profile_dir: Optional[str] = None,
                     driver: Any = None) -> Dict[str, Any]:
    """Actuate the existing evidence hooks for one breach. Returns the
    actions record the incident carries. Never raises — capture is
    best-effort garnish on the incident, not a gate on it.

    * ``CAPTURE`` marker: dropped into ``profile_dir`` (default
      ``<run_dir>/rlt_profile``) — the profiler controller
      (telemetry/profiler.py) polls exactly this file on its cadence
      and captures the next N steps; one marker = one capture.
    * flight persist: ``driver.force_flight_persist()`` when a serving
      driver is wired in — the breach window's final ticks land on
      disk NOW instead of one persist cadence later.
    """
    actions: Dict[str, Any] = {}
    marker_dir = profile_dir or os.path.join(run_dir, "rlt_profile")
    try:
        os.makedirs(marker_dir, exist_ok=True)
        from ray_lightning_tpu.telemetry.profiler import DEFAULT_MARKER

        marker = os.path.join(marker_dir, DEFAULT_MARKER)
        # one marker = one capture (the profiler consumes it); an
        # unconsumed marker from an earlier incident is left alone
        if not os.path.exists(marker):
            with open(marker, "w") as f:
                f.write(json.dumps({"at": time.time(),
                                    "by": "watch"}))
            actions["profiler_marker"] = marker
        else:
            actions["profiler_marker_pending"] = marker
    except OSError as exc:
        actions["profiler_marker_error"] = str(exc)[:160]
    if driver is not None:
        try:
            persisted = driver.force_flight_persist()
            actions["flight_persisted"] = persisted
        except Exception as exc:  # noqa: BLE001 — best-effort capture
            actions["flight_persist_error"] = (
                f"{type(exc).__name__}: {str(exc)[:160]}")
    return actions
