"""Span recorder: where host wall-clock actually goes, per rank.

The fit loop already has host-resident seams for every phase that can
cost wall time — the prefetcher's consumer wait (data), its producer's
place_fn (shard/H2D), the step dispatch call, the cadenced metric
fetch, the blocking part of a checkpoint save, the AOT compile, eval
epochs — plus the driver-side supervision phases (restart backoff,
attempt launch). This module gives those seams one cheap vocabulary:

    with recorder.span(PH_DISPATCH, step=global_step):
        state, metrics = train_step(state, batch, rng)

A span is a host-side ``(phase, start, dur, step, thread)`` record in a
bounded ring (``collections.deque(maxlen=...)``) that is flushed to
JSONL per rank under the run dir on a cadence the caller controls.
Nothing here touches jax: no ``device_get``, no ``block_until_ready``,
no array inspection — a span measures how long the HOST spent inside a
region that was host-resident anyway, so telemetry=off and telemetry=on
compile the byte-identical device program (test-pinned) and telemetry
adds zero new host syncs.

``NullRecorder`` is the off switch: the same surface with a shared
reusable no-op context, so call sites never branch.

Clock alignment: each JSONL file opens with a header line carrying the
pair ``(t0_wall, t0_perf)``; span ``t`` fields are perf_counter offsets
from ``t0_perf``, so the driver-side report can place every rank's
spans on one wall-clock axis (time.time is NTP-aligned across hosts to
far better than a training step).
"""
from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ray_lightning_tpu.analysis.lockwatch import san_lock

#: per-process recorder sequence: a second fit in the same process (or
#: two trainers sharing one telemetry dir — the sweep inline executor)
#: must get its OWN files, never truncate an earlier recorder's
_FILE_SEQ = itertools.count()

# ---- phase vocabulary (docs/OBSERVABILITY.md "span schema") ---------------

PH_DATA_WAIT = "data_wait"      # consumer blocked on the prefetch queue
PH_H2D = "h2d"                  # cast + shard + device_put (producer thread)
PH_DISPATCH = "dispatch"        # enqueueing the jitted step (async dispatch)
PH_METRICS = "metrics_fetch"    # cadenced lazy metric fetch (host sync)
PH_CKPT = "ckpt_stall"          # training thread blocked on checkpoint I/O
PH_COMPILE = "compile"          # trace + lower + XLA compile (AOT or lazy)
PH_EVAL = "eval"                # a validation/test epoch
PH_BACKOFF = "backoff"          # supervisor restart backoff sleep (driver)
PH_ATTEMPT = "attempt"          # one supervised launch, wall (driver)
PH_ROLLBACK = "rollback"        # rollback target selection (driver)
PH_RESHARD = "reshard"          # cross-topology checkpoint restore: the
#                                 worker-side resharding load after an
#                                 elastic world-size change (plus the
#                                 driver's shrink/grow decision span)
PH_STEP = "step"                # per-step host wall (batch_end to batch_end)

#: every phase the schema knows; foreign phases are legal (the recorder
#: is a vocabulary, not a validator) but the report groups them as-is
PHASES = (
    PH_DATA_WAIT, PH_H2D, PH_DISPATCH, PH_METRICS, PH_CKPT, PH_COMPILE,
    PH_EVAL, PH_BACKOFF, PH_ATTEMPT, PH_ROLLBACK, PH_RESHARD, PH_STEP,
)

# ---- serving phases (serve/, docs/SERVING.md) -----------------------------
# Recorded per REQUEST at completion (explicit record() calls on the
# scheduler's measured host times, flushed on a cadence), never per
# engine tick: the serving loop is a hot loop and RLT501's cadence
# discipline applies to it too. Kept OUT of `PHASES` on purpose — the
# training goodput buckets (telemetry/goodput.py) must not learn
# request-scoped phases whose spans overlap each other by design.

PH_QUEUE_WAIT = "queue_wait"    # request submitted -> slot admitted
PH_PREFILL = "prefill"          # admitted -> prompt fully prefilled
PH_DECODE = "decode"            # first sampled token -> retirement
PH_DETOK = "detokenize"         # token ids -> text (driver side)

SERVE_PHASES = (PH_QUEUE_WAIT, PH_PREFILL, PH_DECODE, PH_DETOK)

#: phases recorded from background threads overlap with compute and must
#: NOT be charged against the main thread's wall-time budget
THREAD_MAIN = "main"
THREAD_PRODUCER = "producer"

SPANS_VERSION = "rlt-spans-v1"


class _SpanCtx:
    """One `with recorder.span(...)` region. Slots + a single perf_counter
    pair: the per-span cost is two clock reads, a dict build, and a
    deque append — nanoseconds next to the millisecond phases it times.

    Main-thread spans nest (a lazy eval-step compile runs INSIDE the
    eval span): the span entry keeps the full duration, but the phase
    TOTALS are charged exclusively — a nested child's time is deducted
    from its parent — so the goodput buckets never double-count one
    wall-clock second."""

    __slots__ = ("_rec", "phase", "step", "thread", "meta", "_t0",
                 "child_s")

    def __init__(self, rec: "TelemetryRecorder", phase: str,
                 step: Optional[int], thread: str, meta: Optional[dict]):
        self._rec = rec
        self.phase = phase
        self.step = step
        self.thread = thread
        self.meta = meta
        self.child_s = 0.0

    def __enter__(self) -> "_SpanCtx":
        self._t0 = time.perf_counter()
        if self.thread == THREAD_MAIN:
            self._rec._stack.append(self)
            self._rec._phase = self.phase
        return self

    def __exit__(self, *exc) -> None:
        t1 = time.perf_counter()
        dur = t1 - self._t0
        totals_s = dur
        if self.thread == THREAD_MAIN:
            stack = self._rec._stack
            if stack and stack[-1] is self:
                stack.pop()
            totals_s = max(0.0, dur - self.child_s)
            self._rec._phase = stack[-1].phase if stack else PH_STEP
        # record() credits the (now-exposed) parent with this span's
        # full duration — the same path explicit record() calls take
        self._rec.record(self.phase, self._t0, dur,
                         step=self.step, thread=self.thread,
                         meta=self.meta, totals_s=totals_s)
        return None


class TelemetryRecorder:
    """Bounded-ring span recorder with cadenced JSONL flush.

    ``directory=None`` records in memory only (phase totals + ring) —
    the mode unit tests and the bench's overhead probe use. With a
    directory, ``flush()`` appends the ring's unflushed spans to
    ``<directory>/rank<k>.spans.jsonl``; the trainer calls it on the
    logging cadence and at fit end, never per batch.

    Thread-safe: the producer thread (H2D spans) and the heartbeat
    thread (``current_phase``/``last_span``) share it with the fit loop.
    """

    def __init__(self, directory: Optional[str] = None, rank: int = 0,
                 ring_size: int = 4096):
        self.directory = directory
        self.rank = rank
        self.enabled = True
        self._lock = san_lock("telemetry.spans.recorder")
        self._ring: collections.deque = collections.deque(maxlen=ring_size)
        self._totals: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self._dropped = 0        # unflushed ring overwrites
        self._dropped_total = 0  # lifetime, for the metrics surface
        self._phase: str = "setup"      # read by the heartbeat thread
        self._stack: List[_SpanCtx] = []  # main-thread open spans
        self._last: Optional[dict] = None
        self._step: Optional[int] = None
        self.t0_perf = time.perf_counter()
        self.t0_wall = time.time()
        #: unique per-recorder token: pid distinguishes restarted
        #: attempts, the sequence distinguishes recorders WITHIN one
        #: process (re-fit, inline sweep trials) — nothing ever
        #: truncates an earlier timeline or ledger
        self.uid = f"{os.getpid()}-{next(_FILE_SEQ)}"
        self._path: Optional[str] = None
        if directory is not None:
            os.makedirs(directory, exist_ok=True)
            self._path = os.path.join(
                directory, f"rank{rank}.{self.uid}.spans.jsonl")
            with open(self._path, "w") as f:
                f.write(json.dumps({
                    "version": SPANS_VERSION, "rank": rank,
                    "t0_wall": self.t0_wall, "pid": os.getpid(),
                }) + "\n")

    # ---- recording -------------------------------------------------------

    def span(self, phase: str, step: Optional[int] = None,
             thread: str = THREAD_MAIN,
             meta: Optional[dict] = None) -> _SpanCtx:
        return _SpanCtx(self, phase, step if step is not None else self._step,
                        thread, meta)

    def record(self, phase: str, start_perf: float, dur_s: float,
               step: Optional[int] = None, thread: str = THREAD_MAIN,
               meta: Optional[dict] = None,
               totals_s: Optional[float] = None) -> None:
        """Record one completed span (explicit form; ``span()`` is the
        context-manager sugar over it). ``totals_s`` overrides the
        amount charged to the phase totals — nested main-thread spans
        charge exclusively so the goodput buckets never double-count.
        A main-thread record inside an OPEN main-thread span (an eval
        epoch's data_wait, a nested compile) credits the enclosing span
        the same way, and the exclusive charge is persisted as ``excl``
        so the report's totals agree with the recorder's."""
        charged = dur_s if totals_s is None else totals_s
        entry = {"phase": phase, "t": round(start_perf - self.t0_perf, 6),
                 "dur": round(dur_s, 6), "step": step, "thread": thread}
        if charged != dur_s:
            entry["excl"] = round(charged, 6)
        if meta:
            entry["meta"] = meta
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
                self._dropped_total += 1
            self._ring.append(entry)
            if thread == THREAD_MAIN:
                if self._stack:
                    self._stack[-1].child_s += dur_s
                self._totals[phase] = self._totals.get(phase, 0.0) + charged
                self._counts[phase] = self._counts.get(phase, 0) + 1
            self._last = entry

    def set_step(self, step: int) -> None:
        self._step = step

    # ---- heartbeat-facing state (cross-thread reads are benign) ----------

    def current_phase(self) -> str:
        return self._phase

    def last_span(self) -> Optional[dict]:
        return self._last

    # ---- accounting ------------------------------------------------------

    def phase_totals(self) -> Dict[str, float]:
        """Main-thread wall seconds per phase (producer-thread spans are
        overlapped with compute and deliberately excluded — charging
        them would double-count the wall)."""
        with self._lock:
            return dict(self._totals)

    def phase_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    @property
    def dropped(self) -> int:
        return self._dropped_total

    # ---- flush -----------------------------------------------------------

    def flush(self) -> int:
        """Append the ring's spans to the per-rank JSONL and clear it.
        Call on a cadence (the trainer uses the logging cadence) or at
        teardown — NEVER per batch; RLT501 exists to catch that."""
        if self._path is None:
            return 0
        with self._lock:
            batch: List[dict] = list(self._ring)
            self._ring.clear()
            dropped, self._dropped = self._dropped, 0
        if not batch and not dropped:
            return 0
        with open(self._path, "a") as f:
            for entry in batch:
                f.write(json.dumps(entry) + "\n")
            if dropped:
                f.write(json.dumps({"phase": "_dropped",
                                    "count": dropped}) + "\n")
        return len(batch)

    def close(self) -> None:
        self.flush()


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


_NULL_CTX = _NullCtx()


class NullRecorder:
    """telemetry=off: the same surface, every call a no-op. One shared
    context object — ``span()`` allocates nothing."""

    directory = None
    rank = 0
    enabled = False
    dropped = 0

    def span(self, phase: str, step: Optional[int] = None,
             thread: str = THREAD_MAIN, meta: Optional[dict] = None):
        return _NULL_CTX

    def record(self, *a: Any, **kw: Any) -> None: ...
    def set_step(self, step: int) -> None: ...

    def current_phase(self) -> str:
        return ""

    def last_span(self) -> Optional[dict]:
        return None

    def phase_totals(self) -> Dict[str, float]:
        return {}

    def phase_counts(self) -> Dict[str, int]:
        return {}

    def flush(self) -> int:
        return 0

    def close(self) -> None: ...


#: the shared off-switch instance call sites default to
NULL_RECORDER = NullRecorder()


def ledger_tail_lines(path: str,
                      tail_bytes: Optional[int] = None):
    """``(first_line, body_lines)`` for one JSONL ledger. The first
    line is returned separately because it is the clock-alignment
    header slot — a TAIL-bounded read (``tail_bytes``) must never lose
    it, or the timeline merge would have to guess the ledger's epoch.
    With a bound, only the last ``tail_bytes`` of the body are read
    (the partial line at the window's cut edge is dropped) — the
    RLT503 discipline for cadence-polled readers (`monitor --follow`,
    watch evaluation): a week-old multi-GiB ledger costs a poll one
    seek + one bounded read, not a full parse."""
    with open(path, "rb") as f:
        first = f.readline()
        header_end = f.tell()
        if tail_bytes is None:
            body = f.read()
        else:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            start = max(header_end, size - max(0, int(tail_bytes)))
            f.seek(start)
            body = f.read()
            if start > header_end:
                nl = body.find(b"\n")
                body = body[nl + 1:] if nl >= 0 else b""
    return (first.decode("utf-8", "replace"),
            body.decode("utf-8", "replace").splitlines())


def read_spans(path: str,
               tail_bytes: Optional[int] = None) -> Dict[str, Any]:
    """Parse one rank's spans JSONL: ``{"header": {...}, "spans": [...],
    "dropped": n}``. Unparseable lines are counted, not fatal — a file
    truncated by a kill mid-flush must still report what landed.
    ``tail_bytes`` bounds the read to the header + the file's last N
    bytes (cadence-polled callers: RLT503)."""
    header: Dict[str, Any] = {}
    spans: List[dict] = []
    dropped = 0
    bad = 0
    first, body = ledger_tail_lines(path, tail_bytes)
    for i, line in enumerate([first] + body):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            bad += 1
            continue
        if not isinstance(obj, dict):
            bad += 1
            continue
        if i == 0 and obj.get("version") == SPANS_VERSION:
            header = obj
            continue
        if obj.get("phase") == "_dropped":
            dropped += int(obj.get("count", 0))
            continue
        spans.append(obj)
    return {"header": header, "spans": spans, "dropped": dropped,
            "unparseable_lines": bad}
