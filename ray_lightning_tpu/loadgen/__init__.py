"""Trace-driven load harness (docs/SERVING.md "traffic & SLO
classes"): a deterministic workload generator + versioned replayable
trace format + virtual-clock runner that drive the REAL
`ServeDriver`/`Scheduler`/autoscale stack on CPU.

* `loadgen.trace` — the versioned JSONL trace format (record a live
  run, replay it bitwise) and Request materialization;
* `loadgen.generator` — seeded arrival processes (Poisson / 2-state
  bursty MMPP) with heavy-tailed length distributions and a traffic-
  class mix, every draw from one `np.random.Generator(PCG64(seed))`;
* `loadgen.runner` — the virtual-clock drive loop (the driver tick
  counter is the clock; `autoscale.sim.run_scripted` is a thin shim
  over it);
* `loadgen.cli` — ``python -m ray_lightning_tpu loadgen`` and the
  ``--smoke`` format.sh gate.
"""
from ray_lightning_tpu.loadgen.generator import (  # noqa: F401
    WorkloadConfig,
    generate_events,
)
from ray_lightning_tpu.loadgen.runner import run_trace  # noqa: F401
from ray_lightning_tpu.loadgen.trace import (  # noqa: F401
    TRACE_VERSION,
    TraceEvent,
    TraceRecorder,
    arrivals_by_tick,
    dump_trace,
    events_from_arrivals,
    read_trace,
    to_request,
    write_trace,
)

__all__ = [
    "TRACE_VERSION",
    "TraceEvent",
    "TraceRecorder",
    "WorkloadConfig",
    "arrivals_by_tick",
    "dump_trace",
    "events_from_arrivals",
    "generate_events",
    "read_trace",
    "run_trace",
    "to_request",
    "write_trace",
]
