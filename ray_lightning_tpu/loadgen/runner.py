"""The virtual-clock drive loop.

Wall-clock load tests flake by construction — queue depth depends on
when a submit landed relative to the tick. Here the DRIVER TICK
COUNTER is the clock (1 tick = 1 virtual second for the autoscale
policy's cooldown arithmetic): arrivals fire at their trace tick, the
controller (when armed) polls every ``poll_every_ticks`` ticks, and
the load signal is read from the same flushed metrics files
production reads — the real signal path, the real policy, the real
`ServeDriver` seams, zero sleeps. `autoscale.sim.run_scripted` is a
thin back-compat shim over this loop.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

from ray_lightning_tpu.loadgen.trace import (
    TraceEvent,
    TraceRecorder,
    arrivals_by_tick,
)

__all__ = ["run_trace"]


def run_trace(driver,
              arrivals: Union[Dict[int, Sequence],
                              Sequence[TraceEvent]],
              controller=None,
              poll_every_ticks: int = 2,
              idle_ticks_after_drain: int = 48,
              max_ticks: int = 5000,
              recorder: Optional[TraceRecorder] = None) -> dict:
    """Drive one serving session to completion. ``driver`` must be
    `start()`ed; ``arrivals`` is either ``{tick: [Request, ...]}`` or
    a sequence of `TraceEvent`s. Keeps ticking (and polling)
    ``idle_ticks_after_drain`` ticks after the last stream drains —
    the idle phase a scale-down needs to observe. Returns
    ``{"ticks", "drained_at", "entries", "submitted"}`` where
    ``entries`` is every controller ledger entry in order."""
    if not isinstance(arrivals, dict):
        arrivals = arrivals_by_tick(arrivals)
    entries: List[dict] = []
    drained_at: Optional[int] = None
    submitted = 0
    last_arrival = max(arrivals) if arrivals else 0
    tick = 0
    while tick < max_ticks:
        for req in arrivals.get(tick, ()):
            if recorder is not None:
                recorder.record(tick, req)
            driver.submit(req)
            submitted += 1
        driver.tick()
        if controller is not None and tick % poll_every_ticks == 0:
            entries.append(controller.step(now=float(tick)))
        if tick >= last_arrival and not driver.busy():
            if drained_at is None:
                drained_at = tick
            if tick - drained_at >= idle_ticks_after_drain:
                break
        else:
            drained_at = None
        tick += 1
    return {"ticks": tick, "drained_at": drained_at,
            "entries": entries, "submitted": submitted}
