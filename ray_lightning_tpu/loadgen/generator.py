"""Seeded synthetic workload generation.

Arrival processes (per virtual tick):

* ``poisson`` — homogeneous: arrivals/tick ~ Poisson(``rate``);
* ``mmpp`` — a 2-state Markov-modulated Poisson process: a calm state
  at ``rate`` and a burst state at ``burst_rate``, with geometric
  dwell times (``p_enter_burst`` / ``p_exit_burst``). The bursts are
  what the SLO machinery is FOR — a burst deeper than capacity is the
  overload that sheds best-effort while latency-critical holds its
  TTFT (docs/SERVING.md "traffic & SLO classes").

Lengths are heavy-tailed: prompt length and ``max_new_tokens`` draw
from a bounded Pareto (inverse-CDF transform), so a few long requests
dominate pool pressure the way production traces do — uniform lengths
hide exactly the preemption/shedding behavior this harness exists to
exercise.

Every draw comes from ONE ``np.random.Generator(PCG64(seed))`` in a
fixed order, so the same config yields the byte-identical trace
(`trace.dump_trace` canonical form) on every run — the ``--smoke``
determinism pin.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from ray_lightning_tpu.loadgen.trace import TraceEvent

__all__ = ["WorkloadConfig", "generate_events"]


def _default_mix() -> Dict[str, float]:
    return {"latency_critical": 0.2, "standard": 0.5,
            "best_effort": 0.3}


@dataclasses.dataclass(frozen=True)
class WorkloadConfig:
    """Everything the generator draws from, and nothing else — the
    config IS the trace identity (it lands in the trace header's
    ``meta`` so a replayer can see what produced the file)."""

    seed: int = 0
    n_requests: int = 32
    #: "poisson" | "mmpp"
    process: str = "poisson"
    #: mean arrivals per tick (calm state)
    rate: float = 2.0
    #: MMPP burst-state mean arrivals per tick
    burst_rate: float = 8.0
    p_enter_burst: float = 0.1
    p_exit_burst: float = 0.3
    #: bounded-Pareto prompt length: [min, max], tail index alpha
    prompt_len_min: int = 3
    prompt_len_max: int = 24
    prompt_len_alpha: float = 1.5
    #: bounded-Pareto output budget
    max_new_min: int = 4
    max_new_max: int = 32
    max_new_alpha: float = 1.2
    #: traffic-class weights (normalized; keys sorted for determinism)
    class_mix: Optional[Dict[str, float]] = None
    #: fraction of requests using temperature/top-k sampling (the
    #: rest decode greedily — both paths stay on the bitwise oracle)
    sampled_fraction: float = 0.5
    temperature: float = 0.8
    top_k: int = 5
    vocab_size: int = 256
    #: per-request sampling seed = seed_base + index
    seed_base: int = 1000

    def __post_init__(self):
        if self.process not in ("poisson", "mmpp"):
            raise ValueError(
                f"process {self.process!r} not in ('poisson', 'mmpp')")
        if self.n_requests < 1:
            raise ValueError("n_requests must be >= 1")

    def mix(self) -> Dict[str, float]:
        from ray_lightning_tpu.serve.scheduler import PRIORITIES

        mix = self.class_mix if self.class_mix is not None \
            else _default_mix()
        bad = sorted(set(mix) - set(PRIORITIES))
        if bad:
            raise ValueError(
                f"class_mix names unknown classes {bad} "
                f"(known: {PRIORITIES})")
        total = float(sum(mix.values()))
        if total <= 0:
            raise ValueError("class_mix weights must sum > 0")
        return {k: v / total for k, v in sorted(mix.items())}

    def meta(self) -> dict:
        d = dataclasses.asdict(self)
        d["class_mix"] = self.mix()
        return d


def _bounded_pareto(u: float, lo: int, hi: int, alpha: float) -> int:
    """Inverse CDF of the Pareto truncated to [lo, hi]."""
    if hi <= lo:
        return lo
    ratio = (lo / hi) ** alpha
    x = lo * (1.0 - u * (1.0 - ratio)) ** (-1.0 / alpha)
    return int(min(hi, max(lo, x)))


def generate_events(cfg: WorkloadConfig) -> List[TraceEvent]:
    """The deterministic draw loop. The rng consumption ORDER is part
    of the format contract: per tick one arrival-count draw (plus one
    state draw under mmpp), then per request priority, prompt length,
    prompt tokens, output budget, sampling coin."""
    rng = np.random.Generator(np.random.PCG64(cfg.seed))
    mix = cfg.mix()
    classes: Tuple[str, ...] = tuple(mix)
    weights = np.asarray([mix[c] for c in classes], np.float64)
    events: List[TraceEvent] = []
    tick = 0
    burst = False
    while len(events) < cfg.n_requests:
        if cfg.process == "mmpp":
            # geometric state dwell: one transition draw per tick
            flip = float(rng.random())
            burst = (flip < cfg.p_enter_burst) if not burst \
                else (flip >= cfg.p_exit_burst)
            lam = cfg.burst_rate if burst else cfg.rate
        else:
            lam = cfg.rate
        n = int(rng.poisson(lam))
        for _ in range(min(n, cfg.n_requests - len(events))):
            i = len(events)
            priority = classes[int(rng.choice(len(classes),
                                              p=weights))]
            plen = _bounded_pareto(float(rng.random()),
                                   cfg.prompt_len_min,
                                   cfg.prompt_len_max,
                                   cfg.prompt_len_alpha)
            prompt = tuple(int(t) for t in rng.integers(
                0, cfg.vocab_size, size=plen))
            max_new = _bounded_pareto(float(rng.random()),
                                      cfg.max_new_min,
                                      cfg.max_new_max,
                                      cfg.max_new_alpha)
            sampled = float(rng.random()) < cfg.sampled_fraction
            events.append(TraceEvent(
                tick=tick, rid=f"lg{i:04d}", prompt=prompt,
                max_new_tokens=max_new, priority=priority,
                temperature=cfg.temperature if sampled else 0.0,
                top_k=cfg.top_k if sampled else None,
                seed=cfg.seed_base + i))
        tick += 1
    return events
