"""The versioned, replayable workload trace format.

A trace is JSONL: one header line (kind + version + generator
metadata) followed by one event line per request, sorted by
``(tick, rid)``. Events carry everything a bitwise replay needs —
explicit prompt token ids (never "regenerate from a seed": the trace
must replay against any engine without assuming the generator's
vocab), sampling knobs, and the traffic class — so a recorded
production trace and a synthetic generated one are the same artifact
(docs/SERVING.md "traffic & SLO classes").

Serialization is canonical (sorted keys, compact separators): the
``--smoke`` determinism pin compares whole traces as BYTES, and a
re-serialized read-back must round-trip identically.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

TRACE_VERSION = 1
TRACE_KIND = "rlt-loadgen-trace"

__all__ = [
    "TRACE_KIND",
    "TRACE_VERSION",
    "TraceEvent",
    "TraceRecorder",
    "arrivals_by_tick",
    "dump_trace",
    "events_from_arrivals",
    "read_trace",
    "to_request",
    "write_trace",
]


@dataclasses.dataclass(frozen=True)
class TraceEvent:
    """One arrival: ``tick`` is the VIRTUAL tick (runner clock) the
    request enters the system."""

    tick: int
    rid: str
    prompt: Tuple[int, ...]
    max_new_tokens: int
    priority: str = "standard"
    temperature: float = 0.0
    top_k: Optional[int] = None
    seed: int = 0

    def to_dict(self) -> dict:
        return {
            "tick": self.tick, "rid": self.rid,
            "prompt": list(self.prompt),
            "max_new_tokens": self.max_new_tokens,
            "priority": self.priority,
            "temperature": self.temperature,
            "top_k": self.top_k, "seed": self.seed,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TraceEvent":
        return cls(
            tick=int(d["tick"]), rid=str(d["rid"]),
            prompt=tuple(int(t) for t in d["prompt"]),
            max_new_tokens=int(d["max_new_tokens"]),
            # absent on traces recorded before traffic classes
            priority=str(d.get("priority", "standard")),
            temperature=float(d.get("temperature", 0.0)),
            top_k=(None if d.get("top_k") is None
                   else int(d["top_k"])),
            seed=int(d.get("seed", 0)),
        )


def _canon(obj) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def dump_trace(events: Sequence[TraceEvent],
               meta: Optional[dict] = None) -> str:
    """Canonical serialization — the byte-level determinism surface."""
    ordered = sorted(events, key=lambda e: (e.tick, e.rid))
    header = {"kind": TRACE_KIND, "version": TRACE_VERSION,
              "events": len(ordered), "meta": meta or {}}
    lines = [_canon(header)]
    lines.extend(_canon(e.to_dict()) for e in ordered)
    return "\n".join(lines) + "\n"


def write_trace(path: str, events: Sequence[TraceEvent],
                meta: Optional[dict] = None) -> None:
    with open(path, "w") as f:
        f.write(dump_trace(events, meta))


def read_trace(path: str) -> Tuple[dict, List[TraceEvent]]:
    """Returns ``(header, events)``; refuses unknown kinds/versions
    instead of misreading them."""
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"{path}: empty trace")
    header = json.loads(lines[0])
    if header.get("kind") != TRACE_KIND:
        raise ValueError(
            f"{path}: not a {TRACE_KIND} (kind={header.get('kind')!r})")
    if header.get("version") != TRACE_VERSION:
        raise ValueError(
            f"{path}: trace version {header.get('version')!r}, this "
            f"reader speaks {TRACE_VERSION}")
    events = [TraceEvent.from_dict(json.loads(ln)) for ln in lines[1:]]
    if header.get("events") not in (None, len(events)):
        raise ValueError(
            f"{path}: header claims {header['events']} events, file "
            f"holds {len(events)} — truncated trace")
    return header, events


def to_request(ev: TraceEvent):
    """Materialize the `serve.scheduler.Request` an event describes."""
    from ray_lightning_tpu.serve.scheduler import Request

    return Request(
        rid=ev.rid, prompt=np.asarray(ev.prompt, np.int32),
        max_new_tokens=ev.max_new_tokens, temperature=ev.temperature,
        top_k=ev.top_k, seed=ev.seed, priority=ev.priority)


def arrivals_by_tick(events: Sequence[TraceEvent]) -> Dict[int, list]:
    """``{tick: [Request, ...]}`` — the runner/`ScriptedLoad`
    vocabulary. Within a tick, submission order is the trace's
    canonical ``(tick, rid)`` order."""
    out: Dict[int, list] = {}
    for ev in sorted(events, key=lambda e: (e.tick, e.rid)):
        out.setdefault(ev.tick, []).append(to_request(ev))
    return out


def events_from_arrivals(arrivals: Dict[int, Sequence]) \
        -> List[TraceEvent]:
    """The inverse: lift a scripted ``{tick: [Request]}`` schedule
    (e.g. `autoscale.sim.ScriptedLoad.arrivals`) into trace events."""
    events: List[TraceEvent] = []
    for tick in sorted(arrivals):
        for req in arrivals[tick]:
            events.append(TraceEvent(
                tick=int(tick), rid=req.rid,
                prompt=tuple(int(t) for t in
                             np.asarray(req.prompt).reshape(-1)),
                max_new_tokens=req.max_new_tokens,
                priority=req.priority, temperature=req.temperature,
                top_k=req.top_k, seed=req.seed))
    return events


class TraceRecorder:
    """Record-and-replay capture: hand one to `runner.run_trace` (or
    call ``record()`` wherever submissions happen) and the live run's
    arrival schedule becomes a replayable trace."""

    def __init__(self, meta: Optional[dict] = None):
        self.meta = meta or {}
        self.events: List[TraceEvent] = []

    def record(self, tick: int, req) -> None:
        self.events.extend(events_from_arrivals({tick: [req]}))

    def dump(self) -> str:
        return dump_trace(self.events, self.meta)

    def write(self, path: str) -> None:
        write_trace(path, self.events, self.meta)
