"""``python -m ray_lightning_tpu loadgen`` — the trace-driven load
harness CLI + the format.sh smoke gate.

    python -m ray_lightning_tpu loadgen --out trace.jsonl --seed 7
    python -m ray_lightning_tpu loadgen --trace trace.jsonl
    python -m ray_lightning_tpu loadgen --smoke

``--out`` generates a versioned workload trace (seeded Poisson/MMPP
arrivals, heavy-tailed lengths, traffic-class mix). ``--trace``
replays one through a REAL inline `ServeDriver` session with the SLO
machinery armed and prints the per-class outcome. ``--smoke``
(docs/SERVING.md "traffic & SLO classes") runs three CPU legs and
exits 1 unless ALL hold:

  * **trace leg** — the generator is byte-deterministic (same seed =>
    identical canonical trace twice, different seed => different), a
    write/read round-trip re-serializes identically, and an unknown
    trace version is refused, never misread;
  * **replay leg** — a seeded bursty mixed-class MMPP trace drives an
    inline session TWICE on the virtual clock: identical token
    streams, identical per-class completion/shed accounting, and an
    identical shed-rid set both runs; every completed stream is
    bitwise-identical to single-stream `generate()`; the burst
    demonstrably starves best-effort (typed shed records with
    retry-after hints, ZERO latency-critical sheds) while
    latency-critical p95 TTFT meets its SLO; preemption fires; every
    trace rid ends terminal (completed or shed — zero silent drops,
    RLT505); churn + preemption compile the decode step exactly once;
    and a `class_slo_rules` watch poll lands the class-scoped
    ``shed_best_effort`` incident in incidents.jsonl without paging
    latency-critical;
  * **process leg** — a mixed-class trace against a REAL worker
    process, best-effort admission budget 0: every best-effort rid
    sheds with a typed record fanned in over the channel, survivors
    land bitwise, the shed counter matches the meta ledger exactly,
    and the compile count stays 1.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

import numpy as np


def add_loadgen_parser(sub) -> None:
    p = sub.add_parser(
        "loadgen",
        help="trace-driven load harness: generate/replay seeded "
             "workload traces against the serving stack, or the "
             "format.sh smoke gate (docs/SERVING.md)")
    p.add_argument("--smoke", action="store_true",
                   help="gate mode (see module docstring); exit 1 on "
                        "any failed leg")
    p.add_argument("--out", default=None,
                   help="generate a workload trace to this path")
    p.add_argument("--trace", default=None,
                   help="replay a trace file through an inline "
                        "serving session (SLO machinery armed)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--process", choices=("poisson", "mmpp"),
                   default="poisson", dest="arrival_process")
    p.add_argument("--rate", type=float, default=2.0,
                   help="mean arrivals per virtual tick (calm state)")
    p.add_argument("--burst-rate", type=float, default=8.0,
                   help="MMPP burst-state mean arrivals per tick")
    p.add_argument("--json", action="store_true", dest="as_json",
                   default=False)


def _mixed_slo(be_budget=1):
    """CI-safe targets: generous enough that a loaded CPU box cannot
    flake the attainment check, tight enough that the per-class story
    (best-effort sheds, latency-critical holds) is real."""
    from ray_lightning_tpu.serve.scheduler import ClassSLO, SLOConfig

    return SLOConfig(classes={
        "latency_critical": ClassSLO(ttft_p95_s=10.0, tpot_p95_s=5.0),
        "standard": ClassSLO(ttft_p95_s=30.0, tpot_p95_s=10.0),
        "best_effort": ClassSLO(ttft_p95_s=60.0, tpot_p95_s=20.0,
                                queue_budget=be_budget),
    })


def _burst_workload(seed: int = 7, n: int = 18):
    from ray_lightning_tpu.loadgen.generator import WorkloadConfig

    return WorkloadConfig(
        seed=seed, n_requests=n, process="mmpp", rate=0.5,
        burst_rate=6.0, p_enter_burst=0.25, p_exit_burst=0.25,
        prompt_len_min=3, prompt_len_max=10, prompt_len_alpha=1.5,
        max_new_min=3, max_new_max=10, max_new_alpha=1.2,
        class_mix={"latency_critical": 0.3, "standard": 0.3,
                   "best_effort": 0.4})


def _setup_model(seed: int = 1):
    """Tiny f32 model on the serve smoke's deterministic init path —
    the oracle is the same `generate()` the serving gate pins
    against."""
    import jax
    import jax.numpy as jnp

    from ray_lightning_tpu.models.llama import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(use_flash=False, dtype=jnp.float32)
    model = Llama(cfg)
    probe = np.zeros((1, 4), np.int32)
    params = jax.jit(model.init)(jax.random.key(seed), probe)["params"]
    return cfg, model, params


def _trace_refs(model, params, events):
    """generate() oracle for a trace's requests (completed streams
    must match bitwise; shed streams are excluded by the caller)."""
    from ray_lightning_tpu.loadgen.trace import to_request
    from ray_lightning_tpu.serve.cli import _references

    reqs = [to_request(ev) for ev in events]
    prompts = [np.asarray(ev.prompt, np.int32)[None, :]
               for ev in events]
    return _references(model, params, prompts, reqs)


def _run_trace_inline(cfg, params, events, slo, run_dir, ecfg=None):
    """One virtual-clock replay through a fresh inline session."""
    from ray_lightning_tpu.loadgen.runner import run_trace
    from ray_lightning_tpu.loadgen.trace import arrivals_by_tick
    from ray_lightning_tpu.serve.driver import (
        ReplicaGroupConfig, ServeDriver,
    )
    from ray_lightning_tpu.serve.engine import EngineConfig

    ecfg = ecfg or EngineConfig(capacity=2, block_size=4,
                                blocks_per_slot=8, prefill_chunk=4)
    drv = ServeDriver(cfg, params, ReplicaGroupConfig(
        n_replicas=1, backend="inline", engine=ecfg, run_dir=run_dir,
        metrics_flush_every_n_ticks=2, slo=slo))
    drv.start()
    sim = run_trace(drv, arrivals_by_tick(events),
                    idle_ticks_after_drain=4)
    return drv, sim


def _per_class(meta: dict) -> dict:
    """The per-class accounting the determinism pin compares."""
    out: dict = {}
    for m in meta.values():
        cls = m.get("priority", "standard")
        kind = "sheds" if m.get("finish_reason") == "shed" \
            else "completions"
        c = out.setdefault(cls, {"completions": 0, "sheds": 0})
        c[kind] += 1
    return out


def _shed_rids(meta: dict) -> list:
    return sorted(r for r, m in meta.items()
                  if m.get("finish_reason") == "shed")


def _p95(values) -> float:
    vals = sorted(values)
    if not vals:
        return 0.0
    return vals[min(len(vals) - 1,
                    max(0, int(np.ceil(0.95 * len(vals))) - 1))]


def _smoke_trace_leg(failures: list) -> dict:
    from ray_lightning_tpu.loadgen.generator import generate_events
    from ray_lightning_tpu.loadgen.trace import (
        dump_trace, read_trace, write_trace,
    )

    wl = _burst_workload()
    a = dump_trace(generate_events(wl), wl.meta())
    b = dump_trace(generate_events(wl), wl.meta())
    wl2 = _burst_workload(seed=wl.seed + 1)
    c = dump_trace(generate_events(wl2), wl2.meta())
    leg = {"bytes": len(a), "deterministic": a == b,
           "seed_sensitive": a != c}
    if a != b:
        failures.append(
            "generator is not byte-deterministic: same config "
            "produced two different canonical traces")
    if a == c:
        failures.append(
            "generator ignored the seed: seeds "
            f"{wl.seed}/{wl2.seed} produced the identical trace")
    with tempfile.TemporaryDirectory(prefix="rlt-loadgen-") as tmp:
        path = os.path.join(tmp, "trace.jsonl")
        events = generate_events(wl)
        write_trace(path, events, wl.meta())
        header, back = read_trace(path)
        leg["events"] = len(back)
        if dump_trace(back, header["meta"]) != a:
            failures.append(
                "trace write/read round-trip did not re-serialize "
                "byte-identically")
        # version refusal: a future trace must error, never misread
        with open(path) as f:
            lines = f.read().splitlines()
        doc = json.loads(lines[0])
        doc["version"] = 999
        with open(path, "w") as f:
            f.write("\n".join([json.dumps(doc)] + lines[1:]) + "\n")
        try:
            read_trace(path)
            failures.append(
                "a version-999 trace was read instead of refused")
            leg["version_refused"] = False
        except ValueError:
            leg["version_refused"] = True
    return leg


def _smoke_replay_leg(failures: list, cfg, model, params) -> dict:
    from ray_lightning_tpu.loadgen.generator import generate_events
    from ray_lightning_tpu.serve.cli import _check_outputs
    from ray_lightning_tpu.telemetry.watch import (
        WatchConfig, WatchEngine, class_slo_rules,
    )

    wl = _burst_workload()
    events = generate_events(wl)
    slo = _mixed_slo(be_budget=1)
    refs = _trace_refs(model, params, events)
    runs = []
    incidents = []
    for attempt in range(2):
        with tempfile.TemporaryDirectory(prefix="rlt-loadgen-") as tmp:
            run_dir = os.path.join(tmp, "run")
            drv, sim = _run_trace_inline(cfg, params, events, slo,
                                         run_dir)
            if attempt == 0:
                # poll the class-scoped SLO rules against the run's
                # OWN flushed metrics before the session retires its
                # replica from the live load signal
                eng = WatchEngine(run_dir, WatchConfig(
                    rules=class_slo_rules(slo), capture=False))
                eng.poll(now=1.0)
                incidents = list(eng.incidents)
            result = drv.stop()
            runs.append((sim, result))
    (sim0, res0), (sim1, res1) = runs
    per_class = _per_class(res0.meta)
    sheds0 = _shed_rids(res0.meta)
    done = {r: m for r, m in res0.meta.items()
            if m["finish_reason"] != "shed"}
    lc_ttft = [m["ttft_s"] for m in done.values()
               if m["priority"] == "latency_critical"]
    preempted = sum(m.get("preempted", 0)
                    for m in res0.meta.values())
    bad = _check_outputs(res0.outputs,
                         {r: refs[r] for r in done})
    leg = {
        "requests": len(events),
        "ticks": (sim0["ticks"], sim1["ticks"]),
        "per_class": per_class,
        "sheds": sheds0,
        "preempted_resumes": preempted,
        "lc_ttft_p95_s": round(_p95(lc_ttft), 4),
        "bitwise_mismatches": bad,
        "compile_count": res0.stats["compile_count"],
        "incidents": [i["rule"] for i in incidents],
    }
    if res0.outputs != res1.outputs:
        failures.append(
            "replay is not deterministic: the same trace produced "
            "different token streams across two runs")
    acct = [(r, m["finish_reason"], m["priority"])
            for r, m in sorted(res0.meta.items())]
    acct1 = [(r, m["finish_reason"], m["priority"])
             for r, m in sorted(res1.meta.items())]
    if acct != acct1 or _per_class(res1.meta) != per_class:
        failures.append(
            "per-class accounting diverged across two replays of the "
            "same trace")
    if sheds0 != _shed_rids(res1.meta):
        failures.append(
            f"shed-rid set diverged across replays: {sheds0} vs "
            f"{_shed_rids(res1.meta)}")
    if bad:
        failures.append(
            f"completed streams diverge from generate() under "
            f"mixed-class churn + preemption: {bad}")
    missing = sorted({e.rid for e in events} - set(res0.meta))
    odd = [r for r, m in res0.meta.items()
           if m["finish_reason"] not in ("eos", "length", "shed")]
    if missing or odd:
        failures.append(
            f"silent request drop (RLT505): rids without a terminal "
            f"record {missing}, non-terminal reasons {odd}")
    be = per_class.get("best_effort", {})
    lc = per_class.get("latency_critical", {})
    if not be.get("sheds"):
        failures.append(
            "the burst did not shed best-effort — the overload leg "
            f"is not exercising degradation (per-class {per_class})")
    if lc.get("sheds"):
        failures.append(
            f"latency-critical was shed ({lc['sheds']} records) — "
            "shedding must never reach a non-shed class")
    shed_meta = [res0.meta[r] for r in sheds0]
    unhinted = [m for m in shed_meta
                if not (m.get("reason") and
                        m.get("retry_after_s", 0) > 0)]
    if unhinted:
        failures.append(
            f"shed records missing reason/retry-after hints: "
            f"{unhinted[:3]}")
    if not lc_ttft or _p95(lc_ttft) > 10.0:
        failures.append(
            f"latency-critical p95 TTFT {_p95(lc_ttft):.3f}s missed "
            "its 10s SLO under the burst (or no latency-critical "
            "stream completed)")
    if preempted < 1:
        failures.append(
            "no preemption under the burst — the policy-ordered "
            "preemption seam was not exercised")
    if res0.stats["compile_count"] not in (1, -1):
        failures.append(
            f"mixed-class churn + preemption recompiled the decode "
            f"step: compile_count={res0.stats['compile_count']}")
    fired = [i["rule"] for i in incidents]
    if fired.count("shed_best_effort") != 1:
        failures.append(
            f"expected exactly one class-scoped shed_best_effort "
            f"incident in incidents.jsonl, watch fired {fired}")
    if "slo_ttft_latency_critical" in fired:
        failures.append(
            "latency-critical paged its TTFT SLO rule during the "
            "burst — degradation is not graceful")
    return leg


def _smoke_process_leg(failures: list) -> dict:
    import time

    from ray_lightning_tpu.loadgen.trace import TraceEvent, to_request
    from ray_lightning_tpu.serve.cli import _check_outputs
    from ray_lightning_tpu.serve.driver import (
        ReplicaGroupConfig, ServeDriver, save_params_npz,
    )
    from ray_lightning_tpu.serve.engine import EngineConfig

    cfg, model, params = _setup_model()
    rng = np.random.Generator(np.random.PCG64(77))
    classes = ["latency_critical", "standard", "best_effort",
               "standard", "latency_critical", "best_effort",
               "standard", "latency_critical"]
    events = [TraceEvent(
        tick=i // 3, rid=f"pg{i:02d}",
        prompt=tuple(int(t) for t in rng.integers(
            0, cfg.vocab_size, size=3 + i % 4)),
        max_new_tokens=6, priority=classes[i],
        temperature=0.8 if i % 2 else 0.0,
        top_k=5 if i % 2 else None, seed=31 + i)
        for i in range(len(classes))]
    # budget 0: EVERY best-effort arrival sheds at enqueue — the shed
    # set is deterministic even against a free-running worker process
    slo = _mixed_slo(be_budget=0)
    survivors = [e for e in events if e.priority != "best_effort"]
    refs = _trace_refs(model, params, survivors)
    with tempfile.TemporaryDirectory(prefix="rlt-loadgen-") as tmp:
        run_dir = os.path.join(tmp, "run")
        os.makedirs(run_dir, exist_ok=True)
        ppath = os.path.join(run_dir, "params.npz")
        save_params_npz(params, ppath)
        drv = ServeDriver(cfg, ppath, ReplicaGroupConfig(
            n_replicas=1, backend="process",
            engine=EngineConfig(capacity=2, block_size=4,
                                blocks_per_slot=8, prefill_chunk=4),
            run_dir=run_dir, platform="cpu", cpu_devices_per_rank=1,
            metrics_flush_every_n_ticks=2, slo=slo))
        drv.start()
        for ev in events:
            drv.submit(to_request(ev))
        while drv.busy():
            drv.tick()
            time.sleep(0.01)
        result = drv.stop()
    sheds = _shed_rids(result.meta)
    want_shed = sorted(e.rid for e in events
                       if e.priority == "best_effort")
    bad = _check_outputs(result.outputs, refs)
    leg = {
        "requests": len(events), "sheds": sheds,
        "bitwise_mismatches": bad,
        "requests_shed_counter": result.stats.get("requests_shed"),
        "compile_count": result.stats["compile_count"],
    }
    if sheds != want_shed:
        failures.append(
            f"process-backend shed set {sheds} != every best-effort "
            f"rid {want_shed} (admission budget 0 must shed "
            "deterministically over the channel)")
    if bad:
        failures.append(
            f"process-backend survivor streams diverge from "
            f"generate() around the sheds: {bad}")
    if result.stats.get("requests_shed") != len(want_shed):
        failures.append(
            f"driver shed counter {result.stats.get('requests_shed')} "
            f"!= {len(want_shed)} shed meta records — the typed "
            "records and the counter must agree")
    missing = sorted({e.rid for e in events} - set(result.meta))
    if missing:
        failures.append(
            f"silent request drop over the channel (RLT505): "
            f"{missing}")
    if result.stats["compile_count"] not in (1, -1):
        failures.append(
            f"process-backend compile_count="
            f"{result.stats['compile_count']}, want 1")
    return leg


def run_smoke(args) -> int:
    """The format.sh gate (module docstring for the leg list), CPU."""
    verdict: dict = {"legs": {}}
    failures: list = []
    verdict["legs"]["trace"] = _smoke_trace_leg(failures)
    cfg, model, params = _setup_model()
    verdict["legs"]["replay"] = _smoke_replay_leg(failures, cfg,
                                                 model, params)
    verdict["legs"]["process"] = _smoke_process_leg(failures)
    verdict["ok"] = not failures
    if failures:
        verdict["failures"] = failures
    print(json.dumps(verdict))
    if failures:
        for f in failures:
            print(f"loadgen --smoke FAILED: {f}", file=sys.stderr)
        return 1
    return 0


def _run_generate(args) -> int:
    from ray_lightning_tpu.loadgen.generator import (
        WorkloadConfig, generate_events,
    )
    from ray_lightning_tpu.loadgen.trace import write_trace

    wl = WorkloadConfig(seed=args.seed, n_requests=args.requests,
                        process=args.arrival_process, rate=args.rate,
                        burst_rate=args.burst_rate)
    events = generate_events(wl)
    write_trace(args.out, events, wl.meta())
    by_class: dict = {}
    for e in events:
        by_class[e.priority] = by_class.get(e.priority, 0) + 1
    line = {"trace": args.out, "events": len(events),
            "ticks": max(e.tick for e in events) + 1,
            "by_class": by_class}
    print(json.dumps(line) if args.as_json else
          f"wrote {line['events']} events over {line['ticks']} ticks "
          f"to {args.out} ({by_class})")
    return 0


def _run_replay(args) -> int:
    from ray_lightning_tpu.loadgen.trace import read_trace

    header, events = read_trace(args.trace)
    cfg, model, params = _setup_model()
    over = [e.rid for e in events
            if e.prompt and max(e.prompt) >= cfg.vocab_size]
    if over:
        print(f"error: trace tokens exceed the tiny model's vocab "
              f"({cfg.vocab_size}): {over[:5]}", file=sys.stderr)
        return 2
    slo = _mixed_slo()
    with tempfile.TemporaryDirectory(prefix="rlt-loadgen-") as tmp:
        drv, sim = _run_trace_inline(cfg, params, events, slo,
                                     os.path.join(tmp, "run"))
        result = drv.stop()
    per_class = _per_class(result.meta)
    done = [m for m in result.meta.values()
            if m["finish_reason"] != "shed"]
    attain = {}
    for cls, spec in sorted(slo.classes.items()):
        ttfts = [m["ttft_s"] for m in done if m["priority"] == cls]
        if ttfts:
            attain[cls] = {
                "ttft_p95_s": round(_p95(ttfts), 4),
                "slo_met": _p95(ttfts) <= spec.ttft_p95_s}
    line = {"trace": args.trace, "events": len(events),
            "ticks": sim["ticks"], "per_class": per_class,
            "slo_attainment": attain,
            "compile_count": result.stats["compile_count"]}
    print(json.dumps(line) if args.as_json else
          f"replayed {len(events)} events over {sim['ticks']} ticks: "
          f"{per_class} attainment {attain}")
    return 0


def run_loadgen(args) -> int:
    if args.smoke:
        return run_smoke(args)
    if args.out:
        return _run_generate(args)
    if args.trace:
        return _run_replay(args)
    print("loadgen: one of --smoke / --out / --trace required",
          file=sys.stderr)
    return 2
