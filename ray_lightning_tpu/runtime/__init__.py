"""Runtime substrate (L0' of SURVEY §7.2): worker launch, env bootstrap,
closure shipping, result futures, side-channel queue, SPMD coordination.

Replaces the reference's use of Ray core (actors/object store/queue actor,
reference ray_ddp.py:17-39,106-213, util.py:22-109, session.py:1-63) with
subprocesses + multiprocessing.connection + cloudpickle, and the
MASTER_ADDR/PORT rendezvous (ray_ddp.py:152-156) with a jax.distributed
coordinator.
"""
from ray_lightning_tpu.runtime.group import (
    TpuExecutor,
    WorkerError,
    WorkerGroup,
    find_free_port,
    routable_ip,
)
from ray_lightning_tpu.runtime.fit import (
    FitResult,
    fit_distributed,
    predict_distributed,
    run_distributed,
    test_distributed,
    validate_distributed,
)
from ray_lightning_tpu.runtime.launch import launch, launch_cpu_spmd
from ray_lightning_tpu.runtime.transport import (
    LocalTransport,
    LoopbackTransport,
    SSHTransport,
    Transport,
)
from ray_lightning_tpu.runtime.session import (
    get_actor_rank,
    get_session,
    get_world_size,
    init_session,
    is_session_enabled,
    put_queue,
    reset_session,
)

__all__ = [
    "FitResult",
    "fit_distributed",
    "run_distributed",
    "validate_distributed",
    "test_distributed",
    "predict_distributed",
    "TpuExecutor",
    "WorkerError",
    "WorkerGroup",
    "find_free_port",
    "routable_ip",
    "launch",
    "launch_cpu_spmd",
    "LocalTransport",
    "LoopbackTransport",
    "SSHTransport",
    "Transport",
    "get_actor_rank",
    "get_session",
    "get_world_size",
    "init_session",
    "is_session_enabled",
    "put_queue",
    "reset_session",
]
