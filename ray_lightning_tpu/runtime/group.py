"""Driver-side runtime substrate: worker processes, futures, result pump.

This is the rebuild of the reference's L2/L0 usage of Ray core — actor
creation with resource options (reference ray_ddp.py:106-119), env-var
injection (:158-164), fan-out of ``train_remote`` (:178-182), the
``process_results`` future/queue pump (reference util.py:96-109), and
teardown (:201-213) — with plain subprocesses + ``multiprocessing.connection``
instead of Ray's GCS/raylet/plasma, and ``connection.wait`` (a real select)
instead of the reference's ``ray.wait(timeout=0)`` busy-poll
(a consciously-fixed quirk, SURVEY §2.4).

Pieces:
  * TpuExecutor  — handle to ONE worker process (RayExecutor analog,
    reference ray_ddp.py:17-39): execute/execute_async, set_env_vars,
    get_node_ip, kill.
  * WorkerGroup  — N executors + the pump: run() fans a closure to every
    rank, pumps side-channel items (executing callables driver-side, the
    trampoline of reference util.py:88-93), gathers per-rank results,
    fail-fast on the first worker error (reference failure model, §5.3).
"""
from __future__ import annotations

import os
import secrets
import socket
import subprocess
import threading
import time
from multiprocessing.connection import Connection, Listener, wait as conn_wait
from typing import Any, Callable, Dict, List, Optional, Sequence

import cloudpickle

from ray_lightning_tpu.runtime.transport import LocalTransport, Transport
from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)


def find_free_port(host: str = "127.0.0.1") -> int:
    """Reference analog: ray_ddp.py:152-156's MASTER_PORT discovery — here
    used for the driver listener and the jax.distributed coordinator."""
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def routable_ip() -> str:
    """This machine's address as other hosts see it (reference analog:
    ``get_node_ip``, ray_ddp.py:33-35). UDP-connect trick — no packet is
    sent; falls back to loopback on isolated boxes."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


def _accept_with_deadline(listener: Listener, timeout: float):
    """``listener.accept()`` bounded by ``timeout``; returns None on expiry.

    accept() is unboundedly blocking — not just the socket accept but the
    authkey challenge that follows on the accepted connection, which a
    stalled/hostile peer (possible once the listener binds 0.0.0.0 for
    remote transports) could hold open forever. Run it on a daemon thread
    and abandon it at the deadline; an abandoned thread parked on a dead
    connection costs nothing and dies with the process.
    """
    box: Dict[str, Any] = {}
    done = threading.Event()

    def _run():
        try:
            box["conn"] = listener.accept()
        except Exception as exc:  # noqa: BLE001 — relayed to the caller
            box["err"] = exc
        done.set()

    threading.Thread(target=_run, daemon=True).start()
    if not done.wait(timeout):
        return None
    if "err" in box:
        if isinstance(box["err"], (OSError, EOFError)):
            # auth failure / scanner disconnect: treat as "nobody valid
            # connected" and let the caller's deadline loop continue
            log.warning("listener accept failed: %s", box["err"])
            return None
        raise box["err"]
    return box["conn"]


class WorkerError(RuntimeError):
    def __init__(self, rank: int, traceback_str: str, log_tail: str = ""):
        self.rank = rank
        self.traceback_str = traceback_str
        msg = f"worker rank {rank} failed:\n{traceback_str}"
        if log_tail:
            msg += f"\n--- worker log tail ---\n{log_tail}"
        super().__init__(msg)


class TpuExecutor:
    """One remote worker process (reference RayExecutor, ray_ddp.py:17-39)."""

    def __init__(self, rank: int, world: int, proc: subprocess.Popen,
                 conn: Connection, info: Dict[str, Any], log_path: str,
                 host: Optional[str] = None):
        self.rank = rank
        self.world = world
        self.proc = proc
        self.conn = conn
        self.info = info
        self.log_path = log_path
        self.host = host  # placement target (None = driver machine)
        self._next_tid = 0

    # -- RayExecutor API parity -------------------------------------------
    def set_env_vars(self, env: Dict[str, str]) -> None:
        """reference ray_ddp.py:27-31 (no ack needed: FIFO ordering)."""
        self.conn.send(("env", dict(env)))

    def get_node_ip(self) -> str:
        """reference ray_ddp.py:33-35."""
        return self.info.get("ip", "127.0.0.1")

    def execute_async(self, fn: Callable, *args, **kwargs) -> int:
        """Ship a closure; returns a task id to await via WorkerGroup."""
        tid = self._next_tid
        self._next_tid += 1
        blob = cloudpickle.dumps((fn, args, kwargs))
        self.conn.send(("exec", tid, blob))
        return tid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def log_tail(self, n: int = 40) -> str:
        try:
            with open(self.log_path, "r", errors="replace") as f:
                return "".join(f.readlines()[-n:])
        except OSError:
            return ""

    def kill(self) -> None:
        if self.alive():
            self.proc.kill()
        try:
            self.conn.close()
        except OSError:
            pass


class WorkerGroup:
    """N worker processes + the result/queue pump.

    Lifecycle mirrors the reference plugin's setup/start_training/
    post_dispatch (ray_ddp.py:113-213):

        group = WorkerGroup(num_workers=4, env={...}, init_hook=fn)
        group.start()                      # spawn + hello + init_hook
        results = group.run(train_fn)      # fan-out, pump, gather
        group.shutdown()                   # graceful, then kill
    """

    def __init__(
        self,
        num_workers: Optional[int] = None,
        env: Optional[Dict[str, str]] = None,
        init_hook: Optional[Callable[[], None]] = None,
        log_dir: Optional[str] = None,
        start_timeout: float = 120.0,
        hosts: Optional[Sequence[str]] = None,
        transport: Optional[Transport] = None,
        advertise_host: Optional[str] = None,
    ):
        """``hosts`` + a remote ``transport`` place one worker per host
        (reference ray_ddp.py:106-119's cluster-wide actor placement; on a
        TPU pod: one entry per host VM). Without them, workers are local
        subprocesses. ``advertise_host`` overrides the driver address
        workers dial back to (defaults to the routable IP when remote)."""
        if num_workers is None:
            num_workers = len(hosts) if hosts else 1
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.num_workers = num_workers
        self.env = dict(env or {})
        self.init_hook = init_hook
        self.log_dir = log_dir or os.path.join(
            os.getcwd(), "rlt_logs", "workers"
        )
        self.start_timeout = start_timeout
        self.hosts = list(hosts) if hosts else None
        self.transport = transport or LocalTransport()
        if self.hosts and not self.transport.is_remote:
            # Without this, hosts=[...] + the default transport would
            # silently run every worker on the driver machine while
            # executor.host reports the requested (never-used) hostnames.
            raise ValueError(
                "hosts= requires a remote transport (e.g. SSHTransport); "
                f"got {type(self.transport).__name__}"
            )
        self.advertise_host = advertise_host
        self.executors: List[TpuExecutor] = []
        self._listener: Optional[Listener] = None
        self._queue_items: List[Any] = []

    @property
    def is_remote(self) -> bool:
        return self.transport.is_remote

    def _worker_host(self, rank: int) -> Optional[str]:
        if not self.hosts:
            return None
        return self.hosts[rank % len(self.hosts)]

    # ------------------------------------------------------------- launch
    def start(self) -> "WorkerGroup":
        os.makedirs(self.log_dir, exist_ok=True)
        authkey = secrets.token_bytes(32)
        # Remote workers must reach the driver: bind all interfaces and
        # advertise a routable address (the reference's Listener equivalent
        # was Ray's GCS, reachable cluster-wide by construction; loopback —
        # the round-1/2 limitation — only ever worked on one machine).
        bind_host = "0.0.0.0" if self.is_remote else "127.0.0.1"
        self._listener = Listener((bind_host, 0), authkey=authkey)
        port = self._listener.address[1]
        connect_host = self.advertise_host or (
            routable_ip() if self.is_remote else "127.0.0.1"
        )
        procs: Dict[int, subprocess.Popen] = {}
        logs: Dict[int, str] = {}
        try:
            for rank in range(self.num_workers):
                log_path = os.path.join(self.log_dir, f"worker-{rank}.log")
                logs[rank] = log_path
                procs[rank] = self.transport.spawn(
                    host=self._worker_host(rank),
                    connect=(connect_host, port, rank, self.num_workers),
                    env=self.env,
                    authkey_hex=authkey.hex(),
                    log_path=log_path,
                )
        except Exception:
            # A failed spawn (missing ssh binary, dead host) must not leak
            # the workers already started on other hosts or the listener.
            self._abort_start(procs, logs)
            raise
        # Accept hellos; connections arrive in arbitrary order — the hello
        # message carries the rank (cf. reference get_local_ranks building
        # the rank map driver-side, ray_ddp.py:130-141).
        by_rank: Dict[int, TpuExecutor] = {}
        deadline = time.monotonic() + self.start_timeout
        for _ in range(self.num_workers):
            conn = None
            while conn is None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._abort_start(procs, logs)
                    raise TimeoutError(
                        "workers did not all connect within "
                        f"{self.start_timeout}s"
                    )
                conn = _accept_with_deadline(self._listener, remaining)
            # Bound the hello read too: with the listener on 0.0.0.0 a
            # stray connection that never speaks must not wedge start().
            if not conn.poll(max(0.0, deadline - time.monotonic())):
                self._abort_start(procs, logs)
                raise TimeoutError(
                    "worker connected but sent no hello within "
                    f"{self.start_timeout}s"
                )
            cmd, rank, info = conn.recv()
            assert cmd == "hello", cmd
            by_rank[rank] = TpuExecutor(
                rank, self.num_workers, procs[rank], conn, info, logs[rank],
                host=self._worker_host(rank),
            )
        self.executors = [by_rank[r] for r in range(self.num_workers)]
        if self.init_hook is not None:
            # reference ray_ddp.py:118-119: run init_hook on every worker
            # and wait for completion before training starts.
            self.run(self.init_hook)
        return self

    def _abort_start(self, procs, logs) -> None:
        tails = []
        for rank, p in procs.items():
            if p.poll() is not None:
                try:
                    with open(logs[rank], errors="replace") as f:
                        tails.append(
                            f"rank {rank} exited rc={p.returncode}:\n"
                            + "".join(f.readlines()[-20:])
                        )
                except OSError:
                    pass
            p.kill()
        if self._listener is not None:
            self._listener.close()
            self._listener = None
        if tails:
            log.error("worker startup failure:\n%s", "\n".join(tails))

    # --------------------------------------------------------------- exec
    def set_env_vars(self, env: Dict[str, str]) -> None:
        for ex in self.executors:
            ex.set_env_vars(env)

    def run(
        self,
        fn: Callable,
        per_rank_args: Optional[Sequence[Sequence[Any]]] = None,
        on_queue_item: Optional[Callable[[int, Any], None]] = None,
        timeout: Optional[float] = None,
    ) -> List[Any]:
        """Fan ``fn`` out to every rank and pump until all return.

        The pump is the reference's ``process_results`` (util.py:96-109)
        rebuilt on a real select: side-channel items are handled as they
        arrive (callables executed driver-side — the tune.report trampoline,
        util.py:88-93), the first worker error raises WorkerError
        (fail-fast, SURVEY §5.3), and remaining results are gathered in
        rank order.
        """
        assert self.executors, "call start() first"
        tids = []
        for rank, ex in enumerate(self.executors):
            args = per_rank_args[rank] if per_rank_args is not None else ()
            tids.append(ex.execute_async(fn, *args))
        return self.wait(tids, on_queue_item=on_queue_item, timeout=timeout)

    def wait(
        self,
        tids: Sequence[int],
        on_queue_item: Optional[Callable[[int, Any], None]] = None,
        timeout: Optional[float] = None,
    ) -> List[Any]:
        results: Dict[int, Any] = {}
        done: Dict[int, bool] = {r: False for r in range(self.num_workers)}
        deadline = (
            (time.monotonic() + timeout) if timeout is not None else None
        )
        conns = {ex.conn: ex for ex in self.executors}
        while not all(done.values()):
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"workers still pending: "
                                   f"{[r for r, d in done.items() if not d]}")
            ready = conn_wait(list(conns), timeout=1.0)
            if not ready:
                self._check_liveness(done)
                continue
            for conn in ready:
                ex = conns[conn]
                try:
                    msg = conn.recv()
                except EOFError:
                    raise WorkerError(
                        ex.rank, "worker process died (EOF on channel)",
                        ex.log_tail(),
                    ) from None
                self._dispatch(msg, ex, tids, results, done, on_queue_item)
        self.drain_queue(on_queue_item)
        return [results[r] for r in range(self.num_workers)]

    def run_single(
        self, rank: int, fn: Callable, *args,
        timeout: Optional[float] = None, **kwargs,
    ) -> Any:
        """Execute ``fn`` on ONE rank and return its result (the analog of
        the reference's targeted ``worker.execute.remote`` calls — e.g. the
        MASTER_PORT probe on worker 0, ray_ddp.py:152-156)."""
        assert self.executors, "call start() first"
        ex = self.executors[rank]
        tid = ex.execute_async(fn, *args, **kwargs)
        deadline = (
            (time.monotonic() + timeout) if timeout is not None else None
        )
        while True:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"rank {rank} still pending")
            if not ex.conn.poll(1.0):
                if not ex.alive():
                    raise WorkerError(
                        ex.rank,
                        f"worker process exited rc={ex.proc.returncode} "
                        "without returning a result",
                        ex.log_tail(),
                    )
                continue
            try:
                msg = ex.conn.recv()
            except EOFError:
                raise WorkerError(
                    ex.rank, "worker process died (EOF on channel)",
                    ex.log_tail(),
                ) from None
            cmd = msg[0]
            if cmd == "result" and msg[1] == tid:
                return cloudpickle.loads(msg[2])
            elif cmd == "error":
                if msg[1] == tid:
                    raise WorkerError(ex.rank, msg[2], ex.log_tail())
                log.warning("dropping stale error from rank %d", ex.rank)
            elif cmd == "queue":
                qrank, item = cloudpickle.loads(msg[1])
                self._handle_queue_item(qrank, item, None)

    def _dispatch(self, msg, ex, tids, results, done, on_queue_item) -> None:
        cmd = msg[0]
        if cmd == "result":
            tid, blob = msg[1], msg[2]
            if tid == tids[ex.rank]:
                results[ex.rank] = cloudpickle.loads(blob)
                done[ex.rank] = True
        elif cmd == "error":
            # Stale errors from an earlier, already-raised run stay buffered
            # on the other ranks' connections; only raise for THIS task.
            if msg[1] == tids[ex.rank]:
                raise WorkerError(ex.rank, msg[2], ex.log_tail())
            log.warning(
                "dropping stale error from rank %d (task %s): %s",
                ex.rank, msg[1], msg[2].splitlines()[-1] if msg[2] else "",
            )
        elif cmd == "queue":
            rank, item = cloudpickle.loads(msg[1])
            self._handle_queue_item(rank, item, on_queue_item)
        elif cmd == "bye":
            done[ex.rank] = True

    def _handle_queue_item(self, rank, item, on_queue_item) -> None:
        """The trampoline (reference util.py:88-93): callables run here, in
        the driver process — this is how tune.report-style closures created
        on worker rank 0 execute inside the driver's sweep session."""
        if on_queue_item is not None:
            on_queue_item(rank, item)
        elif callable(item):
            item()
        else:
            self._queue_items.append((rank, item))

    def drain_queue(self, on_queue_item=None) -> None:
        """Post-completion drain (reference util.py:106-109)."""
        for conn, ex in {ex.conn: ex for ex in self.executors}.items():
            while conn.poll(0):
                try:
                    msg = conn.recv()
                except EOFError:
                    break
                if msg[0] == "queue":
                    rank, item = cloudpickle.loads(msg[1])
                    self._handle_queue_item(rank, item, on_queue_item)

    def queue_items(self) -> List[Any]:
        items, self._queue_items = self._queue_items, []
        return items

    def _check_liveness(self, done) -> None:
        for ex in self.executors:
            if not done[ex.rank] and not ex.alive():
                raise WorkerError(
                    ex.rank,
                    f"worker process exited rc={ex.proc.returncode} "
                    "without returning a result",
                    ex.log_tail(),
                )

    # ------------------------------------------------------------ teardown
    def shutdown(self, timeout: float = 10.0) -> None:
        """Graceful shutdown, then kill — reference post_dispatch
        (ray_ddp.py:201-213) with `ray.kill` replaced by SIGKILL."""
        for ex in self.executors:
            if ex.alive():
                try:
                    ex.conn.send(("shutdown",))
                except (BrokenPipeError, OSError):
                    pass
        deadline = time.monotonic() + timeout
        for ex in self.executors:
            remaining = max(0.0, deadline - time.monotonic())
            try:
                ex.proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                ex.kill()
            try:
                ex.conn.close()
            except OSError:
                pass
        if self._listener is not None:
            self._listener.close()
            self._listener = None
        self.executors = []

    def __enter__(self) -> "WorkerGroup":
        return self.start() if not self.executors else self

    def __exit__(self, *exc) -> None:
        self.shutdown()
