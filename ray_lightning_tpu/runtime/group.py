"""Driver-side runtime substrate: worker processes, futures, result pump.

This is the rebuild of the reference's L2/L0 usage of Ray core — actor
creation with resource options (reference ray_ddp.py:106-119), env-var
injection (:158-164), fan-out of ``train_remote`` (:178-182), the
``process_results`` future/queue pump (reference util.py:96-109), and
teardown (:201-213) — with plain subprocesses + ``multiprocessing.connection``
instead of Ray's GCS/raylet/plasma, and ``connection.wait`` (a real select)
instead of the reference's ``ray.wait(timeout=0)`` busy-poll
(a consciously-fixed quirk, SURVEY §2.4).

Pieces:
  * TpuExecutor  — handle to ONE worker process (RayExecutor analog,
    reference ray_ddp.py:17-39): execute/execute_async, set_env_vars,
    get_node_ip, kill.
  * WorkerGroup  — N executors + the pump: run() fans a closure to every
    rank, pumps side-channel items (executing callables driver-side, the
    trampoline of reference util.py:88-93), gathers per-rank results,
    fail-fast on the first worker error (reference failure model, §5.3).
"""
from __future__ import annotations

import hashlib
import os
import secrets
import socket
import subprocess
import threading
import time
from multiprocessing.connection import Connection, Listener, wait as conn_wait
from typing import Any, Callable, Dict, List, Optional, Sequence

import cloudpickle

from ray_lightning_tpu.analysis.lockwatch import san_lock
from ray_lightning_tpu.runtime.transport import LocalTransport, Transport
from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)


def find_free_port(host: str = "127.0.0.1") -> int:
    """Reference analog: ray_ddp.py:152-156's MASTER_PORT discovery — here
    used for the driver listener and the jax.distributed coordinator."""
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def routable_ip() -> str:
    """This machine's address as other hosts see it (reference analog:
    ``get_node_ip``, ray_ddp.py:33-35). ``RLT_NODE_IP`` overrides — the
    multi-NIC escape hatch: the UDP-connect trick picks the
    default-route interface, which on a multi-homed cluster host may not
    be the fabric the other hosts dial (set RLT_NODE_IP per host via the
    transport's host_env to pin the data-network address). No packet
    is sent; falls back to loopback on isolated boxes — callers on a
    remote path must treat that fallback as an error (see
    WorkerGroup.start), not an address."""
    override = os.environ.get("RLT_NODE_IP")
    if override:
        return override
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


def _split_accept_supported(listener: Listener) -> bool:
    """True when the stdlib internals the split accept/auth path needs
    exist: the raw socket listener and the challenge-pair functions."""
    from multiprocessing import connection as mpc

    raw = getattr(listener, "_listener", None)
    return (
        raw is not None
        and callable(getattr(raw, "accept", None))
        and callable(getattr(mpc, "deliver_challenge", None))
        and callable(getattr(mpc, "answer_challenge", None))
    )


class _HelloAcceptor:
    """Accept worker connections without letting any single peer wedge
    startup.

    ``Listener.accept()`` is unboundedly blocking — not just the socket
    accept but the authkey HMAC challenge that follows, which a
    stalled/hostile peer (possible once the listener binds a non-loopback
    interface for remote transports) could hold open forever. Split the
    two (the same pattern as the sweep report server, tuner.py): one
    daemon thread does raw socket accepts only, each authentication runs
    on its own per-connection daemon thread, and authenticated
    connections land on a queue the caller polls in short slices — so
    the caller can also notice dead worker processes between slices
    (spawn fail-fast)."""

    def __init__(self, listener: Listener, authkey: bytes):
        import queue

        self._listener = listener
        self._authkey = authkey
        self._open = True
        # serializes enqueue-vs-close so a connection that authenticates
        # concurrently with close() is closed, never stranded on the queue
        self._lock = san_lock("runtime.group.accept")
        self._conns: "queue.Queue" = queue.Queue()
        # The split accept/auth path rides on stdlib internals
        # (Listener._listener raw accept; the deliver/answer challenge
        # pair). Stable across supported CPythons today, but a minor
        # release could move them — feature-detect and degrade to the
        # public blocking accept() (auth runs inline on the accept
        # thread, so one stalled peer serializes — but startup still
        # works) rather than breaking every driver start.
        self._split = _split_accept_supported(listener)
        if not self._split:
            log.warning(
                "multiprocessing internals moved (Listener._listener / "
                "deliver_challenge); using public blocking accept() — a "
                "stalled peer can delay, though not wedge, startup"
            )
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self) -> None:
        while self._open:
            if not self._split:
                try:
                    # public API: socket accept + authkey challenge inline
                    conn = self._listener.accept()
                except Exception:  # noqa: BLE001 — closed/auth-fail/transient
                    if not self._open:
                        return
                    log.warning("listener accept failed", exc_info=True)
                    time.sleep(0.05)
                    continue
                self._enqueue(conn)
                continue
            try:
                # socket-level accept (internal but stable: returns the
                # raw Connection, no challenge)
                raw = self._listener._listener.accept()
            except Exception:  # noqa: BLE001 — closed or transient
                if not self._open:
                    return
                log.warning("listener accept failed", exc_info=True)
                time.sleep(0.05)  # no hot spin if the listener just closed
                continue
            threading.Thread(
                target=self._challenge, args=(raw,), daemon=True
            ).start()

    def _challenge(self, raw) -> None:
        from multiprocessing import connection as mpc

        try:
            # the exact handshake Listener.accept() performs
            mpc.deliver_challenge(raw, self._authkey)
            mpc.answer_challenge(raw, self._authkey)
        except Exception as exc:  # noqa: BLE001 — scanner/hostile peer
            log.warning("worker handshake failed: %s", exc)
            try:
                raw.close()
            except OSError:
                pass
            return
        self._enqueue(raw)

    def _enqueue(self, conn) -> None:
        # under the lock: close() flips _open under the same lock, so a
        # post-close enqueue is impossible — the straggler (late
        # authenticator racing the final drain) is closed instead of
        # being parked forever on a queue nobody reads
        with self._lock:
            if self._open:
                self._conns.put(conn)
                return
        try:
            conn.close()
        except OSError:
            pass

    def get(self, timeout: float):
        """Next authenticated connection, or None after ``timeout``."""
        import queue

        try:
            return self._conns.get(timeout=max(0.0, timeout))
        except queue.Empty:
            return None

    def close(self) -> None:
        with self._lock:
            self._open = False
        # drop anything that authenticated after the last get(): holding
        # it would leave that worker blocked waiting for commands forever
        while True:
            conn = self.get(0.0)
            if conn is None:
                return
            try:
                conn.close()
            except OSError:
                pass


class WorkerError(RuntimeError):
    """A worker failed. ``cause`` classifies HOW (the resilience policy
    keys on it — see resilience/policy.py):

      "exception" — the worker returned a Python traceback (a real bug
                    in user/model code; ``traceback_str`` carries it)
      "signal"    — the process was killed by ``signal_name`` (negative
                    returncode: SIGKILL'd by the OOM killer, SIGTERM'd
                    by a preemption, ...)
      "exit"      — the process exited with ``exit_code`` without
                    returning a result (a crashed runtime, os._exit)

    The worker's log tail is ALWAYS attached when available, so the user
    sees *why* rank N vanished instead of a bare "worker died".
    """

    def __init__(self, rank: int, traceback_str: str, log_tail: str = "",
                 *, exit_code: Optional[int] = None,
                 signal_name: Optional[str] = None,
                 cause: str = "exception"):
        self.rank = rank
        self.traceback_str = traceback_str
        self.log_tail = log_tail
        self.exit_code = exit_code
        self.signal_name = signal_name
        self.cause = cause
        msg = f"worker rank {rank} failed:\n{traceback_str}"
        if log_tail:
            msg += f"\n--- worker log tail ---\n{log_tail}"
        super().__init__(msg)

    @classmethod
    def from_death(cls, rank: int, returncode: Optional[int],
                   log_tail: str, context: str) -> "WorkerError":
        """Classify a vanished process by its returncode: negative means
        killed by a signal (name it), non-negative a plain exit."""
        import signal as _sig

        if returncode is not None and returncode < 0:
            try:
                signame = _sig.Signals(-returncode).name
            except ValueError:
                signame = f"signal {-returncode}"
            return cls(
                rank,
                f"worker process killed by {signame} (rc={returncode}) "
                f"{context}",
                log_tail, exit_code=returncode, signal_name=signame,
                cause="signal",
            )
        return cls(
            rank,
            f"worker process exited rc={returncode} {context}",
            log_tail, exit_code=returncode, cause="exit",
        )


class TpuExecutor:
    """One remote worker process (reference RayExecutor, ray_ddp.py:17-39)."""

    def __init__(self, rank: int, world: int, proc: subprocess.Popen,
                 conn: Connection, info: Dict[str, Any], log_path: str,
                 host: Optional[str] = None):
        self.rank = rank
        self.world = world
        self.proc = proc
        self.conn = conn
        self.info = info
        self.log_path = log_path
        self.host = host  # placement target (None = driver machine)
        self._next_tid = 0
        # Digests this worker has cached, in insertion order — a MIRROR
        # of the worker's FIFO blob cache (the channel is reliable FIFO,
        # so replaying the same insert/evict sequence keeps both sides
        # in sync; see _note_digest / worker.py _BLOB_CACHE_CAP).
        self._sent_digests: Dict[str, None] = {}

    def _note_digest(self, digest: str) -> bool:
        """Record that `digest` is (about to be) cached worker-side;
        returns True when the blob must be sent. Evicts oldest entries
        exactly as the worker will, so 'digest in _sent_digests' stays
        truthful even past the cache cap."""
        from ray_lightning_tpu.runtime.worker import _BLOB_CACHE_CAP

        if digest in self._sent_digests:
            return False
        while len(self._sent_digests) >= _BLOB_CACHE_CAP:
            del self._sent_digests[next(iter(self._sent_digests))]
        self._sent_digests[digest] = None
        return True

    # -- RayExecutor API parity -------------------------------------------
    def set_env_vars(self, env: Dict[str, str]) -> None:
        """reference ray_ddp.py:27-31 (no ack needed: FIFO ordering)."""
        self.conn.send(("env", dict(env)))

    def get_node_ip(self) -> str:
        """reference ray_ddp.py:33-35."""
        return self.info.get("ip", "127.0.0.1")

    def execute_async(self, fn: Callable, *args, **kwargs) -> int:
        """Ship a closure; returns a task id to await via WorkerGroup."""
        tid = self._next_tid
        self._next_tid += 1
        blob = cloudpickle.dumps((fn, args, kwargs))
        self.conn.send(("exec", tid, blob))
        return tid

    def execute_shared(self, digest: str, blob: Optional[bytes],
                       extra_blob: bytes) -> int:
        """Ship-once execution: the fat (fn, shared_args, kwargs) blob is
        keyed by content digest and sent only the first time this worker
        sees it (the reference's `ray.put(model)` + per-rank object-ref
        fan-out, ray_ddp.py:168-171); afterwards only the digest + the
        tiny per-rank extras cross the wire."""
        tid = self._next_tid
        self._next_tid += 1
        self.conn.send(("exec2", tid, digest, blob, extra_blob))
        return tid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def log_tail(self, n: int = 40) -> str:
        try:
            with open(self.log_path, "r", errors="replace") as f:
                return "".join(f.readlines()[-n:])
        except OSError:
            return ""

    def kill(self) -> None:
        if self.alive():
            self.proc.kill()
        try:
            self.conn.close()
        except OSError:
            pass


class WorkerGroup:
    """N worker processes + the result/queue pump.

    Lifecycle mirrors the reference plugin's setup/start_training/
    post_dispatch (ray_ddp.py:113-213):

        group = WorkerGroup(num_workers=4, env={...}, init_hook=fn)
        group.start()                      # spawn + hello + init_hook
        results = group.run(train_fn)      # fan-out, pump, gather
        group.shutdown()                   # graceful, then kill
    """

    def __init__(
        self,
        num_workers: Optional[int] = None,
        env: Optional[Dict[str, str]] = None,
        init_hook: Optional[Callable[[], None]] = None,
        log_dir: Optional[str] = None,
        start_timeout: float = 120.0,
        hosts: Optional[Sequence[str]] = None,
        transport: Optional[Transport] = None,
        advertise_host: Optional[str] = None,
    ):
        """``hosts`` + a remote ``transport`` place one worker per host
        (reference ray_ddp.py:106-119's cluster-wide actor placement; on a
        TPU pod: one entry per host VM). Without them, workers are local
        subprocesses. ``advertise_host`` overrides the driver address
        workers dial back to (defaults to the routable IP when remote)."""
        if num_workers is None:
            num_workers = len(hosts) if hosts else 1
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.num_workers = num_workers
        self.env = dict(env or {})
        self.init_hook = init_hook
        self.log_dir = log_dir or os.path.join(
            os.getcwd(), "rlt_logs", "workers"
        )
        self.start_timeout = start_timeout
        self.hosts = list(hosts) if hosts else None
        self.transport = transport or LocalTransport()
        if self.hosts and not self.transport.is_remote:
            # Without this, hosts=[...] + the default transport would
            # silently run every worker on the driver machine while
            # executor.host reports the requested (never-used) hostnames.
            raise ValueError(
                "hosts= requires a remote transport (e.g. SSHTransport); "
                f"got {type(self.transport).__name__}"
            )
        self.advertise_host = advertise_host
        self.executors: List[TpuExecutor] = []
        self._listener: Optional[Listener] = None
        self._queue_items: List[Any] = []

    @property
    def is_remote(self) -> bool:
        return self.transport.is_remote

    def _worker_host(self, rank: int) -> Optional[str]:
        if not self.hosts:
            return None
        return self.hosts[rank % len(self.hosts)]

    # ------------------------------------------------------------- launch
    def start(self) -> "WorkerGroup":
        os.makedirs(self.log_dir, exist_ok=True)
        host_env = getattr(self.transport, "host_env", None)
        if host_env:
            # a typo'd host_env key silently dropping RLT_NODE_IP would
            # reproduce the exact multi-NIC hang the override exists to
            # fix — surface the mismatch (warning, not error: a shared
            # transport may carry entries for other groups' hosts)
            unmatched = set(host_env) - set(self.hosts or [])
            if unmatched:
                log.warning(
                    "transport host_env keys match no launched host "
                    "(typo? keys must equal the hosts= entries): %s",
                    sorted(unmatched),
                )
        authkey = secrets.token_bytes(32)
        # Remote workers must reach the driver: bind the cluster-facing
        # interface and advertise its address (the reference's Listener
        # equivalent was Ray's GCS, reachable cluster-wide by
        # construction). Binding the SPECIFIC advertise interface, not
        # 0.0.0.0, keeps the control channel — authenticated pickles,
        # trusted-network transport (see runtime/transport.py SECURITY
        # note) — off interfaces no worker dials in on.
        connect_host = self.advertise_host or (
            routable_ip() if self.is_remote else "127.0.0.1"
        )
        if (self.is_remote and connect_host == "127.0.0.1"
                and self.advertise_host is None
                and not getattr(self.transport, "allows_loopback", False)):
            # An EXPLICIT advertise_host of 127.0.0.1 is honored (an
            # informed choice, e.g. per-host ssh -L port forwarding); only
            # the silent routable_ip() degradation is an error.
            # routable_ip() degraded to loopback (no default route): remote
            # workers told to dial 127.0.0.1 would hang into start_timeout.
            # Diagnose in seconds instead (VERDICT r3 weak #4).
            raise RuntimeError(
                "cannot determine a routable driver address for remote "
                "workers (no default route on this box). Pass "
                "advertise_host= to WorkerGroup / the strategy, or set "
                "RLT_NODE_IP to this machine's cluster-facing IP."
            )
        try:
            self._listener = Listener((connect_host, 0), authkey=authkey)
        except OSError:
            # advertise_host may be a NAT/LB address that is not a local
            # interface (valid: workers dial it, the OS can't bind it).
            # Fall back to all-interfaces with an explicit note.
            log.warning(
                "advertise address %s is not a local interface; binding "
                "0.0.0.0 (ensure the network path to workers is trusted)",
                connect_host,
            )
            self._listener = Listener(("0.0.0.0", 0), authkey=authkey)
        port = self._listener.address[1]
        procs: Dict[int, subprocess.Popen] = {}
        logs: Dict[int, str] = {}
        try:
            for rank in range(self.num_workers):
                log_path = os.path.join(self.log_dir, f"worker-{rank}.log")
                logs[rank] = log_path
                procs[rank] = self.transport.spawn(
                    host=self._worker_host(rank),
                    connect=(connect_host, port, rank, self.num_workers),
                    env=self.env,
                    authkey_hex=authkey.hex(),
                    log_path=log_path,
                )
        except Exception:
            # A failed spawn (missing ssh binary, dead host) must not leak
            # the workers already started on other hosts or the listener.
            self._abort_start(procs, logs)
            raise
        # Accept hellos; connections arrive in arbitrary order — the hello
        # message carries the rank (cf. reference get_local_ranks building
        # the rank map driver-side, ray_ddp.py:130-141).
        by_rank: Dict[int, TpuExecutor] = {}
        deadline = time.monotonic() + self.start_timeout
        acceptor = _HelloAcceptor(self._listener, authkey)
        try:
            for _ in range(self.num_workers):
                conn = None
                while conn is None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        self._abort_start(procs, logs)
                        raise TimeoutError(
                            "workers did not all connect within "
                            f"{self.start_timeout}s"
                        )
                    # short slices so a worker that died before its hello
                    # (bad ssh host, failed auth, bootstrap crash) fails
                    # the start in ~1s with its log tail, not at the full
                    # start_timeout
                    conn = acceptor.get(min(remaining, 1.0))
                    if conn is None:
                        for rank, p in procs.items():
                            if rank not in by_rank and p.poll() is not None:
                                rc = p.returncode
                                tail = ""
                                try:
                                    with open(logs[rank],
                                              errors="replace") as f:
                                        tail = "".join(f.readlines()[-20:])
                                except OSError:
                                    pass
                                self._abort_start(procs, logs)
                                raise WorkerError.from_death(
                                    rank, rc, tail, "before connecting"
                                )
                # Bound the hello read too: a connection that never
                # speaks must not wedge start().
                if not conn.poll(max(0.0, deadline - time.monotonic())):
                    self._abort_start(procs, logs)
                    raise TimeoutError(
                        "worker connected but sent no hello within "
                        f"{self.start_timeout}s"
                    )
                try:
                    msg = conn.recv()
                except EOFError:
                    # authenticated then died mid-hello: abort like every
                    # other startup failure — leaking the other spawned
                    # workers would hold their hosts' chips indefinitely
                    self._abort_start(procs, logs)
                    raise WorkerError(
                        -1, "a worker died between authenticating and "
                        "sending its hello",
                    ) from None
                if not (isinstance(msg, tuple) and len(msg) == 3
                        and msg[0] == "hello"):
                    self._abort_start(procs, logs)
                    raise WorkerError(
                        -1, f"unexpected first message from a worker "
                        f"(want hello): {msg!r:.200}",
                    )
                _, rank, info = msg
                if not isinstance(rank, int) or rank not in procs:
                    # an out-of-range rank would KeyError into procs[rank]
                    # below WITHOUT aborting — leaking every spawned
                    # worker (and their hosts' chips); fail it like any
                    # other startup violation
                    self._abort_start(procs, logs)
                    raise WorkerError(
                        rank if isinstance(rank, int) else -1,
                        f"hello with invalid rank {rank!r} (expected "
                        f"0..{self.num_workers - 1})",
                    )
                if rank in by_rank:
                    # a duplicate would silently consume a hello slot and
                    # only surface as the full start_timeout
                    self._abort_start(procs, logs)
                    raise WorkerError(
                        rank, f"duplicate hello for rank {rank}"
                    )
                by_rank[rank] = TpuExecutor(
                    rank, self.num_workers, procs[rank], conn, info,
                    logs[rank], host=self._worker_host(rank),
                )
        finally:
            acceptor.close()
        self.executors = [by_rank[r] for r in range(self.num_workers)]
        if self.init_hook is not None:
            # reference ray_ddp.py:118-119: run init_hook on every worker
            # and wait for completion before training starts.
            self.run(self.init_hook)
        return self

    def _abort_start(self, procs, logs) -> None:
        tails = []
        for rank, p in procs.items():
            if p.poll() is not None:
                try:
                    with open(logs[rank], errors="replace") as f:
                        tails.append(
                            f"rank {rank} exited rc={p.returncode}:\n"
                            + "".join(f.readlines()[-20:])
                        )
                except OSError:
                    pass
            p.kill()
        if self._listener is not None:
            self._listener.close()
            self._listener = None
        if tails:
            log.error("worker startup failure:\n%s", "\n".join(tails))

    # --------------------------------------------------------------- exec
    def set_env_vars(self, env: Dict[str, str]) -> None:
        for ex in self.executors:
            ex.set_env_vars(env)

    def run(
        self,
        fn: Callable,
        per_rank_args: Optional[Sequence[Sequence[Any]]] = None,
        on_queue_item: Optional[Callable[[int, Any], None]] = None,
        timeout: Optional[float] = None,
        shared_args: Sequence[Any] = (),
        kwargs: Optional[Dict[str, Any]] = None,
        watchdog: Optional[Callable[[], None]] = None,
    ) -> List[Any]:
        """Fan ``fn`` out to every rank and pump until all return. Each
        rank executes ``fn(*shared_args, *per_rank_args[rank], **kwargs)``.

        Ship-once (the reference's ``ray.put(model)`` once + object-ref
        fan-out, ray_ddp.py:168-171): ``(fn, shared_args, kwargs)`` — the
        fat part, carrying user closures like module/data factories — is
        cloudpickled exactly ONCE per call regardless of worker count,
        fanned out by content digest, and cached worker-side, so a repeat
        run with the same payload ships only digests. Only the per-rank
        extras (rank ids, coordinator info) are serialized per worker.

        The pump is the reference's ``process_results`` (util.py:96-109)
        rebuilt on a real select: side-channel items are handled as they
        arrive (callables executed driver-side — the tune.report trampoline,
        util.py:88-93), the first worker error raises WorkerError
        (fail-fast, SURVEY §5.3), and remaining results are gathered in
        rank order.
        """
        assert self.executors, "call start() first"
        blob = cloudpickle.dumps((fn, tuple(shared_args), dict(kwargs or {})))
        digest = hashlib.sha256(blob).hexdigest()
        tids = []
        extra_blobs: Dict[int, bytes] = {}
        for rank, ex in enumerate(self.executors):
            extra = per_rank_args[rank] if per_rank_args is not None else ()
            extra_blobs[rank] = cloudpickle.dumps(tuple(extra))
            payload = blob if ex._note_digest(digest) else None
            tids.append(ex.execute_shared(digest, payload, extra_blobs[rank]))
        # The digest mirror is an optimization, not a correctness
        # mechanism: a worker whose cache disagrees (eviction, an earlier
        # parse failure) answers "need_blob" and the pump resends —
        # desyncs self-heal.
        resend = {"digest": digest, "blob": blob, "extras": extra_blobs}
        return self.wait(tids, on_queue_item=on_queue_item, timeout=timeout,
                         resend=resend, watchdog=watchdog)

    def wait(
        self,
        tids: Sequence[int],
        on_queue_item: Optional[Callable[[int, Any], None]] = None,
        timeout: Optional[float] = None,
        resend: Optional[Dict[str, Any]] = None,
        watchdog: Optional[Callable[[], None]] = None,
    ) -> List[Any]:
        """``watchdog`` runs once per pump slice (~1 Hz) in the driver:
        the resilience layer's stall monitor raises StallError from it to
        fail a hung-but-alive worker group (health.HealthMonitor.check).
        """
        results: Dict[int, Any] = {}
        done: Dict[int, bool] = {r: False for r in range(self.num_workers)}
        deadline = (
            (time.monotonic() + timeout) if timeout is not None else None
        )
        conns = {ex.conn: ex for ex in self.executors}
        while not all(done.values()):
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"workers still pending: "
                                   f"{[r for r, d in done.items() if not d]}")
            if watchdog is not None:
                watchdog()
            ready = conn_wait(list(conns), timeout=1.0)
            if not ready:
                self._check_liveness(done)
                continue
            for conn in ready:
                ex = conns[conn]
                try:
                    msg = conn.recv()
                except EOFError:
                    raise self._eof_error(ex) from None
                self._dispatch(msg, ex, tids, results, done, on_queue_item,
                               resend)
        self.drain_queue(on_queue_item)
        return [results[r] for r in range(self.num_workers)]

    def _eof_error(self, ex: TpuExecutor) -> WorkerError:
        """EOF on the channel means the process died (or is dying):
        harvest its returncode so the death is CLASSIFIED — a SIGKILL'd
        host reads differently from an os._exit in the resilience policy
        and in the user's eyes."""
        try:
            rc = ex.proc.wait(timeout=2.0)
        except subprocess.TimeoutExpired:
            rc = ex.proc.poll()  # channel closed but process lingering
        if rc is None:
            return WorkerError(
                ex.rank,
                "worker closed its channel but the process is still "
                "running (EOF on channel)",
                ex.log_tail(), cause="exit",
            )
        return WorkerError.from_death(
            ex.rank, rc, ex.log_tail(), "(EOF on channel)"
        )

    def run_single(
        self, rank: int, fn: Callable, *args,
        timeout: Optional[float] = None, **kwargs,
    ) -> Any:
        """Execute ``fn`` on ONE rank and return its result (the analog of
        the reference's targeted ``worker.execute.remote`` calls — e.g. the
        MASTER_PORT probe on worker 0, ray_ddp.py:152-156)."""
        assert self.executors, "call start() first"
        ex = self.executors[rank]
        tid = ex.execute_async(fn, *args, **kwargs)
        deadline = (
            (time.monotonic() + timeout) if timeout is not None else None
        )
        while True:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"rank {rank} still pending")
            if not ex.conn.poll(1.0):
                if not ex.alive():
                    raise WorkerError.from_death(
                        ex.rank, ex.proc.returncode, ex.log_tail(),
                        "without returning a result",
                    )
                continue
            try:
                msg = ex.conn.recv()
            except EOFError:
                raise self._eof_error(ex) from None
            cmd = msg[0]
            if cmd == "result" and msg[1] == tid:
                return cloudpickle.loads(msg[2])
            elif cmd == "error":
                if msg[1] == tid:
                    raise WorkerError(ex.rank, msg[2], ex.log_tail())
                log.warning("dropping stale error from rank %d", ex.rank)
            elif cmd == "queue":
                qrank, item = cloudpickle.loads(msg[1])
                self._handle_queue_item(qrank, item, None)

    def _dispatch(self, msg, ex, tids, results, done, on_queue_item,
                  resend=None) -> None:
        cmd = msg[0]
        if cmd == "need_blob":
            # the worker's cache disagrees with the driver's mirror
            # (eviction past the cap, or a blob whose parse failed
            # earlier): resend the payload for THIS task and move on
            tid, digest = msg[1], msg[2]
            if tids[ex.rank] != tid:
                # stale request from an earlier, already-raised run (cf.
                # the stale-error drop below): that task's pump is gone,
                # so just ignore it — the worker moves on with the next
                # exec it receives
                log.warning(
                    "dropping stale need_blob from rank %d (task %s)",
                    ex.rank, tid,
                )
                return
            if resend is not None and resend["digest"] == digest:
                ex.conn.send(("exec2", tid, digest, resend["blob"],
                              resend["extras"][ex.rank]))
                return
            # current task but unanswerable: without the payload the task
            # can never finish
            raise WorkerError(
                ex.rank,
                f"worker requested blob {digest[:12]} for task {tid} but "
                "the driver no longer holds it",
                ex.log_tail(),
            )
        if cmd == "result":
            tid, blob = msg[1], msg[2]
            if tid == tids[ex.rank]:
                results[ex.rank] = cloudpickle.loads(blob)
                done[ex.rank] = True
        elif cmd == "error":
            # Stale errors from an earlier, already-raised run stay buffered
            # on the other ranks' connections; only raise for THIS task.
            if msg[1] == tids[ex.rank]:
                raise WorkerError(ex.rank, msg[2], ex.log_tail())
            log.warning(
                "dropping stale error from rank %d (task %s): %s",
                ex.rank, msg[1], msg[2].splitlines()[-1] if msg[2] else "",
            )
        elif cmd == "queue":
            rank, item = cloudpickle.loads(msg[1])
            self._handle_queue_item(rank, item, on_queue_item)
        elif cmd == "bye":
            done[ex.rank] = True

    def _handle_queue_item(self, rank, item, on_queue_item) -> None:
        """The trampoline (reference util.py:88-93): callables run here, in
        the driver process — this is how tune.report-style closures created
        on worker rank 0 execute inside the driver's sweep session."""
        if on_queue_item is not None:
            on_queue_item(rank, item)
        elif callable(item):
            item()
        else:
            self._queue_items.append((rank, item))

    def drain_queue(self, on_queue_item=None) -> None:
        """Post-completion drain (reference util.py:106-109)."""
        for conn, ex in {ex.conn: ex for ex in self.executors}.items():
            while conn.poll(0):
                try:
                    msg = conn.recv()
                except EOFError:
                    break
                if msg[0] == "queue":
                    rank, item = cloudpickle.loads(msg[1])
                    self._handle_queue_item(rank, item, on_queue_item)

    def queue_items(self) -> List[Any]:
        items, self._queue_items = self._queue_items, []
        return items

    def _check_liveness(self, done) -> None:
        for ex in self.executors:
            if not done[ex.rank] and not ex.alive():
                raise WorkerError.from_death(
                    ex.rank, ex.proc.returncode, ex.log_tail(),
                    "without returning a result",
                )

    # ------------------------------------------------------------ teardown
    def shutdown(self, timeout: float = 10.0) -> None:
        """Graceful shutdown, then kill — reference post_dispatch
        (ray_ddp.py:201-213) with `ray.kill` replaced by SIGKILL."""
        for ex in self.executors:
            if ex.alive():
                try:
                    ex.conn.send(("shutdown",))
                except (BrokenPipeError, OSError):
                    pass
        deadline = time.monotonic() + timeout
        for ex in self.executors:
            remaining = max(0.0, deadline - time.monotonic())
            try:
                ex.proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                ex.kill()
            try:
                ex.conn.close()
            except OSError:
                pass
        if self._listener is not None:
            self._listener.close()
            self._listener = None
        self.executors = []

    def __enter__(self) -> "WorkerGroup":
        return self.start() if not self.executors else self

    def __exit__(self, *exc) -> None:
        self.shutdown()
