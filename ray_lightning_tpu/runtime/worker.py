"""Worker process entrypoint — the remote half of the runtime substrate.

This file is executed BY PATH (``python /.../worker.py host port rank world``),
never via ``-m``, so that nothing imports ``ray_lightning_tpu`` (and hence
``jax``) before the shipped closure has a chance to set platform/device-count
config. It is intentionally stdlib + cloudpickle only.

Reference analog: the ``RayExecutor`` actor body
(reference ray_lightning/ray_ddp.py:17-39) — a generic remote-execution
process that can run arbitrary functions (``execute``, :37), accept env-var
injection (``set_env_vars``, :27) and report its node IP (``get_node_ip``,
:33). Ray actors are replaced by plain subprocesses + a
``multiprocessing.connection`` duplex channel back to the driver; the Ray
object store is replaced by cloudpickle blobs over that channel.

Wire protocol (all messages are tuples, first element is the command):
  driver -> worker:
    ("env", {k: v})            merge into os.environ (no ack; FIFO ordering
                               guarantees later execs see it)
    ("exec", tid, blob)        blob = cloudpickle((fn, args, kwargs));
                               reply is ("result", tid, blob) or
                               ("error", tid, traceback_str)
    ("shutdown",)              reply ("bye", rank), then exit 0
  worker -> driver:
    ("hello", rank, info)      sent once on connect
    ("result", tid, blob)
    ("error", tid, tb_str)
    ("queue", blob)            side-channel item from session.put_queue;
                               blob = cloudpickle((rank, item))
"""
import os
import socket
import sys
import threading
import traceback
from multiprocessing.connection import Client

import cloudpickle


def _node_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


class _WorkerChannel:
    """Thread-safe sender shared by the exec loop and the session side
    channel (reference session.py:17-24 tags items with rank; we do the
    same in the blob)."""

    def __init__(self, conn, rank: int, world: int):
        self.conn = conn
        self.rank = rank
        self.world = world
        self._lock = threading.Lock()

    def send(self, msg) -> None:
        with self._lock:
            self.conn.send(msg)

    def put_queue(self, item) -> None:
        self.send(("queue", cloudpickle.dumps((self.rank, item))))


def _bind_session(channel: _WorkerChannel) -> None:
    """Make ray_lightning_tpu.runtime.session work inside this worker.

    Deferred + best-effort: the import pulls in the package (and jax), so it
    only happens right before user code runs — by which point the shipped
    closure has already had its chance to set jax config at the top of its
    own body (config updates like jax_platforms work post-import as long as
    no backend has initialized).
    """
    from ray_lightning_tpu.runtime import session

    session.init_session(
        rank=channel.rank, world_size=channel.world, queue=channel
    )


def main(argv) -> int:
    host, port, rank, world = argv[1], int(argv[2]), int(argv[3]), int(argv[4])
    authkey = bytes.fromhex(os.environ.pop("RLT_WORKER_AUTHKEY"))
    conn = Client((host, port), authkey=authkey)
    channel = _WorkerChannel(conn, rank, world)
    channel.send(("hello", rank, {"pid": os.getpid(), "ip": _node_ip()}))
    session_bound = False
    while True:
        msg = conn.recv()
        cmd = msg[0]
        if cmd == "env":
            os.environ.update(msg[1])
        elif cmd == "exec":
            tid, blob = msg[1], msg[2]
            try:
                fn, args, kwargs = cloudpickle.loads(blob)
                if not session_bound:
                    _bind_session(channel)
                    session_bound = True
                result = fn(*args, **kwargs)
                channel.send(("result", tid, cloudpickle.dumps(result)))
            except BaseException:
                channel.send(("error", tid, traceback.format_exc()))
        elif cmd == "shutdown":
            channel.send(("bye", rank))
            return 0
        else:
            channel.send(("error", -1, f"unknown command {cmd!r}"))


if __name__ == "__main__":
    sys.exit(main(sys.argv))
