"""Worker process entrypoint — the remote half of the runtime substrate.

This file is executed BY PATH (``python /.../worker.py host port rank world``),
never via ``-m``, so that nothing imports ``ray_lightning_tpu`` (and hence
``jax``) before the shipped closure has a chance to set platform/device-count
config. It is intentionally stdlib + cloudpickle only.

Reference analog: the ``RayExecutor`` actor body
(reference ray_lightning/ray_ddp.py:17-39) — a generic remote-execution
process that can run arbitrary functions (``execute``, :37), accept env-var
injection (``set_env_vars``, :27) and report its node IP (``get_node_ip``,
:33). Ray actors are replaced by plain subprocesses + a
``multiprocessing.connection`` duplex channel back to the driver; the Ray
object store is replaced by cloudpickle blobs over that channel.

Wire protocol (all messages are tuples, first element is the command):
  driver -> worker:
    ("env", {k: v})            merge into os.environ (no ack; FIFO ordering
                               guarantees later execs see it)
    ("exec", tid, blob)        blob = cloudpickle((fn, args, kwargs));
                               reply is ("result", tid, blob) or
                               ("error", tid, traceback_str)
    ("exec2", tid, digest, blob_or_None, extra_blob)
                               ship-once execution (the reference's
                               ray.put fan-out, ray_ddp.py:168-171):
                               blob = cloudpickle((fn, shared_args,
                               kwargs)) on first sight of `digest`, None
                               when this worker already cached it;
                               extra_blob = cloudpickle(per_rank_args).
                               Runs fn(*shared_args, *per_rank_args,
                               **kwargs). If blob is None but the digest
                               is NOT cached (eviction, earlier parse
                               failure), the worker replies
                               ("need_blob", tid, digest) and the driver
                               resends with the payload — cache desyncs
                               self-heal. NOTE the cached (fn, args)
                               objects are REUSED across calls — like a
                               plasma-store value, they must not rely on
                               call-local mutation.
    ("shutdown",)              reply ("bye", rank), then exit 0
  worker -> driver:
    ("hello", rank, info)      sent once on connect
    ("result", tid, blob)
    ("error", tid, tb_str)
    ("queue", blob)            side-channel item from session.put_queue;
                               blob = cloudpickle((rank, item))
"""
import os
import socket
import sys
import threading
import time
import traceback
from multiprocessing.connection import Client

import cloudpickle

from ray_lightning_tpu.analysis.lockwatch import san_lock

#: stamped at import — the earliest observable moment of this worker's
#: life; telemetry's goodput "launch" bucket (spawn -> fit start)
#: measures against it via the session registry
_PROC_START = time.time()


def _node_ip() -> str:
    """This worker host's address as the other hosts see it. RLT_NODE_IP
    overrides (the multi-NIC escape hatch — deliverable per host through
    the transport env); otherwise the default-route interface via the
    UDP-connect trick (no packet is sent)."""
    override = os.environ.get("RLT_NODE_IP")
    if override:
        return override
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


class _WorkerChannel:
    """Thread-safe sender shared by the exec loop and the session side
    channel (reference session.py:17-24 tags items with rank; we do the
    same in the blob)."""

    def __init__(self, conn, rank: int, world: int):
        self.conn = conn
        self.rank = rank
        self.world = world
        self._lock = san_lock("runtime.worker.channel")

    def send(self, msg) -> None:
        with self._lock:
            self.conn.send(msg)

    def put_queue(self, item) -> None:
        self.send(("queue", cloudpickle.dumps((self.rank, item))))


def _bind_session(channel: _WorkerChannel) -> None:
    """Make ray_lightning_tpu.runtime.session work inside this worker.

    Deferred + best-effort: the import pulls in the package (and jax), so it
    only happens right before user code runs — by which point the shipped
    closure has already had its chance to set jax config at the top of its
    own body (config updates like jax_platforms work post-import as long as
    no backend has initialized).
    """
    from ray_lightning_tpu.runtime import session

    session.init_session(
        rank=channel.rank, world_size=channel.world, queue=channel,
        started_at=_PROC_START,
    )


#: parsed (fn, shared_args, kwargs) tuples by content digest; tiny FIFO —
#: a worker group rarely runs more than init_hook + the job, and a fat
#: entry (model factories, tokenizer tables) must not accumulate.
_BLOB_CACHE_CAP = 4


def main(argv) -> int:
    host, port, rank, world = argv[1], int(argv[2]), int(argv[3]), int(argv[4])
    authkey = bytes.fromhex(os.environ.pop("RLT_WORKER_AUTHKEY"))
    conn = Client((host, port), authkey=authkey)
    channel = _WorkerChannel(conn, rank, world)
    channel.send(("hello", rank, {"pid": os.getpid(), "ip": _node_ip()}))
    session_bound = False
    blob_cache: dict = {}  # digest -> (fn, shared_args, kwargs)
    while True:
        msg = conn.recv()
        cmd = msg[0]
        if cmd == "env":
            os.environ.update(msg[1])
        elif cmd == "exec":
            tid, blob = msg[1], msg[2]
            try:
                fn, args, kwargs = cloudpickle.loads(blob)
                if not session_bound:
                    _bind_session(channel)
                    session_bound = True
                result = fn(*args, **kwargs)
                channel.send(("result", tid, cloudpickle.dumps(result)))
            except BaseException:
                channel.send(("error", tid, traceback.format_exc()))
        elif cmd == "exec2":
            tid, digest, blob, extra_blob = msg[1], msg[2], msg[3], msg[4]
            if blob is None and digest not in blob_cache:
                # The driver's cache mirror was optimistic (an eviction
                # it replayed differently, or an earlier blob whose parse
                # failed): ask for a resend instead of failing the task —
                # cache desyncs self-heal.
                channel.send(("need_blob", tid, digest))
                continue
            try:
                if blob is not None and digest not in blob_cache:
                    parsed = cloudpickle.loads(blob)  # before any insert
                    while len(blob_cache) >= _BLOB_CACHE_CAP:
                        blob_cache.pop(next(iter(blob_cache)))
                    blob_cache[digest] = parsed
                fn, args, kwargs = blob_cache[digest]
                extra = cloudpickle.loads(extra_blob)
                if not session_bound:
                    _bind_session(channel)
                    session_bound = True
                result = fn(*args, *extra, **kwargs)
                channel.send(("result", tid, cloudpickle.dumps(result)))
            except BaseException:
                channel.send(("error", tid, traceback.format_exc()))
        elif cmd == "shutdown":
            channel.send(("bye", rank))
            return 0
        else:
            channel.send(("error", -1, f"unknown command {cmd!r}"))


if __name__ == "__main__":
    sys.exit(main(sys.argv))
