"""Worker-process transports: HOW the driver starts a worker on a host.

The reference placed workers on arbitrary cluster nodes through Ray's
actor scheduler (``RayExecutor.options(...).remote()``, reference
ray_ddp.py:106-119) and bootstrapped rendezvous across them with env-var
injection (:158-164). The rebuild makes that placement step a pluggable
*transport*: the WorkerGroup decides WHAT to run (the worker loop, its
rank, the driver's listener address) and the transport decides how a
process running it appears on ``host``.

  * LocalTransport — subprocess on the driver machine (dev box, CI, and
    single-host TPU VMs).
  * SSHTransport   — ``ssh host python -u -`` with the worker program
    piped over stdin: nothing needs to be pre-staged on the remote host
    for the worker *loop* itself (user closures still import
    ``ray_lightning_tpu``, so the package must be installed remotely),
    and the connection authkey travels over the encrypted stdin, never
    on a command line. On GCP TPU pods, point ``ssh`` at
    ``gcloud compute tpus tpu-vm ssh``-compatible wrappers or plain ssh
    to the per-host VM IPs.
  * LoopbackTransport — the SSH bootstrap path with the ssh prefix
    removed: runs locally but crosses the same "remote" semantics
    (scrubbed environment, stdin bootstrap, routable listener). This is
    the test seam for the cross-host code path.

Every transport returns a ``subprocess.Popen``-compatible handle
(poll/kill/wait/returncode); for SSH the handle is the local ssh client
process — killing it drops the stdin/stdout pipes, which the worker
observes as EOF and the driver's pump reports fail-fast.

SECURITY: the driver⇄worker control channel carries pickled closures over
TCP, authenticated by a per-launch random 256-bit authkey (the
``multiprocessing.connection`` HMAC challenge) but NOT encrypted — the
challenge authenticates connection setup only. The listener binds the
specific cluster-facing interface (never 0.0.0.0 unless the advertise
address is non-local, see WorkerGroup.start), and the SSH bootstrap keeps
the authkey off argv/process listings; but an attacker who can inject
into the established TCP stream on the cluster network can deliver a
pickle payload. Run on a trusted/isolated cluster network (the same
assumption Ray's GCS/object-store channels make), or tunnel the control
channel itself (e.g. ssh -L port forwarding per host) on anything less.
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
from typing import Dict, Optional, Sequence

_WORKER_PATH = os.path.join(os.path.dirname(__file__), "worker.py")
_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


class Transport:
    """Spawn one worker process on ``host``.

    ``is_remote`` drives address resolution in WorkerGroup/launch: remote
    transports get a listener bound on all interfaces and a routable
    advertise address; local ones stay on loopback.
    """

    is_remote = False

    def spawn(
        self,
        *,
        host: Optional[str],
        connect: tuple,  # (driver_host, driver_port, rank, world)
        env: Dict[str, str],
        authkey_hex: str,
        log_path: str,
    ) -> subprocess.Popen:
        raise NotImplementedError


class LocalTransport(Transport):
    """Workers as plain subprocesses of the driver (the round-1 behavior)."""

    def spawn(self, *, host, connect, env, authkey_hex, log_path):
        driver_host, port, rank, world = connect
        wenv = dict(os.environ)
        wenv.update(env)
        wenv["RLT_WORKER_AUTHKEY"] = authkey_hex
        # Make the package importable in the worker no matter where the
        # driver was launched from (env bootstrap, C7 of SURVEY §7.1).
        wenv["PYTHONPATH"] = (
            _REPO_ROOT + os.pathsep + wenv.get("PYTHONPATH", "")
        )
        logf = open(log_path, "w")
        try:
            return subprocess.Popen(
                [sys.executable, "-u", _WORKER_PATH,
                 driver_host, str(port), str(rank), str(world)],
                env=wenv, stdout=logf, stderr=subprocess.STDOUT,
            )
        finally:
            logf.close()


def _bootstrap_source(
    connect: tuple,
    env: Dict[str, str],
    authkey_hex: str,
    pythonpath: Sequence[str],
) -> str:
    """Self-contained worker program for ``python -u -`` on a remote host.

    Preamble injects env + sys.argv, then the verbatim worker.py source
    runs as __main__ (stdin programs are __main__, so its entrypoint
    guard fires). Secrets ride the (encrypted) stdin, not argv or the
    remote process environment listing... env vars ARE process env, but
    they were never on a command line where `ps` could see them.
    """
    driver_host, port, rank, world = connect
    wenv = dict(env)
    wenv["RLT_WORKER_AUTHKEY"] = authkey_hex
    with open(_WORKER_PATH, "r") as f:
        worker_src = f.read()
    preamble = (
        "import os, sys\n"
        f"os.environ.update({wenv!r})\n"
        f"_pp = {list(pythonpath)!r}\n"
        "if _pp:\n"
        "    os.environ['PYTHONPATH'] = os.pathsep.join(\n"
        "        _pp + ([os.environ['PYTHONPATH']]\n"
        "               if os.environ.get('PYTHONPATH') else []))\n"
        "    sys.path[:0] = _pp\n"
        f"sys.argv = ['worker.py', {driver_host!r}, {str(port)!r}, "
        f"{str(rank)!r}, {str(world)!r}]\n"
    )
    return preamble + worker_src


class SSHTransport(Transport):
    """Start workers on remote hosts over ssh.

    Parameters
    ----------
    ssh: argv prefix invoked as ``<ssh...> <host> -- <python> -u -``.
        Default plain ssh with BatchMode (no password prompts).
    remote_python: interpreter on the remote host.
    pythonpath: remote directories prepended to sys.path/PYTHONPATH in
        the worker (where ``ray_lightning_tpu`` + deps live, if not
        installed into the interpreter).
    env: transport-level env applied to every worker, merged under the
        group's per-launch env.
    host_env: per-host env overrides keyed by the host string passed to
        WorkerGroup(hosts=...) — applied on top of ``env`` for workers
        on that host. The multi-NIC escape hatch: on multi-homed hosts,
        ``host_env={ssh_addr: {"RLT_NODE_IP": fabric_addr}}`` pins the
        address the worker advertises (and, for worker 0, the jax
        coordinator binds) to the data network, independent of the
        address ssh dials.

    v5p-pod recipe (one worker per host VM)::

        transport = SSHTransport(remote_python="python3")
        group = WorkerGroup(hosts=[ip0, ip1, ...], transport=transport)
    """

    is_remote = True

    def __init__(
        self,
        ssh: Sequence[str] = ("ssh", "-o", "BatchMode=yes"),
        remote_python: str = "python3",
        pythonpath: Sequence[str] = (),
        env: Optional[Dict[str, str]] = None,
        host_env: Optional[Dict[str, Dict[str, str]]] = None,
    ):
        self.ssh = list(ssh)
        self.remote_python = remote_python
        self.pythonpath = list(pythonpath)
        self.env = dict(env or {})
        self.host_env = {k: dict(v) for k, v in (host_env or {}).items()}

    def _command(self, host: Optional[str]) -> list:
        if not host:
            raise ValueError("SSHTransport needs a host per worker "
                             "(pass hosts=[...] to WorkerGroup)")
        return self.ssh + [host, "--", self.remote_python, "-u", "-"]

    def _popen_env(self) -> Optional[dict]:
        return None  # the ssh CLIENT runs with the driver's env

    def spawn(self, *, host, connect, env, authkey_hex, log_path):
        source = _bootstrap_source(
            connect,
            {**self.env, **env, **self.host_env.get(host or "", {})},
            authkey_hex, self.pythonpath,
        )
        logf = open(log_path, "w")
        try:
            proc = subprocess.Popen(
                self._command(host),
                stdin=subprocess.PIPE,
                stdout=logf,
                stderr=subprocess.STDOUT,
                env=self._popen_env(),
            )
        finally:
            logf.close()
        # Feed the bootstrap on a helper thread: a wedged ssh that never
        # drains stdin must surface through the group's start_timeout as a
        # no-hello spawn failure, not block the driver inside write()
        # before the timeout machinery even engages (the source can
        # exceed the OS pipe buffer).
        def _feed(stdin, data):
            try:
                stdin.write(data)
                stdin.close()
            except (BrokenPipeError, OSError):
                pass  # dead ssh: poll()/log tail report it

        threading.Thread(
            target=_feed, args=(proc.stdin, source.encode()), daemon=True
        ).start()
        return proc


class LoopbackTransport(SSHTransport):
    """The SSH bootstrap path without ssh: ``python -u -`` locally, with a
    scrubbed environment (like a fresh remote login shell — the driver's
    env does NOT leak in; only the explicit env + bootstrap preamble do).

    Used by tests to drive the cross-host code path — stdin bootstrap,
    explicit env propagation, routable listener/coordinator addresses —
    on one machine, and handy as a dev-box smoke of an SSH deployment.
    """

    #: remote semantics, but the processes really are local — loopback is
    #: a legitimate driver address here (WorkerGroup's no-default-route
    #: fail-fast is for transports whose workers live on OTHER machines)
    allows_loopback = True

    #: env vars a login shell would have anyway; everything else is dropped
    _KEEP = ("PATH", "HOME", "TMPDIR", "LANG", "LC_ALL", "USER", "SHELL")

    def __init__(self, pythonpath: Sequence[str] = (_REPO_ROOT,), **kw):
        super().__init__(pythonpath=pythonpath, **kw)
        self.spawned: list = []  # (host, rank) — test introspection

    def _command(self, host):
        return [sys.executable, "-u", "-"]

    def _popen_env(self):
        return {k: os.environ[k] for k in self._KEEP if k in os.environ}

    def spawn(self, *, host, connect, env, authkey_hex, log_path):
        self.spawned.append((host, connect[2]))
        return super().spawn(
            host=host, connect=connect, env=env,
            authkey_hex=authkey_hex, log_path=log_path,
        )
