"""Worker-local session registry: rank + report side channel.

Rebuild of the reference's per-worker singleton
(reference ray_lightning/session.py:1-63): Tune-style callbacks running
deep inside the fit loop need the worker's rank and a handle to the
driver-bound queue WITHOUT those being plumbed through every call —
a process-global registry, double-init guarded (reference session.py:30-36).

Here the "queue" is the worker's duplex channel back to the driver
(bound by runtime/worker.py before user code runs); items are tagged with
the sending rank (reference session.py:17-24) and, if callable, executed
driver-side by the pump's trampoline (reference util.py:88-93).
"""
from __future__ import annotations

from typing import Any, Optional


class TpuSession:
    def __init__(self, rank: int, world_size: int, queue: Optional[Any],
                 started_at: Optional[float] = None):
        self.rank = rank
        self.world_size = world_size
        self.queue = queue
        #: wall-clock of worker-process start (worker.py stamps its own
        #: import time) — telemetry's goodput launch bucket measures
        #: spawn -> fit start against this
        self.started_at = started_at

    def put_queue(self, item: Any) -> None:
        if self.queue is None:
            raise ValueError(
                "this session has no report queue attached "
                "(reference analog: session.py:21-24)"
            )
        self.queue.put_queue(item)


_session: Optional[TpuSession] = None


def init_session(rank: int, world_size: int = 1, queue: Optional[Any] = None,
                 started_at: Optional[float] = None,
                 _overwrite: bool = True) -> None:
    """Bind the process-global session. Unlike the reference (which raises
    on double init, session.py:30-36) re-binding is allowed so a worker
    process can be reused across execs; pass _overwrite=False for the
    strict behavior."""
    global _session
    if _session is not None and not _overwrite:
        raise ValueError("a session already exists in this process")
    _session = TpuSession(rank, world_size, queue, started_at=started_at)


def get_session() -> Optional[TpuSession]:
    return _session


def reset_session() -> None:
    global _session
    _session = None


def is_session_enabled() -> bool:
    """True iff running inside a runtime worker (reference analog:
    tune.is_session_enabled, tune.py:14-22)."""
    return _session is not None


def get_actor_rank() -> int:
    """Rank of this worker (reference session.py:56-58)."""
    assert _session is not None, "init_session must be called first"
    return _session.rank


def get_world_size() -> int:
    assert _session is not None, "init_session must be called first"
    return _session.world_size


def put_queue(item: Any) -> None:
    """Ship an item to the driver's pump (reference session.py:61-63).
    Callables are executed driver-side — the tune.report trampoline."""
    assert _session is not None, "init_session must be called first"
    _session.put_queue(item)
