"""Distributed jobs: the full state round-trip over the runtime substrate.

Rebuild of the reference's core protocol (reference ray_ddp.py:143-199):
driver ships a job to N workers, workers run it jointly, and rank 0's
results / trained weights / best_model_path come back and are patched
into the DRIVER's objects — after `fit_distributed` returns, the caller's
module object holds trained weights (C5 of SURVEY §7.1; reference
ray_ddp.py:186-193 `load_state_dict` + best_model_path patch-in).

The reference's plugin hosts every Trainer entrypoint, not just fit — its
canonical test matrix is train/load/predict through the plugin (reference
tests/test_ddp.py:79-113). Here the same round-trip protocol carries a
job *kind*: ``fit | validate | test | predict``, with eval metrics and
predictions returning from rank 0.

Differences from the reference, by design (SURVEY §7.4 hard parts #1-#3):
  * the workers are H host-processes jointly executing ONE SPMD program
    (a global mesh), not N independent replicas — so "shipping the model"
    means shipping its FACTORY (static module def + config), not a pickled
    live trainer; array state is created sharded on the mesh.
  * weights return via a host gather (`process_allgather`) only when small
    enough (`return_weights`), else as a sharded checkpoint path — never
    funnel 8B params through a driver pickle (SURVEY §2.4 scaling hazard).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

from ray_lightning_tpu.runtime.launch import launch
from ray_lightning_tpu.runtime.transport import Transport
from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)

_KINDS = ("fit", "validate", "test", "predict")


@dataclasses.dataclass
class FitResult:
    """What comes back from rank 0 (reference tuple at ray_ddp.py:186 —
    made a proper type instead of the reference's order-sensitive tuple,
    whose plugin-dependent ordering was an acknowledged accident,
    SURVEY §2.4)."""

    metrics: Dict[str, Any]
    best_model_path: Optional[str]
    state_dict: Optional[Any]  # host numpy pytree, or None if too large
    checkpoint_path: Optional[str]
    predictions: Optional[List[Any]] = None  # kind="predict" only


def _job_remote(
    kind: str,
    module_factory: Callable[[], Any],
    trainer_factory: Callable[[], Any],
    data_factory: Callable[[], Any],
    return_weights: bool,
    final_ckpt_dir: Optional[str],
    ckpt_path: Optional[str],
):
    """Runs in EVERY worker process after jax.distributed init (the analog
    of train_remote, reference ray_ddp.py:217-246 — generalized to the
    reference protocol's full train/validate/test/predict surface)."""
    import jax
    import numpy as np

    from ray_lightning_tpu.core.data import DataModule, ensure_sharded

    module = module_factory()
    trainer = trainer_factory()
    data = data_factory()
    rank = jax.process_index()
    world = jax.process_count()

    if isinstance(data, DataModule):
        # normalize here (not in trainer.fit) so the per-stage loaders are
        # visible for shard injection below.
        data.setup()
        if kind == "fit":
            data = (data.train_dataloader(), data.val_dataloader())
        else:
            data = {
                "validate": data.val_dataloader,
                "test": data.test_dataloader,
                "predict": data.predict_dataloader,
            }[kind]()

    if kind != "fit":
        # Forced shard semantics for the eval family too (the reference
        # injects its sampler per-stage — val/test/predict loaders alike,
        # ray_ddp.py:293-303 via PTL's per-stage dataloader hooks).
        data = ensure_sharded(data, world, rank, stage=kind)
        # Eval-family jobs: weights come from the factory or a checkpoint
        # (the reference's load-then-predict leg, tests/test_ddp.py:79-113).
        # load_checkpoint gathers to host — the small/medium-model path;
        # resume-at-scale goes through fit's sharded restore instead.
        if ckpt_path is not None:
            from ray_lightning_tpu.checkpoint import load_checkpoint

            ckpt = load_checkpoint(ckpt_path)
            module.setup()
            module.params = ckpt["params"]
            module.on_load_checkpoint(ckpt)
        runner = {
            "validate": trainer.validate,
            "test": trainer.test,
            "predict": trainer.predict,
        }[kind]
        out = runner(module, data)
        if rank != 0:
            return None
        if kind == "predict":
            return FitResult(
                metrics=dict(trainer.callback_metrics),
                best_model_path=None, state_dict=None,
                checkpoint_path=None,
                predictions=jax.tree.map(np.asarray, out),
            )
        return FitResult(
            metrics=dict(out), best_model_path=None,
            state_dict=None, checkpoint_path=None,
        )

    if not isinstance(data, tuple):
        data = (data, None)
    train_data, val_data = data
    # The reference's forcing guarantee (ray_ddp.py:293-303): in a
    # multi-process job, forgetting shard arguments is impossible — the
    # launcher injects them, and unshardable inputs are a hard error, not
    # silently-duplicated per-host batches.
    train_data = ensure_sharded(train_data, world, rank, stage="train")
    val_data = ensure_sharded(val_data, world, rank, stage="val")
    trainer.fit(module, train_data, val_data, ckpt_path=ckpt_path)

    out_ckpt = None
    if final_ckpt_dir is not None:
        # Sharded write: every process writes its addressable shards
        # (orbax handles the coordination); replaces the reference's
        # driver-side single-file checkpoint.
        out_ckpt = trainer.save_checkpoint(
            os.path.join(final_ckpt_dir, "final")
        )
    state_dict = None
    if return_weights:
        from jax.experimental import multihost_utils

        params = trainer.state.params
        if jax.process_count() > 1:
            params = multihost_utils.process_allgather(params, tiled=True)
        if rank == 0:
            state_dict = jax.tree.map(np.asarray, jax.device_get(params))

    best = None
    if trainer.checkpoint_callback is not None:
        best = trainer.checkpoint_callback.best_model_path
    if rank == 0:
        return FitResult(
            metrics=dict(trainer.callback_metrics),
            best_model_path=best,
            state_dict=state_dict,
            checkpoint_path=out_ckpt,
        )
    return None


def run_distributed(
    kind: str,
    module_factory: Callable[[], Any],
    trainer_factory: Callable[[], Any],
    data_factory: Callable[[], Any],
    num_processes: int,
    *,
    module: Optional[Any] = None,
    ckpt_path: Optional[str] = None,
    platform: Optional[str] = None,
    num_cpu_devices_per_process: Optional[int] = None,
    env: Optional[Dict[str, str]] = None,
    init_hook: Optional[Callable[[], None]] = None,
    on_queue_item: Optional[Callable[[int, Any], None]] = None,
    return_weights: bool = True,
    final_ckpt_dir: Optional[str] = None,
    timeout: Optional[float] = None,
    log_dir: Optional[str] = None,
    hosts: Optional[Sequence[str]] = None,
    transport: Optional[Transport] = None,
    watchdog: Optional[Callable[[], None]] = None,
    resilience: Optional[Any] = None,
) -> FitResult:
    """Run one Trainer job (`fit|validate|test|predict`) as a multi-process
    SPMD program; return rank 0's results.

    The three factories are shipped by value (cloudpickle), replacing the
    reference's "model must be pickleable" contract (README.md:119) with
    the JAX-friendly split of static definition vs array state
    (SURVEY §7.4 hard part #3). For fit, ``data_factory`` returns a train
    loader or a (train, val) tuple; for the eval kinds it returns that
    kind's loader. ``ckpt_path`` resumes a fit, or supplies the weights
    for an eval-family job (the reference's train→load→predict matrix).

    ``hosts``/``transport`` place workers on cluster hosts (see
    runtime/transport.py); default is local subprocesses.

    ``resilience=ResilienceConfig(...)`` runs the job under the
    supervisor (resilience/supervisor.py): transient failures — a
    SIGTERM'd host, a dropped coordinator, a hung worker — restart the
    group and resume from the latest valid checkpoint instead of losing
    the run. Returns the final FitResult; use ``supervise()`` directly
    when the restart ledger is needed. ``watchdog`` runs ~1 Hz inside
    the driver's result pump (the stall-monitor hook).
    """
    if kind not in _KINDS:
        raise ValueError(f"kind must be one of {_KINDS}, got {kind!r}")
    if resilience is not None:
        # lazy import: resilience imports this module
        from ray_lightning_tpu.resilience.supervisor import supervise

        supervised = supervise(
            kind, module_factory, trainer_factory, data_factory,
            num_processes,
            resilience=resilience, watchdog=watchdog,
            module=module, ckpt_path=ckpt_path, platform=platform,
            num_cpu_devices_per_process=num_cpu_devices_per_process,
            env=env, init_hook=init_hook, on_queue_item=on_queue_item,
            return_weights=return_weights, final_ckpt_dir=final_ckpt_dir,
            timeout=timeout, log_dir=log_dir, hosts=hosts,
            transport=transport,
        )
        if supervised.restarts or supervised.preemptions:
            log.info("supervised %s finished after %d restart(s) / %d "
                     "preemption resume(s)", kind, supervised.restarts,
                     supervised.preemptions)
        return supervised.result
    results: List[Any] = launch(
        _job_remote,
        num_processes,
        args=(kind, module_factory, trainer_factory, data_factory,
              return_weights, final_ckpt_dir, ckpt_path),
        platform=platform,
        num_cpu_devices_per_process=num_cpu_devices_per_process,
        env=env,
        init_hook=init_hook,
        on_queue_item=on_queue_item,
        timeout=timeout,
        log_dir=log_dir,
        hosts=hosts,
        transport=transport,
        watchdog=watchdog,
    )
    result = results[0]
    assert isinstance(result, FitResult), (
        f"rank 0 returned {type(result)}; expected FitResult"
    )
    if kind == "fit" and module is not None and result.state_dict is not None:
        # reference ray_ddp.py:190: driver model gets the trained weights,
        # ready for local inference.
        if hasattr(module, "setup"):
            module.setup()
        module.params = result.state_dict
    return result


def fit_distributed(
    module_factory: Callable[[], Any],
    trainer_factory: Callable[[], Any],
    data_factory: Callable[[], Any],
    num_processes: int,
    **kw,
) -> FitResult:
    """Distributed ``Trainer.fit`` round-trip (reference ray_ddp.py:143-199).
    See `run_distributed` for the full parameter surface."""
    return run_distributed(
        "fit", module_factory, trainer_factory, data_factory,
        num_processes, **kw,
    )


def validate_distributed(module_factory, trainer_factory, data_factory,
                         num_processes, **kw) -> FitResult:
    """Distributed ``Trainer.validate``; metrics return from rank 0."""
    return run_distributed(
        "validate", module_factory, trainer_factory, data_factory,
        num_processes, **kw,
    )


def test_distributed(module_factory, trainer_factory, data_factory,
                     num_processes, **kw) -> FitResult:
    """Distributed ``Trainer.test``; metrics return from rank 0."""
    return run_distributed(
        "test", module_factory, trainer_factory, data_factory,
        num_processes, **kw,
    )


def predict_distributed(module_factory, trainer_factory, data_factory,
                        num_processes, **kw) -> FitResult:
    """Distributed ``Trainer.predict``; the globally-gathered predictions
    return from rank 0 in ``result.predictions`` (reference predict leg of
    tests/test_ddp.py:79-113)."""
    return run_distributed(
        "predict", module_factory, trainer_factory, data_factory,
        num_processes, **kw,
    )
