"""Single-controller launch of a multi-controller SPMD program.

SURVEY §7.4 hard part #1: Ray is single-driver/many-actors, while JAX on a
pod is one process per host all executing the same program. This module
reconciles the two: the driver (your single script, C1 of SURVEY §7.1)
ships ONE closure to H host-processes; each process initializes
``jax.distributed`` against a coordinator the driver picked (the analog of
the reference's MASTER_ADDR/PORT dance, ray_ddp.py:152-156 — but the
coordination service is JAX's, not a torch TCPStore), joins the global
device mesh, and jointly executes the SPMD program. The driver keeps the
Ray-like futures/queue view via WorkerGroup.

On a real TPU pod the same closure runs with per-host launch handled by
the pod runtime (one of these processes per host; ``coordinator_address``
a pod-internal IP); on a dev box / CI, ``platform="cpu"`` +
``num_cpu_devices_per_process`` gives REAL multi-process collectives over
gloo — the test story of SURVEY §7.1 C8.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence

from ray_lightning_tpu.runtime.group import WorkerGroup, find_free_port
from ray_lightning_tpu.runtime.transport import Transport
from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)


def _probe_coordinator_port():
    """Runs ON worker 0: find a port free on all interfaces of ITS host.

    Stdlib-only by design: cloudpickle pickles module-level functions by
    REFERENCE, so the remote side imports this module to resolve it —
    fine (the package is required on workers anyway, since user closures
    import it too), but the body must not assume anything about the
    worker's jax state. Reference analog: find_free_port executed on
    worker 0 for MASTER_PORT (ray_ddp.py:154-156).
    """
    import socket

    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _await_coordinator(coordinator: str, rank: int) -> None:
    """Bounded preflight from a non-zero rank: the jax coordinator (on
    worker 0) must become dialable within the window, else fail with the
    fix by name — a wrong coordinator address otherwise surfaces as a
    multi-minute opaque barrier hang inside jax.distributed.initialize
    (VERDICT r3 weak #4 / next #7).

    The window defaults to 60s and is raised via RLT_COORD_PREFLIGHT_S
    (a slow-but-healthy rank 0 — cold NFS jax import, fat job blob —
    must not be misdiagnosed as unroutable); <= 0 skips the preflight.
    """
    import os
    import socket
    import time

    try:
        timeout = float(os.environ.get("RLT_COORD_PREFLIGHT_S", "60"))
    except ValueError:
        timeout = 60.0
    if timeout <= 0:
        return
    host, port = coordinator.rsplit(":", 1)
    deadline = time.monotonic() + timeout
    last_err: Exception | None = None
    while time.monotonic() < deadline:
        try:
            with socket.create_connection((host, int(port)), timeout=5):
                return
        except OSError as exc:  # not up yet, or unroutable
            last_err = exc
            time.sleep(0.5)
    raise RuntimeError(
        f"rank {rank}: jax coordinator {coordinator} was unreachable for "
        f"{timeout:.0f}s ({last_err}). In a multi-host job this address "
        "must be a fabric-routable IP of worker 0 — set RLT_NODE_IP in "
        "worker 0's environment (transport host_env) to pin the right "
        "interface, or pass coordinator_address= to launch(). If worker 0 "
        "is just slow to start (cold imports), raise RLT_COORD_PREFLIGHT_S."
    )


def _spmd_main(
    fn: Callable,
    args: tuple,
    kwargs: dict,
    num_processes: int,
    coordinator: str,
    platform: Optional[str],
    num_cpu_devices: Optional[int],
    rank: int,
    rank_args: tuple = (),
):
    """Body shipped to every worker — shared prefix (fat: the user job)
    first, per-rank suffix last, matching WorkerGroup.run's ship-once
    split. Order matters: jax config BEFORE any backend initialization,
    distributed init BEFORE user code touches devices."""
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    if num_cpu_devices:
        try:
            jax.config.update("jax_num_cpu_devices", num_cpu_devices)
        except AttributeError:
            # older jax (< 0.5) has no jax_num_cpu_devices config; the
            # pre-backend XLA flag is the portable spelling. We run
            # before any backend init (nothing has touched devices yet),
            # so the flag is still honored. Strip an inherited count
            # first — repeated flags must not fight.
            import re as _re

            flags = os.environ.get("XLA_FLAGS", "")
            flags = _re.sub(
                r"--xla_force_host_platform_device_count=\d+", "", flags)
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{num_cpu_devices}")
        # Cross-process CPU collectives ride gloo (the CI fabric; on TPU
        # the fabric is ICI and this knob is untouched). Only with > 1
        # process: gloo requires the distributed client, which a
        # single-process job never initializes — setting it there kills
        # backend creation with an opaque "distributed_client: NoneType".
        if num_processes > 1:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    if num_processes > 1:
        if rank != 0:
            _await_coordinator(coordinator, rank)
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=rank,
        )
    result = fn(*args, *rank_args, **kwargs)
    # Success path ONLY: on an exception the peers may be mid-collective,
    # and tearing the coordination service out from under them turns one
    # rank's Python exception into cluster-wide gloo aborts (observed:
    # EnforceNotMet 'op.preamble.length 16 vs 4' -> SIGABRT on the
    # healthy rank) while THIS rank blocks in the shutdown barrier —
    # delaying the very error message the driver's fail-fast
    # classification needs. The failed group is torn down by the driver
    # (group.shutdown kills after the grace window), which is the
    # correct owner of cleanup on the error path.
    if num_processes > 1:
        try:
            jax.distributed.shutdown()
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass
    return result


def launch(
    fn: Callable,
    num_processes: int,
    *,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    platform: Optional[str] = None,
    num_cpu_devices_per_process: Optional[int] = None,
    env: Optional[Dict[str, str]] = None,
    init_hook: Optional[Callable[[], None]] = None,
    on_queue_item: Optional[Callable[[int, Any], None]] = None,
    per_rank_args: Optional[Sequence[tuple]] = None,
    log_dir: Optional[str] = None,
    timeout: Optional[float] = None,
    hosts: Optional[Sequence[str]] = None,
    transport: Optional[Transport] = None,
    coordinator_address: Optional[str] = None,
    watchdog: Optional[Callable[[], None]] = None,
) -> List[Any]:
    """Run ``fn`` on ``num_processes`` host-processes as one SPMD job.

    Returns the per-rank results in rank order (reference analog: the
    fan-out + process_results + unpack sequence, ray_ddp.py:178-193 — but
    every rank's return value is kept; rank 0's is the conventional
    carrier of results).

    ``fn`` runs AFTER jax.distributed.initialize, so inside it
    ``jax.devices()`` is the global device set and a ``Mesh`` built over it
    spans all processes.

    ``hosts`` + a remote ``transport`` (e.g. SSHTransport) place one
    process per cluster host — the cross-host path. The jax coordinator
    then binds on WORKER 0's host at its routable IP (the reference's
    MASTER_ADDR ← worker0 IP, MASTER_PORT ← free port dance,
    ray_ddp.py:152-156); locally it stays on loopback. Override with an
    explicit ``coordinator_address`` when pod metadata supplies one.
    """
    group = WorkerGroup(
        num_workers=num_processes,
        env=env,
        init_hook=init_hook,
        log_dir=log_dir,
        hosts=hosts,
        transport=transport,
    )
    group.start()
    try:
        if coordinator_address is not None:
            coordinator = coordinator_address
        elif group.is_remote and num_processes > 1:
            # rank 0 hosts the coordination service: its routable IP (from
            # the hello) + a port probed free on its own interfaces.
            host0 = group.executors[0].get_node_ip()
            port0 = group.run_single(0, _probe_coordinator_port, timeout=60)
            coordinator = f"{host0}:{port0}"
            log.info("jax coordinator at %s (worker 0)", coordinator)
        else:
            coordinator = f"127.0.0.1:{find_free_port()}"
        # Ship-once split (reference ray.put fan-out, ray_ddp.py:168-171):
        # the fat user job (fn + its args, typically module/data factories
        # with captured datasets) serializes ONCE in WorkerGroup.run; only
        # the rank id + per-rank extras are serialized per worker.
        shared = (fn, tuple(args), dict(kwargs or {}), num_processes,
                  coordinator, platform, num_cpu_devices_per_process)
        rank_extras = [
            (r, tuple(per_rank_args[r]) if per_rank_args else ())
            for r in range(num_processes)
        ]
        return group.run(
            _spmd_main,
            shared_args=shared,
            per_rank_args=rank_extras,
            on_queue_item=on_queue_item,
            timeout=timeout,
            watchdog=watchdog,
        )
    finally:
        group.shutdown()


def launch_cpu_spmd(
    fn: Callable,
    num_processes: int = 2,
    devices_per_process: int = 2,
    **kw,
) -> List[Any]:
    """CI/dev-box convenience: a real multi-process gloo-backed mesh with
    ``num_processes * devices_per_process`` CPU devices — the TPU-rebuild
    analog of the reference's throwaway local Ray clusters
    (``ray.init(num_cpus=2)``, reference tests/test_ddp.py:16-21)."""
    return launch(
        fn,
        num_processes,
        platform="cpu",
        num_cpu_devices_per_process=devices_per_process,
        **kw,
    )
