"""Distribution strategies: how params/optimizer-state/batches map onto a mesh.

This is the rebuild of the reference's plugin layer (RayPlugin,
ray_lightning/ray_ddp.py:42-307; HorovodRayPlugin, ray_horovod.py:29-196).
The reference had exactly one strategy — allreduce data-parallelism — in two
protocol flavors (torch DDP / Horovod). On TPU the "protocol" dimension
disappears (one collective fabric: XLA over ICI) and the strategy dimension
widens: a strategy here is a *sharding policy* over a `Mesh`; XLA emits the
collectives. No process group object exists and no explicit allreduce is
ever called.

Strategies keep the reference's constructor-object UX
(`Trainer(strategy=DataParallel(num_workers=8))`, mirroring
`Trainer(plugins=[RayPlugin(num_workers=8)])`, ray_ddp.py:89-94) including
`init_hook` (ray_ddp.py:66-67,118-119) and env-var injection
(ray_ddp.py:21-31,158-164).
"""
from __future__ import annotations

import math
import os
from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_lightning_tpu.parallel import mesh as mesh_lib
from ray_lightning_tpu.utils import get_logger
from ray_lightning_tpu.utils.pytree import _path_str, named_leaves as _named_leaves

log = get_logger(__name__)


class Strategy:
    """Base sharding strategy.

    Lifecycle (driven by the Trainer, cf. reference setup/start_training/
    post_dispatch at ray_ddp.py:113,143,201):
        setup(module)        — build the mesh, run init_hook, inject env vars
        shard_params(params) — place the param pytree with this policy
        shard_batch(batch)   — place a host batch as a global device array
        teardown()           — release mesh-related state
    """

    #: mesh axes this strategy uses; subclasses override.
    spec: mesh_lib.MeshSpec

    def __init__(
        self,
        num_workers: Optional[int] = None,
        init_hook: Optional[Callable[[], None]] = None,
        env: Optional[dict[str, str]] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        overlap: str = "off",
    ):
        if overlap not in ("off", "on", "serial"):
            raise ValueError(
                f"overlap must be 'off', 'on' or 'serial', got {overlap!r}")
        self.num_workers = num_workers
        self.init_hook = init_hook
        self.env = dict(env or {})
        self._devices = list(devices) if devices is not None else None
        #: collective-overlap schedule knob (docs/PERFORMANCE.md
        #: "collective overlap"): "on" asks the bound module to run its
        #: ZeRO/FSDP path with the double-buffered weight-gather
        #: prefetch (modules that have no such path ignore it). "off"
        #: (default) compiles the exact pre-knob program — test-pinned.
        #: "serial" is the ablation control: the same explicit per-layer
        #: gather schedule with the prefetch disabled (gather blocks at
        #: use) — bitwise-identical training to "on" (test-pinned), so
        #: any measured delta between the two is pure latency hiding.
        self.overlap = overlap
        self.mesh: Optional[Mesh] = None
        self._module = None

    @property
    def overlap_enabled(self) -> bool:
        return self.overlap != "off"

    # ---- lifecycle -------------------------------------------------------

    def _select_devices(self) -> list[jax.Device]:
        devices = self._devices if self._devices is not None else jax.devices()
        if self.num_workers is not None:
            if self.num_workers > len(devices):
                raise ValueError(
                    f"num_workers={self.num_workers} exceeds available "
                    f"devices ({len(devices)})"
                )
            devices = devices[: self.num_workers]
        return list(devices)

    def build_spec(self, n_devices: int) -> mesh_lib.MeshSpec:
        raise NotImplementedError

    def setup(self, module=None) -> Mesh:
        if self.env:
            os.environ.update(self.env)
        if self.init_hook is not None:
            self.init_hook()
        devices = self._select_devices()
        self.spec = self.build_spec(len(devices))
        self.mesh = self.spec.build(devices)
        self._module = module
        if module is not None:
            # bind before the module builds its model so seq/tensor manual
            # islands (e.g. ring attention) can close over the mesh.
            module.mesh = self.mesh
            module.overlap = self.overlap if self.overlap_enabled else False
        log.info(
            "strategy=%s mesh=%s over %d %s device(s)",
            type(self).__name__,
            dict(self.mesh.shape),
            len(devices),
            devices[0].platform,
        )
        return self.mesh

    def bind_module(self, module) -> None:
        """Point an already-built mesh at a (new) module: its param_specs
        drive sharding and it sees the mesh before building its model."""
        self._module = module
        if module is not None:
            module.mesh = self.mesh
            module.overlap = self.overlap if self.overlap_enabled else False

    def teardown(self) -> None:
        self.mesh = None
        self._module = None

    # ---- sharding policy -------------------------------------------------

    def param_spec(self, path: str, leaf) -> P:
        """PartitionSpec for one parameter leaf. Default: replicate."""
        return P()

    def param_shardings(self, params) -> Any:
        assert self.mesh is not None, "call setup() first"
        module_specs = {}
        if self._module is not None and hasattr(self._module, "param_specs"):
            module_specs = self._module.param_specs(params) or {}

        def one(path, leaf):
            spec = module_specs.get(path)
            if spec is None:
                spec = self.param_spec(path, leaf)
            else:
                # BEFORE adaptation: _adapt_spec silently drops axes the
                # mesh doesn't know, so a typo'd axis name would quietly
                # replicate the leaf — the OOM-at-scale failure the
                # shardcheck subsystem exists to catch (RLT101)
                self._require_known_axes(path, spec)
            spec = self._adapt_spec(spec, getattr(leaf, "shape", ()))
            self._require_well_formed(path, spec,
                                      getattr(leaf, "shape", ()))
            return NamedSharding(self.mesh, spec)

        return jax.tree_util.tree_map_with_path(
            lambda kp, leaf: one(_path_str(kp), leaf), params
        )

    def _require_known_axes(self, path: str, spec: P) -> None:
        """Raise when a module-provided spec names an axis the mesh does
        not have at all (distinct from a size-1 axis, which is legal and
        dropped by _adapt_spec)."""
        known = set(self.mesh.shape)
        unknown = sorted(_spec_names(spec) - known)
        if unknown:
            raise ValueError(
                f"param_specs for {path!r} names unknown mesh "
                f"axis(es) {unknown} (mesh axes: {sorted(known)}) — a "
                "typo here would silently replicate the leaf "
                "[shardcheck RLT101]"
            )

    def _require_well_formed(self, path: str, spec: P, shape) -> None:
        """Eager structural validation of the COMPOSED spec (shardcheck
        RLT102/103/104): fail at setup with the leaf's name instead of
        at compile time with an XLA sharding error."""
        from ray_lightning_tpu.analysis.plan_checker import spec_findings

        errors = [f for f in spec_findings(
            spec, shape, dict(self.mesh.shape), path=path)
            if f.severity == "error"]
        if errors:
            raise ValueError(
                "sharding plan is malformed:\n"
                + "\n".join(f.format() for f in errors)
            )

    def _adapt_spec(self, spec: P, shape) -> P:
        """Drop mesh axes the strategy's mesh doesn't materialize (size 1)."""
        assert self.mesh is not None
        out = []
        for dim in spec:
            if dim is None:
                out.append(None)
                continue
            names = dim if isinstance(dim, tuple) else (dim,)
            kept = tuple(n for n in names if self.mesh.shape.get(n, 1) > 1)
            out.append(kept if len(kept) > 1 else (kept[0] if kept else None))
        while out and out[-1] is None:
            out.pop()
        return P(*out)

    def opt_state_shardings(self, abstract_opt, params) -> Any:
        """Shardings for the optimizer state: param-shaped leaves (adam
        mu/nu, momentum, …) inherit their param's sharding — ZeRO
        semantics; scalars/schedules replicate.

        Without this, `jit(tx.init)` leaves the whole opt state on one
        device (the init is shape-only, so XLA drops the input dependency
        and with it the sharding propagation).

        Opt-state pytrees embed param subtrees (optax builds them with
        `tree_map(zeros_like, params)`), so each opt leaf is matched to
        the param whose full path is the longest suffix of the opt leaf's
        path and whose shape agrees.
        """
        assert self.mesh is not None, "call setup() first"
        param_shardings = self.param_shardings(params)
        by_path = {}
        for (path, leaf), sharding in zip(
            _named_leaves(params), jax.tree.leaves(param_shardings)
        ):
            by_path[path] = (getattr(leaf, "shape", ()), sharding)
        replicated = self.replicated()

        def one(path: str, leaf):
            parts = path.split("/")
            for i in range(len(parts)):
                cand = "/".join(parts[i:])
                hit = by_path.get(cand)
                if hit and hit[0] == getattr(leaf, "shape", ()):
                    return hit[1]
            return replicated

        return jax.tree_util.tree_map_with_path(
            lambda kp, leaf: one(_path_str(kp), leaf), abstract_opt
        )

    def batch_spec(self) -> P:
        assert self.mesh is not None
        return P(mesh_lib.dp_axis_names(self.mesh))

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec())

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    # ---- auditing --------------------------------------------------------

    def audit_step(self, module, example_batch, *, topology="v5p-8",
                   n_devices: Optional[int] = None,
                   reserve_fraction: float = 0.10, label: str = ""):
        """tracecheck this strategy's REAL jitted train step for
        ``module`` on ``topology`` (a name like ``"v5p-64"`` or an
        `analysis.costmodel.Topology`) — zero hardware, CPU-host safe.

        Returns an `analysis.tracecheck.TraceReport`: the collective
        schedule with ICI bytes/latency estimates, implicit-resharding
        findings (RLT301), ring/pipeline schedule checks (RLT303), and
        the peak-HBM estimate vs the chip budget (RLT302). Like
        `plan_train_memory`/`check_plan`, the strategy instance is
        CONSUMED (its mesh becomes abstract) — pass a fresh one, not
        the instance a live Trainer holds."""
        from ray_lightning_tpu.analysis.tracecheck import audit_step

        return audit_step(
            module, self, example_batch, topology=topology,
            n_devices=n_devices, reserve_fraction=reserve_fraction,
            label=label or f"{type(module).__name__} x "
                           f"{type(self).__name__}")

    # ---- trainguard: SDC fingerprint probe -------------------------------

    def sdc_probe(self, params):
        """Build the trainguard silent-data-corruption probe for this
        strategy's mesh (resilience/guard.py): a jitted ``shard_map`` in
        which every device digests its OWN local parameter bytes
        (bitcast-uint32 wraparound sum), gathered to one fingerprint per
        device with a single small collective.

        Returns ``(fn, devices, groups)``: ``fn(params) -> (n_devices,)``
        uint32 fingerprints in ``mesh.devices.reshape(-1)`` order,
        ``groups`` the replica groups whose members hold bit-identical
        bytes by this strategy's sharding policy (pure DP: all devices;
        pure FSDP: none — no redundancy to cross-check). Usable directly
        for an ad-hoc fleet screen: run it twice around a suspect step
        and diff."""
        from ray_lightning_tpu.resilience.guard import build_sdc_probe

        assert self.mesh is not None, "call setup() first"
        return build_sdc_probe(params, self.mesh)

    # ---- compile-cache identity ------------------------------------------

    def compile_cache_key(self) -> str:
        """Stable identity of this sharding plan for the persistent
        compilation cache (pipeline/compile_cache.py): strategy class +
        mesh axis sizes + device platform. Two runs with the same key
        lower the same step program, so they can share one cache dir;
        the actual cache entry key is XLA's own (hash of the lowered
        program), so this only partitions the directory space."""
        from ray_lightning_tpu.pipeline.compile_cache import plan_cache_key

        parts = [type(self).__name__]
        if self.mesh is not None:
            parts.append(sorted(self.mesh.shape.items()))
            parts.append(self.mesh.devices.flat[0].platform)
        if self._module is not None:
            parts.append(type(self._module).__name__)
        return plan_cache_key(*parts)

    def compile_cache_dir(self, base_dir: str) -> str:
        """Per-plan persistent cache directory under ``base_dir`` —
        hand this to ``Trainer(compile_cache_dir=...)`` (the resilience
        supervisor derives its own beside the checkpoint dir)."""
        import os as _os

        return _os.path.join(_os.path.abspath(base_dir),
                             self.compile_cache_key())

    # ---- placement -------------------------------------------------------

    def shard_params(self, params) -> Any:
        return jax.device_put(params, self.param_shardings(params))

    def shard_batch(self, batch) -> Any:
        """Place a host batch (pytree of numpy arrays) as global jax.Arrays.

        Single-process: a plain device_put against the batch sharding.
        Multi-process: each host holds its local shard of the global batch
        (the DistributedSampler analog; reference forces a sampler with
        num_replicas=num_workers, rank=global_rank at ray_ddp.py:293-303)
        and we assemble a global array from per-process shards.
        """
        sharding = self.batch_sharding()
        divisor = mesh_lib.batch_size_divisor(self.mesh)

        def place(x):
            x = np.asarray(x)
            if x.shape and x.shape[0] % divisor != 0:
                raise ValueError(
                    f"Global batch dim {x.shape[0]} not divisible by "
                    f"data-parallel degree {divisor} (mesh {dict(self.mesh.shape)})"
                )
            if jax.process_count() > 1:
                return jax.make_array_from_process_local_data(sharding, x)
            return jax.device_put(x, sharding)

        return jax.tree.map(place, batch)

    # ---- introspection ---------------------------------------------------

    @property
    def world_size(self) -> int:
        return math.prod(self.mesh.shape.values()) if self.mesh else 1

    @property
    def dp_size(self) -> int:
        return mesh_lib.batch_size_divisor(self.mesh) if self.mesh else 1


class DataParallel(Strategy):
    """Pure data parallelism: params replicated, batch sharded on `data`.

    Parity target: `RayPlugin` (reference ray_ddp.py:42-307). The gradient
    all-reduce the reference got from NCCL/Gloo buckets is compiled by XLA
    from the sharding annotations (psum over the `data` axis) and rides ICI.
    """

    def build_spec(self, n_devices: int) -> mesh_lib.MeshSpec:
        return mesh_lib.MeshSpec(data=n_devices)


class FSDP(Strategy):
    """ZeRO-style fully-sharded data parallelism as sharding annotations.

    Params and optimizer state are sharded along the `fsdp` mesh axis (each
    leaf on its largest divisible dimension); activations stay data-parallel.
    XLA inserts the all-gather (forward/backward) and reduce-scatter (grad)
    that FSDP implementations hand-schedule. Stands in the "second protocol"
    slot Horovod occupied in the reference (ray_horovod.py:29-196) and is
    the BASELINE.json Llama-8B strategy.
    """

    def __init__(self, *args, min_shard_size: int = 2**10, **kwargs):
        super().__init__(*args, **kwargs)
        self.min_shard_size = min_shard_size

    def build_spec(self, n_devices: int) -> mesh_lib.MeshSpec:
        return mesh_lib.MeshSpec(fsdp=n_devices)

    def param_spec(self, path: str, leaf) -> P:
        return fsdp_auto_spec(
            getattr(leaf, "shape", ()),
            self.mesh.shape.get("fsdp", 1),
            self.min_shard_size,
        )

    def _adapt_spec(self, spec: P, shape) -> P:
        return _fsdp_adapt_spec(self, spec, shape)


class ShardedMesh(Strategy):
    """Explicit N-D mesh strategy composing dp × fsdp × tensor × seq
    (× expert × pipe).

    The general form: `ShardedMesh(data=2, fsdp=2, tensor=2)`. Tensor-axis
    placement comes from the module's `param_specs` hook (Megatron-style
    column/row splits are module knowledge); fsdp placement is automatic;
    `pipe` feeds the GPipe building block (ops/pipeline.py).
    """

    def __init__(
        self,
        data: int = 1,
        fsdp: int = 1,
        expert: int = 1,
        seq: int = 1,
        tensor: int = 1,
        pipe: int = 1,
        min_shard_size: int = 2**10,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self._spec = mesh_lib.MeshSpec(data, fsdp, expert, seq, tensor, pipe)
        self.min_shard_size = min_shard_size

    def build_spec(self, n_devices: int) -> mesh_lib.MeshSpec:
        return self._spec.resolve(n_devices)

    def param_spec(self, path: str, leaf) -> P:
        return fsdp_auto_spec(
            getattr(leaf, "shape", ()),
            self.mesh.shape.get("fsdp", 1),
            self.min_shard_size,
        )

    def _adapt_spec(self, spec: P, shape) -> P:
        return _fsdp_adapt_spec(self, spec, shape)


class SingleDevice(Strategy):
    """Trivial strategy: one device, no sharding (debug / laptop path)."""

    def __init__(self, **kwargs):
        kwargs.setdefault("num_workers", 1)
        super().__init__(**kwargs)

    def build_spec(self, n_devices: int) -> mesh_lib.MeshSpec:
        return mesh_lib.MeshSpec()


# Reference-familiar alias: `RayPlugin` → the TPU DP strategy; the north
# star names it RayXlaPlugin (BASELINE.json).
class RayXlaPlugin(DataParallel):
    """Drop-in ctor shape of the reference's RayPlugin (ray_ddp.py:89-94).

    ``num_cpus_per_worker`` is honored as the per-worker host-CPU budget:
    it is exported through the strategy's env injection and sizes the data
    pipeline's prefetch thread pool (core/data.py); pair it with
    ``TpuResources(cpus=...)`` for sweep-level packing. ``use_gpu`` has no
    TPU meaning and warns when set (the device set IS the TPU slice).
    """

    def __init__(self, num_workers: Optional[int] = None,
                 num_cpus_per_worker: Optional[int] = None,
                 use_gpu: bool = False, init_hook=None, **kwargs):
        if use_gpu:
            log.warning("RayXlaPlugin(use_gpu=True) ignored: this is the "
                        "TPU backend; devices come from the slice topology")
        env = dict(kwargs.pop("env", None) or {})
        if num_cpus_per_worker is not None:
            # only an EXPLICIT budget is exported — a default injection
            # would leak into os.environ and retune every DataLoader in
            # the process, not just this strategy's
            env.setdefault("RLT_NUM_CPUS_PER_WORKER",
                           str(max(1, num_cpus_per_worker)))
        self.num_cpus_per_worker = max(1, num_cpus_per_worker or 1)
        super().__init__(num_workers=num_workers, init_hook=init_hook,
                         env=env, **kwargs)


# ---- spec helpers --------------------------------------------------------


def _fsdp_adapt_spec(strategy: Strategy, spec: P, shape) -> P:
    """Shared FSDP/ShardedMesh adapt: drop trivial axes, then overlay
    `fsdp` on a free divisible dim of module-provided tensor specs."""
    spec = Strategy._adapt_spec(strategy, spec, shape)
    if (strategy.mesh.shape.get("fsdp", 1) > 1
            and "fsdp" not in _spec_names(spec)):
        spec = _augment_with_axis(
            spec, shape, "fsdp", strategy.mesh.shape["fsdp"],
            strategy.min_shard_size,
        )
    return spec


def _spec_names(spec: P) -> set:
    names = set()
    for dim in spec:
        if dim is None:
            continue
        for n in dim if isinstance(dim, tuple) else (dim,):
            names.add(n)
    return names


def _augment_with_axis(
    spec: P, shape, axis_name: str, axis_size: int, min_size: int
) -> P:
    """Add `axis_name` to the largest free, divisible dim of `spec`."""
    if not shape or int(np.prod(shape)) < min_size:
        return spec
    dims = list(spec) + [None] * (len(shape) - len(spec))
    candidates = sorted(
        range(len(shape)), key=lambda i: shape[i], reverse=True
    )
    for i in candidates:
        if dims[i] is None and shape[i] % axis_size == 0:
            dims[i] = axis_name
            return P(*dims)
    return spec


def fsdp_auto_spec(shape, fsdp_size: int, min_size: int) -> P:
    """Shard the largest divisible dim on `fsdp`; replicate small leaves."""
    if fsdp_size <= 1:
        return P()
    return _augment_with_axis(P(*([None] * len(shape))), shape, "fsdp",
                              fsdp_size, min_size)


