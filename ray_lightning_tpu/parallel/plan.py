"""Pre-flight sharding/memory planner: prove a training configuration
fits a target topology BEFORE touching hardware.

The reference could not need this — its models were MNIST-sized MLPs
(reference tests/utils.py:96-120) and memory planning was "it fits". At
the north-star scale (BASELINE.json config 4: Llama-3-8B FSDP on a
v5p-64) a mis-sized mesh surfaces as a compile-time OOM after minutes of
queueing, so the framework owns a planner:

  * params/optimizer-state/gradient bytes are computed EXACTLY — the
    model is built only as `jax.eval_shape` abstractions and sharded by
    the strategy's own composition logic over a `jax.sharding.AbstractMesh`
    (zero devices of any kind needed, so an 8-chip dev box can plan a
    4096-chip pod);
  * activations are an analytic, documented bound (they depend on the
    remat policy and loss path, not just shapes) — see
    `llama_activation_bytes` for the flagship model's formula.

Typical use (and the shape of tests/test_llama8b_plan.py)::

    plan = plan_train_memory(
        LlamaModule(LlamaConfig.llama3_8b()),
        ShardedMesh(fsdp=64),
        n_devices=64,
        example_batch={"tokens": np.zeros((64, 8193), np.int32)},
        device_kind="TPU v5p",
    )
    assert plan.fits, plan.summary()
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import AbstractMesh

from ray_lightning_tpu.parallel.mesh import AXIS_ORDER, MeshSpec

#: usable HBM per jax device, by PJRT device_kind (public spec sheets).
#: v5p advertises 95 GiB per chip; v5e/v6e per-chip figures likewise.
HBM_BYTES_BY_KIND: Dict[str, int] = {
    "TPU v3": 16 * 1024**3,
    "TPU v4": 32 * 1024**3,
    "TPU v5 lite": 16 * 1024**3,
    "TPU v5e": 16 * 1024**3,
    "TPU v5": 95 * 1024**3,
    "TPU v5p": 95 * 1024**3,
    "TPU v6 lite": 32 * 1024**3,
    "TPU v6e": 32 * 1024**3,
}


def abstract_mesh(spec: MeshSpec) -> AbstractMesh:
    """An AbstractMesh with this spec's axis names/sizes — NamedSharding
    accepts it, `shard_shape` works, and no devices are required.

    Handles both AbstractMesh signatures: the current
    ``AbstractMesh(axis_sizes, axis_names)`` and the older
    ``AbstractMesh(shape_tuple)`` of (name, size) pairs (jax <= 0.4.x),
    so the planner keeps its zero-device guarantee across the jax
    versions the runtime supports."""
    sizes = spec.sizes()
    shape = tuple(sizes[ax] for ax in AXIS_ORDER)
    try:
        return AbstractMesh(shape, AXIS_ORDER)
    except TypeError:
        return AbstractMesh(tuple(zip(AXIS_ORDER, shape)))


def hbm_bytes_for_kind(device_kind: str,
                       hbm_bytes: Optional[int] = None) -> int:
    """Usable HBM per device for ``device_kind`` — or the explicit
    ``hbm_bytes`` override for hardware the table doesn't know. An
    unknown kind without an override raises a ValueError LISTING the
    known kinds (never a bare KeyError): the planner's most common
    first-contact failure is a device_kind string that doesn't match the
    spec-sheet spelling."""
    if hbm_bytes is not None:
        if hbm_bytes <= 0:
            raise ValueError(f"hbm_bytes must be positive, got {hbm_bytes}")
        return int(hbm_bytes)
    if device_kind not in HBM_BYTES_BY_KIND:
        raise ValueError(
            f"unknown device_kind {device_kind!r} (known: "
            f"{sorted(HBM_BYTES_BY_KIND)}); pass hbm_bytes_per_device= "
            "(plan_train_memory) / hbm_bytes= (this helper; CLI "
            "--hbm-bytes) explicitly for other hardware"
        )
    return HBM_BYTES_BY_KIND[device_kind]


@dataclasses.dataclass(frozen=True)
class MemoryPlan:
    mesh_axes: Dict[str, int]
    n_devices: int
    hbm_bytes_per_device: int
    params_bytes_global: int
    opt_bytes_global: int
    params_bytes_per_device: int
    opt_bytes_per_device: int
    grads_bytes_per_device: int
    activation_bytes_per_device: int
    #: fraction of HBM the plan refuses to allocate (XLA workspace,
    #: fragmentation, infeed buffers)
    reserve_fraction: float = 0.10

    @property
    def per_device_total(self) -> int:
        return (self.params_bytes_per_device + self.opt_bytes_per_device
                + self.grads_bytes_per_device
                + self.activation_bytes_per_device)

    @property
    def budget(self) -> int:
        return int(self.hbm_bytes_per_device * (1 - self.reserve_fraction))

    @property
    def fits(self) -> bool:
        return self.per_device_total <= self.budget

    @property
    def headroom_bytes(self) -> int:
        return self.budget - self.per_device_total

    def summary(self) -> str:
        gib = 1024**3
        return (
            f"mesh {self.mesh_axes} x{self.n_devices} devices: "
            f"params {self.params_bytes_per_device / gib:.2f} + "
            f"opt {self.opt_bytes_per_device / gib:.2f} + "
            f"grads {self.grads_bytes_per_device / gib:.2f} + "
            f"acts {self.activation_bytes_per_device / gib:.2f} = "
            f"{self.per_device_total / gib:.2f} GiB/device vs budget "
            f"{self.budget / gib:.2f} GiB "
            f"({'FITS' if self.fits else 'DOES NOT FIT'}; global params "
            f"{self.params_bytes_global / gib:.2f} GiB, opt "
            f"{self.opt_bytes_global / gib:.2f} GiB)"
        )


def _tree_bytes(tree) -> int:
    return sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for l in jax.tree.leaves(tree)
    )


def _sharded_tree_bytes(tree, shardings) -> int:
    total = 0
    for leaf, sh in zip(jax.tree.leaves(tree), jax.tree.leaves(shardings)):
        total += int(np.prod(sh.shard_shape(leaf.shape))) * leaf.dtype.itemsize
    return total


def _abstract(batch) -> Any:
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
        if not isinstance(x, jax.ShapeDtypeStruct) else x,
        batch,
    )


def plan_train_memory(
    module,
    strategy,
    n_devices: int,
    example_batch: Any,
    *,
    activation_bytes_per_device: int = 0,
    device_kind: str = "TPU v5p",
    hbm_bytes_per_device: Optional[int] = None,
    reserve_fraction: float = 0.10,
) -> MemoryPlan:
    """Exact per-device param/opt/grad bytes for ``module`` trained under
    ``strategy`` on ``n_devices``, plus the caller's activation estimate.

    Builds NOTHING on devices: the strategy's sharding composition
    (module `param_specs` overlay + fsdp auto-placement + opt-state
    inheritance — the same code the Trainer runs) is evaluated against an
    AbstractMesh, and the model exists only as `eval_shape` output. The
    ``strategy`` instance is consumed by the plan (its mesh becomes
    abstract) — pass a fresh one, not the instance a Trainer will use.
    """
    spec = strategy.build_spec(n_devices).resolve(n_devices)
    mesh = abstract_mesh(spec)
    strategy.spec = spec
    strategy.mesh = mesh
    strategy.bind_module(module)
    module.setup()

    # The planner must never initialize a jax backend — it may be run
    # precisely because the accelerator is unavailable. Two traps:
    #   * a concrete jax.random.key(0) would materialize on the default
    #     device → the rng key is eval_shape'd abstract instead;
    #   * the pallas dispatch decision (ops/dispatch.py on_tpu) queries
    #     jax.default_backend() at TRACE time → pin the XLA reference
    #     path via the context-scoped override (kernel choice cannot
    #     change shapes; a contextvar, unlike an env write, leaves
    #     concurrent traces in other threads untouched).
    from ray_lightning_tpu.ops.dispatch import force_xla

    a_key = jax.eval_shape(lambda: jax.random.key(0))
    with force_xla():
        a_params = jax.eval_shape(
            module.init_params, a_key, _abstract(example_batch)
        )
        p_shardings = strategy.param_shardings(a_params)
        tx = module.configure_optimizers()
        a_opt = jax.eval_shape(tx.init, a_params)
        o_shardings = strategy.opt_state_shardings(a_opt, a_params)

    hbm_bytes_per_device = hbm_bytes_for_kind(
        device_kind, hbm_bytes_per_device)
    params_dev = _sharded_tree_bytes(a_params, p_shardings)
    opt_dev = _sharded_tree_bytes(a_opt, o_shardings)
    return MemoryPlan(
        mesh_axes={k: v for k, v in spec.sizes().items() if v > 1},
        n_devices=n_devices,
        hbm_bytes_per_device=hbm_bytes_per_device,
        params_bytes_global=_tree_bytes(a_params),
        opt_bytes_global=_tree_bytes(a_opt),
        params_bytes_per_device=params_dev,
        opt_bytes_per_device=opt_dev,
        # grads materialize at param sharding/dtype during the step (the
        # donated update overlaps them with params briefly — count them
        # in full; this is the conservative peak)
        grads_bytes_per_device=params_dev,
        activation_bytes_per_device=activation_bytes_per_device,
        reserve_fraction=reserve_fraction,
    )


def llama_activation_bytes(cfg, local_batch: int, seq: int,
                           weight_shard_degree: int = 1) -> int:
    """Activation-footprint bound for the flagship train step —
    remat=True (policy "nothing") + scan_layers + fused CE, the only
    configuration class that holds at 8B (models/llama.py):

      * saved residuals: the per-layer checkpoint stores each block's
        input, L x [B, S, D] bf16 (policy "nothing" saves only inputs);
      * one layer's live recompute set during its backward: the block
        re-runs forward, materializing qkv [B,S,(H+2Hkv)hd], two norms /
        residual adds [B,S,D] each, and the SwiGLU pair [B,S,3F], with
        gradient buffers alongside — 2x (value + cotangent);
      * loss tail: embedding output + final hidden [B,S,D] (bf16 + f32
        copy) and the fused-CE live tile, chunk x V bf16 logits x2
        (recompute + grad);
      * ce_inline_bwd adds its residuals: dx [B·S, D] (hidden dtype) and
        the f32 dW accumulator [D, V] (ops/fused_ce.py _ce_inline) —
        live from the forward scan until the optimizer update. Under
        SPMD the accumulator inherits the lm_head grad's sharding, so
        pass ``weight_shard_degree`` (the fsdp×tensor product) to charge
        the per-device shard instead of the full [D, V] — a ~3 GB
        overcharge at 8B scale would otherwise flip the exact flagship
        FSDP config this path was built for to DOES-NOT-FIT;
      * 1.5x slack for allocator fragmentation and XLA temporaries.

    Deliberately an over-estimate: a plan that passes here compiles with
    room to spare; exactness lives in the params/opt terms.
    """
    bs = local_batch * seq
    hd = cfg.head_dim
    saved = cfg.n_layers * bs * cfg.dim * 2
    if (getattr(cfg, "remat", True)
            and getattr(cfg, "remat_policy", "nothing") == "attn_out"):
        # per-layer saved attention residuals (q, o: H·hd; k, v: Hkv·hd;
        # model dtype — charged at cfg.dtype's width, not a bf16
        # assumption) + the f32 logsumexp — models/llama.py
        # _attn_residuals_saveable. Gated on cfg.remat: with remat=False
        # the model documents the policy as ignored, so charging the
        # residuals would overestimate against the config contract.
        elem = int(np.dtype(cfg.dtype).itemsize) if getattr(
            cfg, "dtype", None) is not None else 2
        saved += cfg.n_layers * bs * (
            (2 * cfg.n_heads + 2 * cfg.n_kv_heads) * hd * elem
            + cfg.n_heads * 4)
    live = bs * (
        2 * cfg.dim
        + (cfg.n_heads + 2 * cfg.n_kv_heads) * hd
        + 3 * cfg.hidden_dim
    ) * 2 * 2
    ce = (cfg.ce_chunk_tokens * cfg.vocab_size * 2 * 2
          + bs * cfg.dim * (2 + 4))
    if getattr(cfg, "ce_inline_bwd", False):
        # + the live-tile delta: the inline body holds the f32 logits AND
        # the bf16 dlogits (6 B/elem, ops/fused_ce.py _ce_inline_fwd)
        # where the remat path's charge above assumed two bf16 tiles
        ce += (cfg.ce_chunk_tokens * cfg.vocab_size * 2
               + bs * cfg.dim * 2
               + cfg.dim * cfg.vocab_size * 4 // max(1, weight_shard_degree))
    return int(1.5 * (saved + live + ce))


def llama_overlap_buffer_bytes(cfg, fsdp: int = 1, tensor: int = 1,
                               mode: str = "on") -> int:
    """Extra per-device HBM the collective-overlap schedule holds beyond
    the naive ZeRO path (models/llama.py `_overlapped_hidden`,
    docs/PERFORMANCE.md "collective overlap"):

      * the double buffer: ONE extra layer's weights gathered over
        `fsdp` (the prefetched layer i+1 — layer i's gathered working
        set exists transiently under the naive schedule too, so only
        the second buffer is NEW). Still `tensor`-split — the gather
        un-does only the fsdp overlay. Weights live at param_dtype
        (f32, models/llama.py LlamaBlock);
      * the rolled prefetch xs: the scan consumes a second stacked copy
        of the layer weights (`jnp.concatenate([p[1:], p[:1]])`),
        fsdp-sharded like the original — one layer-stack shard;
      * the in-flight gradient: one layer's grad shard mid
        reduce-scatter while the backward scan retires the next layer.

    ``mode="serial"`` (the ablation) charges only the in-flight grad
    shard: the serial schedule gathers in-body (no second buffer — the
    transient gathered layer exists under the naive schedule too) and
    scans the original stack (no rolled xs copy). ``mode="off"`` — or
    any config where the schedule never goes live (models/llama.py
    ``_use_overlap``) — returns 0, so callers can pass the knob through
    unguarded (RLT302 HBM accounting stays honest either way).
    """
    if mode == "off":
        return 0
    # the schedule is only LIVE with fsdp latency to hide on a scanned
    # stack deep enough to pipeline (models/llama.py _use_overlap) —
    # on an inert config the compiled program is the naive one and the
    # honest charge is zero (a phantom ~n_layers x layer_bytes charge
    # here would flip a fitting fsdp=1 job to DOES-NOT-FIT)
    if (fsdp <= 1 or not getattr(cfg, "scan_layers", True)
            or cfg.n_layers < 2):
        return 0
    d, f, hd = cfg.dim, cfg.hidden_dim, cfg.head_dim
    layer_params = (
        d * (cfg.n_heads + 2 * cfg.n_kv_heads) * hd   # wqkv
        + cfg.n_heads * hd * d                        # wo
        + d * 2 * f                                   # w_gate_up
        + f * d                                       # w_down
        + 2 * d                                       # the two norm gains
    )
    layer_bytes = layer_params * 4  # param_dtype is f32
    gathered = layer_bytes // max(1, tensor)
    shard = layer_bytes // max(1, fsdp * tensor)
    if mode == "serial":
        return int(shard)
    stack_shard = cfg.n_layers * shard
    return int(gathered + stack_shard + shard)


def find_max_local_batch(
    module,
    strategy,
    n_devices: int,
    example_batch: Any,
    activation_bytes_fn,
    *,
    device_kind: str = "TPU v5p",
    hbm_bytes_per_device: Optional[int] = None,
    reserve_fraction: float = 0.10,
    ceiling: int = 65536,
) -> tuple[int, MemoryPlan]:
    """Largest per-device batch that fits, found at plan time — the
    TPU-first analog of PTL's ``auto_scale_batch_size`` (which the
    reference inherited from its PTL base): instead of trial-and-error
    OOM probing on live hardware, the weight-side costs are planned once
    (params/opt/grads are batch-independent) and the analytic activation
    bound is binary-searched against the remaining HBM. Zero devices
    touched, zero failed compiles.

    ``activation_bytes_fn(local_batch) -> int`` must be monotone
    non-decreasing (e.g. ``lambda b: llama_activation_bytes(cfg, b, S)``).
    ``example_batch`` sizes only the init trace; its batch dim does not
    constrain the search.

    Returns ``(local_batch, plan)`` where ``plan`` charges the found
    batch's activations; ``(0, plan)`` with the activation-free plan when
    even ``local_batch=1`` does not fit (the caller's model/mesh choice is
    the problem, not the batch). The global batch is
    ``local_batch * dp_degree(spec)``.
    """
    base = plan_train_memory(
        module, strategy, n_devices, example_batch,
        activation_bytes_per_device=0, device_kind=device_kind,
        hbm_bytes_per_device=hbm_bytes_per_device,
        reserve_fraction=reserve_fraction,
    )
    avail = base.headroom_bytes

    def fits(b: int) -> bool:
        return activation_bytes_fn(b) <= avail

    if not fits(1):  # covers avail < 0: no non-negative bound fits
        return 0, base

    # exponential growth to bracket, then bisect. Invariant: fits(lo) is
    # verified; hi is an EXCLUSIVE upper bound (failed, or past ceiling).
    lo, hi = 1, 2
    while hi <= ceiling and fits(hi):
        lo, hi = hi, hi * 2
    hi = min(hi, ceiling + 1)
    while hi - lo > 1:
        mid = (lo + hi) // 2
        if fits(mid):
            lo = mid
        else:
            hi = mid
    best = dataclasses.replace(
        base, activation_bytes_per_device=int(activation_bytes_fn(lo)))
    return lo, best


# ---- multi-slice (DCN) layout queries -------------------------------------
#
# The mesh layer lays devices out slice-major with `data` outermost
# (mesh.order_devices_for_slices): on an S-slice deployment, slice k
# owns the k-th contiguous block of T/S linear device indices of the
# AXIS_ORDER-major mesh array. These helpers answer, from that layout
# contract alone (no devices), which communication groups cross the
# slice boundary — the seam tracecheck's DCN tier (RLT306) and the
# elastic planner both price against.


def group_dcn_span(axes: Sequence[str], mesh_sizes: Mapping[str, int],
                   n_slices: int) -> int:
    """Number of distinct DCN slices a collective group varying exactly
    ``axes`` touches (1 = the group lives inside one slice).

    Computed from the mixed-radix AXIS_ORDER-major layout with
    slice-major device order: enumerate the group's member coordinates
    (axes absent from ``mesh_sizes`` count as size 1) and count the
    distinct ``linear_index // devices_per_slice`` blocks. Exact for the
    base-0 representative group; the layout is regular, so every other
    group of the same axes has the same span."""
    sizes = {ax: int(mesh_sizes.get(ax, 1)) for ax in AXIS_ORDER}
    total = math.prod(sizes.values())
    if n_slices <= 1 or total % n_slices:
        return 1
    per_slice = total // n_slices
    strides: Dict[str, int] = {}
    st = 1
    for ax in reversed(AXIS_ORDER):
        strides[ax] = st
        st *= sizes[ax]
    group_axes = [ax for ax in AXIS_ORDER
                  if ax in tuple(axes) and sizes[ax] > 1]
    members = {0}
    for ax in group_axes:
        members = {
            base + k * strides[ax]
            for base in members for k in range(sizes[ax])
        }
    return len({idx // per_slice for idx in members})


def dcn_crossing_axes(mesh_sizes: Mapping[str, int],
                      n_slices: int) -> Dict[str, int]:
    """Per non-trivial mesh axis: how many slices a group varying only
    that axis spans (entries only for axes that DO cross, span > 1).
    On the canonical layout only `data` (the outermost axis) should
    appear here; any other axis crossing DCN is the performance cliff
    RLT306 flags."""
    out: Dict[str, int] = {}
    for ax in AXIS_ORDER:
        if int(mesh_sizes.get(ax, 1)) <= 1:
            continue
        span = group_dcn_span((ax,), mesh_sizes, n_slices)
        if span > 1:
            out[ax] = span
    return out


def dp_degree(spec: MeshSpec) -> int:
    """Batch divisor of a spec (mirrors mesh_lib.dp_axis_names for
    specs). Requires a RESOLVED spec — a -1 wildcard would silently
    contribute nothing and undercount the degree."""
    sizes = spec.sizes()
    if any(s == -1 for s in sizes.values()):
        raise ValueError(
            f"dp_degree needs a resolved spec (call spec.resolve(n) "
            f"first); got {sizes}"
        )
    return math.prod(sizes[ax] for ax in ("data", "fsdp", "expert"))
