"""Device-mesh construction and topology queries.

This layer replaces the reference's rendezvous + rank bookkeeping
(reference: ray_lightning/ray_ddp.py:130-141 IP-based local-rank map,
:152-156 MASTER_ADDR/PORT dance, :257-264 torch.distributed process-group
init). On TPU there is no process group: a `jax.sharding.Mesh` over the
slice's devices is the communication fabric, and XLA compiles collectives
from sharding annotations. Rank helpers become topology queries.

Canonical axis names (outer→inner, DCN-slowest to ICI-fastest):
    data    — pure data parallelism (batch axis)
    pipe    — pipeline-parallel stages (microbatches flow stage→stage)
    fsdp    — parameter/optimizer-state sharding (ZeRO-style), also carries batch
    tensor  — tensor (Megatron-style) parallelism inside a layer
    seq     — sequence/context parallelism (ring attention)
    expert  — expert parallelism for MoE
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("data", "pipe", "fsdp", "expert", "seq", "tensor")

# Axes whose groups should ride ICI (fast, intra-slice): tensor/seq innermost.
# `data` is the outermost axis so multi-slice DCN traffic only carries
# gradient all-reduces, never per-layer tensor collectives.


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape. -1 on at most one axis means "all remaining"."""

    data: int = 1
    fsdp: int = 1
    expert: int = 1
    seq: int = 1
    tensor: int = 1
    #: pipeline-parallel stages (GPipe building block, ops/pipeline.py);
    #: appended last so positional (data, fsdp, expert, seq, tensor)
    #: construction stays valid
    pipe: int = 1

    def sizes(self) -> dict[str, int]:
        return {ax: getattr(self, ax) for ax in AXIS_ORDER}

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = self.sizes()
        wild = [ax for ax, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"At most one -1 axis allowed, got {wild}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        total = math.prod(sizes.values())
        if total != n_devices:
            raise ValueError(
                f"Mesh {sizes} covers {total} devices but {n_devices} are available"
            )
        return MeshSpec(**sizes)

    def build(self, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        spec = self.resolve(len(devices))
        shape = tuple(spec.sizes()[ax] for ax in AXIS_ORDER)
        devices = order_devices_for_slices(devices, spec)
        arr = np.asarray(devices).reshape(shape)
        return Mesh(arr, AXIS_ORDER)


def order_devices_for_slices(devices: Sequence, spec: "MeshSpec") -> list:
    """Multi-slice (DCN) aware device ordering.

    On a multi-slice TPU deployment each device carries a ``slice_index``;
    ICI only spans a slice, slices talk over DCN. The mesh's OUTERMOST
    axis (`data`, AXIS_ORDER[0]) must therefore vary across slices so the
    only cross-slice collective is the gradient all-reduce, while
    fsdp/tensor/seq/expert groups stay inside a slice on ICI (the layout
    contract stated at the top of this module; the reference has no
    analog — NCCL ring costs were Ray's problem, SURVEY §2.2).

    Returns devices slice-major (slice 0's devices first, stable order
    within a slice) so ``reshape(data, ...)`` puts whole slices under
    distinct `data` coordinates. Single-slice (or CPU) inputs come back
    unchanged. Raises when `data` cannot absorb the slice count or slices
    are uneven — a mesh silently splitting tensor groups across DCN would
    be a performance cliff, not a config choice.
    """
    slice_ids = sorted(
        {getattr(d, "slice_index", None) or 0 for d in devices}
    )
    if len(slice_ids) <= 1:
        return list(devices)
    n_slices = len(slice_ids)
    by_slice = {s: [] for s in slice_ids}
    for d in devices:
        by_slice[getattr(d, "slice_index", None) or 0].append(d)
    per = len(devices) // n_slices
    if any(len(v) != per for v in by_slice.values()):
        raise ValueError(
            f"uneven slices: { {s: len(v) for s, v in by_slice.items()} }"
        )
    if spec.data % n_slices != 0:
        raise ValueError(
            f"data axis ({spec.data}) must be a multiple of the slice "
            f"count ({n_slices}) so only data-parallel gradient reduction "
            "crosses DCN; tensor/seq/fsdp groups cannot span slices"
        )
    out: list = []
    for s in slice_ids:
        out.extend(by_slice[s])
    return out


def make_mesh(
    data: int = 1,
    fsdp: int = 1,
    expert: int = 1,
    seq: int = 1,
    tensor: int = 1,
    pipe: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    return MeshSpec(data, fsdp, expert, seq, tensor, pipe).build(devices)


# --- Topology queries (replace reference's get_local_ranks / root_device) ---


def process_index() -> int:
    """Global host rank (reference analog: global_rank, ray_ddp.py:266-270)."""
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def local_device_count() -> int:
    return jax.local_device_count()


def global_device_count() -> int:
    return jax.device_count()


def dp_axis_names(mesh: Mesh) -> tuple[str, ...]:
    """Axes that carry the batch: every non-trivial axis except tensor/seq.

    `fsdp` and `expert` groups also consume distinct batch shards (ZeRO
    semantics: each shard-group is a data-parallel replica for activations).
    """
    return tuple(
        ax for ax in ("data", "fsdp", "expert") if mesh.shape.get(ax, 1) > 1
    ) or ("data",)


def batch_size_divisor(mesh: Mesh) -> int:
    return math.prod(mesh.shape.get(ax, 1) for ax in dp_axis_names(mesh))
