"""Device-mesh construction and topology queries.

This layer replaces the reference's rendezvous + rank bookkeeping
(reference: ray_lightning/ray_ddp.py:130-141 IP-based local-rank map,
:152-156 MASTER_ADDR/PORT dance, :257-264 torch.distributed process-group
init). On TPU there is no process group: a `jax.sharding.Mesh` over the
slice's devices is the communication fabric, and XLA compiles collectives
from sharding annotations. Rank helpers become topology queries.

Canonical axis names (outer→inner, DCN-slowest to ICI-fastest):
    data    — pure data parallelism (batch axis)
    fsdp    — parameter/optimizer-state sharding (ZeRO-style), also carries batch
    tensor  — tensor (Megatron-style) parallelism inside a layer
    seq     — sequence/context parallelism (ring attention)
    expert  — expert parallelism for MoE
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("data", "fsdp", "expert", "seq", "tensor")

# Axes whose groups should ride ICI (fast, intra-slice): tensor/seq innermost.
# `data` is the outermost axis so multi-slice DCN traffic only carries
# gradient all-reduces, never per-layer tensor collectives.


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape. -1 on at most one axis means "all remaining"."""

    data: int = 1
    fsdp: int = 1
    expert: int = 1
    seq: int = 1
    tensor: int = 1

    def sizes(self) -> dict[str, int]:
        return {ax: getattr(self, ax) for ax in AXIS_ORDER}

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = self.sizes()
        wild = [ax for ax, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"At most one -1 axis allowed, got {wild}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        total = math.prod(sizes.values())
        if total != n_devices:
            raise ValueError(
                f"Mesh {sizes} covers {total} devices but {n_devices} are available"
            )
        return MeshSpec(**sizes)

    def build(self, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        spec = self.resolve(len(devices))
        shape = tuple(spec.sizes()[ax] for ax in AXIS_ORDER)
        arr = np.asarray(devices).reshape(shape)
        return Mesh(arr, AXIS_ORDER)


def make_mesh(
    data: int = 1,
    fsdp: int = 1,
    expert: int = 1,
    seq: int = 1,
    tensor: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    return MeshSpec(data, fsdp, expert, seq, tensor).build(devices)


# --- Topology queries (replace reference's get_local_ranks / root_device) ---


def process_index() -> int:
    """Global host rank (reference analog: global_rank, ray_ddp.py:266-270)."""
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def local_device_count() -> int:
    return jax.local_device_count()


def global_device_count() -> int:
    return jax.device_count()


def dp_axis_names(mesh: Mesh) -> tuple[str, ...]:
    """Axes that carry the batch: every non-trivial axis except tensor/seq.

    `fsdp` and `expert` groups also consume distinct batch shards (ZeRO
    semantics: each shard-group is a data-parallel replica for activations).
    """
    return tuple(
        ax for ax in ("data", "fsdp", "expert") if mesh.shape.get(ax, 1) > 1
    ) or ("data",)


def batch_size_divisor(mesh: Mesh) -> int:
    return math.prod(mesh.shape.get(ax, 1) for ax in dp_axis_names(mesh))
