from ray_lightning_tpu.parallel.mesh import MeshSpec, make_mesh, AXIS_ORDER
from ray_lightning_tpu.parallel.strategy import (
    Strategy,
    DataParallel,
    FSDP,
    ShardedMesh,
    SingleDevice,
    RayXlaPlugin,
)

__all__ = [
    "MeshSpec",
    "make_mesh",
    "AXIS_ORDER",
    "Strategy",
    "DataParallel",
    "FSDP",
    "ShardedMesh",
    "SingleDevice",
    "RayXlaPlugin",
]
