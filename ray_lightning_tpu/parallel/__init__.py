from ray_lightning_tpu.parallel.mesh import MeshSpec, make_mesh, AXIS_ORDER
from ray_lightning_tpu.parallel.plan import (
    MemoryPlan,
    find_max_local_batch,
    hbm_bytes_for_kind,
    llama_activation_bytes,
    plan_train_memory,
)
from ray_lightning_tpu.parallel.strategy import (
    Strategy,
    DataParallel,
    FSDP,
    ShardedMesh,
    SingleDevice,
    RayXlaPlugin,
)

__all__ = [
    "MeshSpec",
    "make_mesh",
    "AXIS_ORDER",
    "MemoryPlan",
    "find_max_local_batch",
    "hbm_bytes_for_kind",
    "llama_activation_bytes",
    "plan_train_memory",
    "Strategy",
    "DataParallel",
    "FSDP",
    "ShardedMesh",
    "SingleDevice",
    "RayXlaPlugin",
]
