"""Deterministic scripted-load harness: the control loop on a virtual
clock.

Wall-clock autoscale tests flake by construction — pressure depends on
when the poll landed relative to the flush cadence. This harness makes
the whole loop a pure function of the script: the DRIVER TICK COUNTER
is the clock (1 tick = 1 virtual second for the policy's cooldown
arithmetic), arrivals fire at scripted ticks, the controller polls
every ``poll_every_ticks`` ticks, and the load signal is read from the
same flushed metrics files production reads — so the smoke/test
exercises the real signal path, the real policy, and the real
`ServeDriver` seams with zero sleeps and zero wall-clock sensitivity
(tests/test_autoscale.py, ``autoscale --smoke``).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

__all__ = ["ScriptedLoad", "run_scripted"]


@dataclasses.dataclass
class ScriptedLoad:
    """``arrivals[tick]`` = requests submitted at that virtual tick."""

    arrivals: Dict[int, Sequence]
    #: keep ticking (and polling) this many ticks after the last
    #: stream drains — the idle phase a scale-down needs to observe
    idle_ticks_after_drain: int = 48

    def last_arrival_tick(self) -> int:
        return max(self.arrivals) if self.arrivals else 0


def run_scripted(driver, controller, load: ScriptedLoad,
                 poll_every_ticks: int = 2,
                 max_ticks: int = 5000) -> dict:
    """Drive one scripted serving session to completion. The driver
    session must be `start()`ed. Returns
    ``{"ticks", "drained_at", "entries"}`` where ``entries`` is every
    controller ledger entry in order."""
    entries: List[dict] = []
    drained_at: Optional[int] = None
    last_arrival = load.last_arrival_tick()
    tick = 0
    while tick < max_ticks:
        for req in load.arrivals.get(tick, ()):
            driver.submit(req)
        driver.tick()
        if tick % poll_every_ticks == 0:
            entries.append(controller.step(now=float(tick)))
        if tick >= last_arrival and not driver.busy():
            if drained_at is None:
                drained_at = tick
            if tick - drained_at >= load.idle_ticks_after_drain:
                break
        else:
            drained_at = None
        tick += 1
    return {"ticks": tick, "drained_at": drained_at,
            "entries": entries}
