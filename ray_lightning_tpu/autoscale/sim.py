"""Deterministic scripted-load harness — a thin back-compat shim over
`ray_lightning_tpu.loadgen` (the trace-driven load harness that
generalized this module; docs/SERVING.md "traffic & SLO classes").

The virtual-clock drive loop now lives in `loadgen.runner.run_trace`:
the DRIVER TICK COUNTER is the clock (1 tick = 1 virtual second for
the policy's cooldown arithmetic), arrivals fire at scripted ticks,
the controller polls every ``poll_every_ticks`` ticks, and the load
signal is read from the same flushed metrics files production reads —
so the smoke/test exercises the real signal path, the real policy,
and the real `ServeDriver` seams with zero sleeps and zero wall-clock
sensitivity (tests/test_autoscale.py, ``autoscale --smoke``).
`ScriptedLoad` keeps its historical API and gains ``to_events()``, so
any scripted schedule can be persisted as a versioned loadgen trace
and replayed bitwise.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

__all__ = ["ScriptedLoad", "run_scripted"]


@dataclasses.dataclass
class ScriptedLoad:
    """``arrivals[tick]`` = requests submitted at that virtual tick."""

    arrivals: Dict[int, Sequence]
    #: keep ticking (and polling) this many ticks after the last
    #: stream drains — the idle phase a scale-down needs to observe
    idle_ticks_after_drain: int = 48

    def last_arrival_tick(self) -> int:
        return max(self.arrivals) if self.arrivals else 0

    def to_events(self) -> List:
        """Lift the schedule into loadgen trace events — write them
        with `loadgen.trace.write_trace` for a replayable artifact."""
        from ray_lightning_tpu.loadgen.trace import events_from_arrivals

        return events_from_arrivals(self.arrivals)


def run_scripted(driver, controller, load: ScriptedLoad,
                 poll_every_ticks: int = 2,
                 max_ticks: int = 5000) -> dict:
    """Drive one scripted serving session to completion. The driver
    session must be `start()`ed. Returns
    ``{"ticks", "drained_at", "entries"}`` where ``entries`` is every
    controller ledger entry in order."""
    from ray_lightning_tpu.loadgen.runner import run_trace

    out = run_trace(
        driver, load.arrivals, controller=controller,
        poll_every_ticks=poll_every_ticks,
        idle_ticks_after_drain=load.idle_ticks_after_drain,
        max_ticks=max_ticks)
    return {"ticks": out["ticks"], "drained_at": out["drained_at"],
            "entries": out["entries"]}
