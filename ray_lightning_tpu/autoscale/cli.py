"""``python -m ray_lightning_tpu autoscale`` — the closed-loop serving
autoscaler demo + the format.sh smoke gate.

    python -m ray_lightning_tpu autoscale            # scripted demo
    python -m ray_lightning_tpu autoscale --smoke    # the gate

``--smoke`` (docs/AUTOSCALE.md "acceptance") runs four CPU legs —
three on the deterministic scripted-load harness (`autoscale/sim.py` —
the driver tick counter is the clock, so nothing there is wall-clock
sensitive) plus a process-backend ramp — and exits 1 unless ALL hold:

  * **ramp leg** — under a scripted load ramp the controller scales
    1 -> 2 on sustained pressure and back to 1 on idle, exactly once
    each (cooldowns + hysteresis honored: many polls, two scale
    events); every decision lands in ``autoscale.jsonl`` with its
    signal snapshot; and every stream completes **bitwise-identical**
    to independent single-stream `generate()` runs — a graceful drain
    drops zero streams and corrupts none;
  * **drill leg** — a capacity-oracle probe file at 1 world CLAMPS the
    wanted scale-up (ledger records the clamp + the oracle's answer);
    capacity returns (file -> 2) and the spawn is hit by an injected
    SIGKILL-class `WorkerError` mid-scale-up: the controller
    classifies it via `resilience.policy`, retries within budget, and
    lands the target — absorbed without dropping it;
  * **deferral leg** — with every replica draining, `submit()` defers
    with a structured reason (driver ``submit_deferrals`` counter)
    instead of round-robining onto a stopping replica, and the
    deferred stream completes bitwise once a replica is live again;
  * **process-ramp leg** — the same 1 -> 2 -> 1 ramp against REAL
    worker processes, every command flowing over the request channel
    (serve/channel.py): `add_replica` spawns a process replica
    mid-session, `remove_replica(graceful=True)` drains it over the
    channel, an injected mid-stream SIGKILL is classified and absorbed
    by the channel-epoch respawn replay, and every stream still lands
    bitwise — the leg that retired the old "dynamic sessions are
    inline-backend only" limit (docs/SERVING.md "request channel").
"""
from __future__ import annotations

import json
import os
import sys
import tempfile


def add_autoscale_parser(sub) -> None:
    p = sub.add_parser(
        "autoscale",
        help="closed-loop serving autoscaler: scripted-load demo or "
             "the format.sh smoke gate (docs/AUTOSCALE.md)")
    p.add_argument("--smoke", action="store_true",
                   help="gate mode (see module docstring); exit 1 on "
                        "any failed leg")
    p.add_argument("--requests", type=int, default=12,
                   help="synthetic demo requests in the scripted ramp")
    p.add_argument("--max-new", type=int, default=8)
    p.add_argument("--max-replicas", type=int, default=2)
    p.add_argument("--json", action="store_true", dest="as_json",
                   default=False)


def _ramp_setup(n_requests: int, max_new: int):
    """Tiny model + requests + bitwise references — reuses the serve
    smoke's deterministic builder so the oracle is the same
    `generate()` the serving gate pins against."""
    from ray_lightning_tpu.serve.cli import _references, _tiny_setup
    from ray_lightning_tpu.serve.engine import EngineConfig

    ecfg = EngineConfig(capacity=4, block_size=4, blocks_per_slot=8,
                        prefill_chunk=4)
    cfg, model, params, prompts, reqs = _tiny_setup(n_requests, max_new)
    refs = _references(model, params, prompts, reqs)
    return cfg, params, ecfg, reqs, refs


def _ramp_policy(max_replicas: int = 2):
    from ray_lightning_tpu.autoscale.policy import PolicyConfig

    # cooldowns are in VIRTUAL seconds (driver ticks): 4 ticks after a
    # scale-up, 8 after any event before scaling down
    return PolicyConfig(min_replicas=1, max_replicas=max_replicas,
                        high_pressure=0.5, low_pressure=0.05,
                        idle_occupancy=0.25, sustain_polls=2,
                        up_cooldown_s=4.0, down_cooldown_s=8.0)


def _run_ramp(cfg, params, ecfg, reqs, run_dir: str,
              max_replicas: int = 2):
    from ray_lightning_tpu.autoscale import (
        AutoscaleController, ControllerConfig, ScriptedLoad,
        run_scripted,
    )
    from ray_lightning_tpu.serve.driver import (
        ReplicaGroupConfig, ServeDriver,
    )

    drv = ServeDriver(cfg, params, ReplicaGroupConfig(
        n_replicas=1, backend="inline", engine=ecfg, run_dir=run_dir,
        metrics_flush_every_n_ticks=2))
    drv.start()
    ctl = AutoscaleController(drv, ControllerConfig(
        policy=_ramp_policy(max_replicas), signal_window=8))
    third = max(1, len(reqs) // 3)
    load = ScriptedLoad(arrivals={
        0: reqs[:2 * third], 2: reqs[2 * third:2 * third + third // 2],
        4: reqs[2 * third + third // 2:]})
    sim = run_scripted(drv, ctl, load, poll_every_ticks=2)
    result = drv.stop()
    return drv, ctl, sim, result


def _check_streams(outputs, refs) -> list:
    import numpy as np

    return [rid for rid, ref in refs.items()
            if not np.array_equal(np.asarray(outputs.get(rid, [])),
                                  ref)]


def _scale_events(entries):
    return [e for e in entries
            if e["decision"]["action"] in ("scale_up", "scale_down")
            and e["outcome"].get("ok")]


def run_smoke(args) -> int:
    """The format.sh gate. Three deterministic CPU legs."""
    from ray_lightning_tpu.autoscale.controller import read_ledger

    verdict = {"legs": {}}
    failures = []
    cfg, params, ecfg, reqs, refs = _ramp_setup(args.requests,
                                                args.max_new)

    # ---- leg 1: the scripted ramp -------------------------------------
    with tempfile.TemporaryDirectory(prefix="rlt-autoscale-") as tmp:
        run_dir = os.path.join(tmp, "run")
        drv, ctl, sim, result = _run_ramp(cfg, params, ecfg, reqs,
                                          run_dir)
        ledger = read_ledger(run_dir)
        events = _scale_events(ledger)
        bad = _check_streams(result.outputs, refs)
        incomplete = [rid for rid, m in result.meta.items()
                      if m["finish_reason"] not in ("eos", "length")]
        leg = {
            "decisions": ctl.decisions,
            "ledger_lines": len(ledger),
            "scale_ups": ctl.scale_ups,
            "scale_downs": ctl.scale_downs,
            "final_replicas": result.stats["final_replicas"],
            "bitwise_mismatches": bad,
            "completed": len(result.meta),
            "compile_count": result.stats["compile_count"],
            "events": [{"now": e["now"],
                        "action": e["decision"]["action"],
                        "target": e["decision"]["target"]}
                       for e in events],
        }
        verdict["legs"]["ramp"] = leg
        if ctl.scale_ups != 1 or ctl.scale_downs != 1:
            failures.append(
                f"expected exactly one scale-up and one scale-down "
                f"under the ramp (cooldowns+hysteresis must stop "
                f"flapping), got {ctl.scale_ups} up / "
                f"{ctl.scale_downs} down over {ctl.decisions} polls")
        if result.stats["final_replicas"] != 1:
            failures.append(
                f"ramp must end back at 1 replica, ended at "
                f"{result.stats['final_replicas']}")
        if bad:
            failures.append(
                f"streams diverge from generate() across the "
                f"scale-up/drain: {bad}")
        if len(result.meta) != len(reqs) or incomplete:
            failures.append(
                f"dropped streams: {len(result.meta)}/{len(reqs)} "
                f"completed (incomplete: {incomplete})")
        if len(ledger) != ctl.decisions or not ledger:
            failures.append(
                f"ledger holds {len(ledger)} parseable lines for "
                f"{ctl.decisions} decisions — every decision must "
                "land")
        missing = [i for i, e in enumerate(ledger)
                   if not ("signal" in e and "decision" in e
                           and "outcome" in e and "duration_s" in e)]
        if missing:
            failures.append(
                f"ledger entries missing required fields at lines "
                f"{missing[:5]}")
        if len(events) >= 2:
            gap = events[1]["now"] - events[0]["now"]
            if gap < 8.0:  # the down-cooldown in virtual seconds
                failures.append(
                    f"scale events {gap:g} virtual seconds apart — "
                    "the down-cooldown (8) was not honored")

    # ---- leg 2: capacity clamp + SIGKILL-during-scale-up drill --------
    with tempfile.TemporaryDirectory(prefix="rlt-autoscale-") as tmp:
        verdict["legs"]["drill"] = _smoke_drill(
            failures, cfg, params, ecfg, os.path.join(tmp, "run"),
            os.path.join(tmp, "capacity"))

    # ---- leg 3: all-draining submit deferral --------------------------
    with tempfile.TemporaryDirectory(prefix="rlt-autoscale-") as tmp:
        verdict["legs"]["deferral"] = _smoke_deferral(
            failures, cfg, params, ecfg, reqs, refs,
            os.path.join(tmp, "run"))

    # ---- leg 4: process-backend ramp over the request channel ---------
    with tempfile.TemporaryDirectory(prefix="rlt-autoscale-") as tmp:
        verdict["legs"]["process_ramp"] = _smoke_process_ramp(
            failures, ecfg, os.path.join(tmp, "run"))

    verdict["ok"] = not failures
    if failures:
        verdict["failures"] = failures
    print(json.dumps(verdict))
    if failures:
        for f in failures:
            print(f"autoscale --smoke FAILED: {f}", file=sys.stderr)
        return 1
    return 0


def _smoke_drill(failures: list, cfg, params, ecfg, run_dir: str,
                 cap_file: str) -> dict:
    """Capacity clamp then SIGKILL-absorbing scale-up: the oracle file
    says 1 world -> the wanted scale-up HOLDS with the capacity clamp
    in the ledger; the file flips to 2 and the spawn dies with a
    SIGKILL-class WorkerError -> classified RETRYABLE, retried within
    budget, target landed."""
    from ray_lightning_tpu.autoscale import (
        AutoscaleController, CapacityOracle, ControllerConfig,
        PolicyConfig,
    )
    from ray_lightning_tpu.serve.driver import (
        ReplicaGroupConfig, ServeDriver,
    )

    with open(cap_file, "w") as f:
        f.write("1")
    drv = ServeDriver(cfg, params, ReplicaGroupConfig(
        n_replicas=1, backend="inline", engine=ecfg, run_dir=run_dir,
        metrics_flush_every_n_ticks=2))
    drv.start()
    # a fabricated sustained-high signal isolates the drill from the
    # ramp: this leg tests the ACTUATION path, not signal plumbing
    high = {"available": True, "pressure": 2.0, "queue_depth_now": 8.0,
            "queue_depth_p50": 8.0, "occupancy": 1.0, "total_slots": 4.0}
    ctl = AutoscaleController(
        drv,
        ControllerConfig(
            policy=PolicyConfig(min_replicas=1, max_replicas=2,
                                sustain_polls=1, up_cooldown_s=1.0),
            oracle=CapacityOracle(probe_file=cap_file),
            max_spawn_retries=2),
        run_dir=run_dir, signal_fn=lambda: dict(high))
    clamped = ctl.step(now=0.0)
    leg = {"clamped": clamped["decision"]}
    if not (clamped["decision"]["action"] == "hold"
            and "capacity" in clamped["decision"]["clamps"]):
        failures.append(
            f"capacity 1 did not clamp the scale-up: {clamped['decision']}")
    if clamped.get("capacity", {}).get("source") != "file":
        failures.append(
            "ledger entry is missing the capacity oracle's file answer")
    with open(cap_file, "w") as f:
        f.write(json.dumps({"capacity": 2}))
    drv.inject_spawn_faults(1, signal_name="SIGKILL")
    scaled = ctl.step(now=2.0)
    leg["scaled"] = {"decision": scaled["decision"],
                     "outcome": scaled["outcome"],
                     "n_live": drv.n_live}
    out = scaled["outcome"]
    if not (scaled["decision"]["action"] == "scale_up"
            and out.get("ok") and out.get("retries") == 1):
        failures.append(
            f"SIGKILL-during-scale-up was not absorbed by one "
            f"classified retry: {out}")
    if drv.n_live != 2:
        failures.append(
            f"scale target dropped after the spawn SIGKILL: "
            f"{drv.n_live} live replicas (want 2)")
    kinds = [f_["kind"] for f_ in out.get("failures", [])]
    if kinds != ["retryable"]:
        failures.append(
            f"spawn death classification not recorded as retryable: "
            f"{out.get('failures')}")
    drv.stop()
    return leg


def _smoke_deferral(failures: list, cfg, params, ecfg, reqs, refs,
                    run_dir: str) -> dict:
    """Every replica draining -> submit() defers with a structured
    reason and the metrics counter; once a replica is live again the
    deferred stream routes, completes, and matches generate()."""
    from ray_lightning_tpu.serve.driver import (
        ReplicaGroupConfig, ServeDriver,
    )

    drv = ServeDriver(cfg, params, ReplicaGroupConfig(
        n_replicas=1, backend="inline", engine=ecfg, run_dir=run_dir,
        metrics_flush_every_n_ticks=2))
    drv.start()
    drv.remove_replica(graceful=True)   # the only replica drains
    target = drv.submit(reqs[0])
    leg = {"deferred_target": target,
           "last_deferral": drv.last_deferral}
    if target is not None or drv.last_deferral is None:
        failures.append(
            "submit() with every replica draining routed onto a "
            f"stopping replica (target={target}) instead of deferring")
    counters = drv.driver_metrics.counters()
    leg["submit_deferrals"] = counters.get("submit_deferrals", 0)
    if counters.get("submit_deferrals", 0) != 1:
        failures.append(
            f"deferral counter reads "
            f"{counters.get('submit_deferrals', 0)}, want 1")
    drv.add_replica()
    result = drv.stop()   # drains: the deferred request must complete
    bad = _check_streams(result.outputs,
                         {reqs[0].rid: refs[reqs[0].rid]})
    leg["bitwise_mismatches"] = bad
    if bad:
        failures.append(
            f"deferred stream diverged after re-routing: {bad}")
    return leg


def _smoke_process_ramp(failures: list, ecfg, run_dir: str,
                        n_requests: int = 6, max_new: int = 8) -> dict:
    """The process-backend ramp: 1 -> 2 -> 1 REAL worker processes,
    every command flowing over the request channel (serve/channel.py),
    with an injected mid-stream SIGKILL absorbed by the classified
    respawn + channel-epoch replay. This is the leg that retired
    docs/AUTOSCALE.md's old "dynamic sessions are inline-backend only"
    limit: the same `add_replica`/`remove_replica(graceful=True)` seams
    the controller actuates, against spawned processes instead of
    inline engines. Scripted actuation (not policy polling) keeps the
    leg deterministic — the policy's signal loop is leg 1's job; this
    leg pins the ACTUATION seams the controller calls."""
    import numpy as np

    from ray_lightning_tpu.serve.cli import _references, _tiny_setup
    from ray_lightning_tpu.serve.driver import (
        ReplicaGroupConfig, ServeDriver, save_params_npz,
    )

    cfg, model, params, prompts, reqs = _tiny_setup(n_requests, max_new)
    refs = _references(model, params, prompts, reqs)
    ppath = os.path.join(run_dir, "params.npz")
    os.makedirs(run_dir, exist_ok=True)
    save_params_npz(params, ppath)
    drv = ServeDriver(cfg, ppath, ReplicaGroupConfig(
        n_replicas=1, backend="process", engine=ecfg,
        run_dir=run_dir, platform="cpu", cpu_devices_per_rank=1,
        max_restarts=2, metrics_flush_every_n_ticks=2))
    # the SIGKILL lands mid-stream on replica 0 after a few emitted
    # tokens: the session thread classifies the death (retryable /
    # worker-signal), respawns the replica on a fresh channel epoch,
    # and the replayed commands regenerate every stream bitwise
    drv.start(fault={"replica": 0, "kill_after_tokens": 6})
    half = max(1, len(reqs) // 2)
    for r in reqs[:half]:
        drv.submit(r)
    added = drv.add_replica()          # scale 1 -> 2, over the channel
    for r in reqs[half:]:
        drv.submit(r)
    import time as _time
    while drv.busy():
        drv.tick()
        _time.sleep(0.01)
    victim = drv.remove_replica(graceful=True)   # scale 2 -> 1: drain op
    result = drv.stop()
    bad = [rid for rid, ref in refs.items()
           if not np.array_equal(
               np.asarray(result.outputs.get(rid, [])), ref)]
    leg = {
        "added": added, "removed": victim,
        "replicas_spawned": result.stats["replicas_spawned"],
        "final_replicas": result.stats["final_replicas"],
        "restarts": {str(k): v for k, v in result.restarts.items()},
        "bitwise_mismatches": bad,
        "completed": len(result.meta),
    }
    if bad:
        failures.append(
            f"process-backend streams diverge from generate() across "
            f"the ramp + SIGKILL respawn: {bad}")
    if result.stats["replicas_spawned"] != 2:
        failures.append(
            f"process ramp spawned {result.stats['replicas_spawned']} "
            "replicas, want 2 (1 -> 2 via the channel)")
    if result.stats["final_replicas"] != 1:
        failures.append(
            f"process ramp must end back at 1 replica, ended at "
            f"{result.stats['final_replicas']}")
    if result.restarts.get(0, 0) < 1:
        failures.append(
            "the injected mid-stream SIGKILL was not absorbed by a "
            f"classified respawn (restarts: {result.restarts})")
    if len(result.meta) != len(reqs):
        failures.append(
            f"process ramp dropped streams: {len(result.meta)}/"
            f"{len(reqs)} completed")
    return leg


def _run_demo(args) -> int:
    cfg, params, ecfg, reqs, refs = _ramp_setup(args.requests,
                                                args.max_new)
    with tempfile.TemporaryDirectory(prefix="rlt-autoscale-") as tmp:
        run_dir = os.path.join(tmp, "run")
        drv, ctl, sim, result = _run_ramp(cfg, params, ecfg, reqs,
                                          run_dir,
                                          max_replicas=args.max_replicas)
        bad = _check_streams(result.outputs, refs)
        line = {
            "requests": len(reqs),
            "ticks": sim["ticks"],
            "decisions": ctl.decisions,
            "scale_ups": ctl.scale_ups,
            "scale_downs": ctl.scale_downs,
            "scale_up_s": round(max(ctl.scale_up_s), 4)
            if ctl.scale_up_s else None,
            "final_replicas": result.stats["final_replicas"],
            "decode_tokens_per_s": round(
                result.stats["decode_tokens_per_s"], 2),
            "bitwise_ok": not bad,
        }
    if args.as_json:
        print(json.dumps(line))
    else:
        print(f"autoscale demo: {line['requests']} requests over "
              f"{line['ticks']} ticks, {line['decisions']} decisions "
              f"-> {line['scale_ups']} up / {line['scale_downs']} "
              f"down, spawn {line['scale_up_s']}s, streams "
              f"{'bitwise-identical' if line['bitwise_ok'] else 'DIVERGED'}")
    return 0 if not bad else 1


def run_autoscale(args) -> int:
    if args.smoke:
        return run_smoke(args)
    return _run_demo(args)
