"""Closed-loop serving autoscale (docs/AUTOSCALE.md): a pure decision
core (`policy`), the capacity oracle shared with the elastic training
ladder (`capacity`), the actuator driving `ServeDriver` scaling seams
with an append-only decision ledger (`controller`), and the
deterministic scripted-load harness (`sim`)."""
from ray_lightning_tpu.autoscale.capacity import (
    CapacityAnswer,
    CapacityOracle,
    default_oracle,
    spawn_probe,
)
from ray_lightning_tpu.autoscale.controller import (
    AutoscaleController,
    ControllerConfig,
    read_ledger,
)
from ray_lightning_tpu.autoscale.policy import (
    Decision,
    PolicyConfig,
    PolicyState,
    decide,
)
from ray_lightning_tpu.autoscale.sim import ScriptedLoad, run_scripted

__all__ = [
    "AutoscaleController",
    "CapacityAnswer",
    "CapacityOracle",
    "ControllerConfig",
    "Decision",
    "PolicyConfig",
    "PolicyState",
    "ScriptedLoad",
    "decide",
    "default_oracle",
    "read_ledger",
    "run_scripted",
    "spawn_probe",
]
