"""The capacity oracle: ONE answer to "how many worlds can the runtime
schedule right now?", shared by the elastic training ladder
(`elastic/budget.py` grow-back) and the serving autoscale controller
(`autoscale/controller.py` capacity clamp).

Before this module the elastic budget's default was **assume
restored**: every relaunch pretended full capacity was back, so a
shrunk run would propose a grow into hosts that were still gone and
pay a failed relaunch to learn it. The oracle replaces that with real
sources, consulted in order:

  1. ``RLT_CAPACITY`` env — an integer world count. The operator's (or
     a scheduler hook's) direct override.
  2. a **probe file** (``probe_file=`` or ``RLT_CAPACITY_FILE``) —
     either a bare integer or JSON ``{"capacity": n}``. Re-read on
     every query: an external agent (cluster scheduler webhook,
     preemption-notice watcher, a test) keeps it current.
  3. the **WorkerGroup spawn probe** (when ``spawn_probe_world`` is
     set): actually spawn that many trivial workers through
     `runtime.WorkerGroup` and count what came up — the ground truth
     the runtime itself reports. Expensive (process spawn), so the
     verdict is cached for ``cache_ttl_s``.
  4. the caller's ``assume`` fallback — the old assume-restored
     answer, now LABELED (``source="assumed"``) so a consumer can
     record the honesty gap instead of mistaking an assumption for a
     measurement (the supervisor's reshard ledger does exactly that on
     a refused grow).

Answers carry their source; ``worlds=None`` means "no source answered
and no assumption was offered" — a consumer must treat that as
no-clamp / no-grow, never as zero.
"""
from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time
from typing import Dict, Optional

from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)

__all__ = ["CapacityAnswer", "CapacityOracle", "default_oracle",
           "spawn_probe", "ENV_CAPACITY", "ENV_CAPACITY_FILE"]

ENV_CAPACITY = "RLT_CAPACITY"
ENV_CAPACITY_FILE = "RLT_CAPACITY_FILE"


@dataclasses.dataclass(frozen=True)
class CapacityAnswer:
    """One oracle query's result. ``worlds`` is the schedulable world
    count (None = nothing answered); ``source`` names where it came
    from: env | file | spawn_probe | capacity_fn | assumed | none."""

    worlds: Optional[int]
    source: str
    detail: str = ""

    def to_dict(self) -> dict:
        d = {"worlds": self.worlds, "source": self.source}
        if self.detail:
            d["detail"] = self.detail
        return d


def _probe_main() -> int:
    """The spawn probe's worker body: prove the process scheduled and
    answered. Deliberately trivial — no jax import, no device touch —
    the probe measures schedulability, not device health."""
    return os.getpid()


def spawn_probe(world: int, timeout_s: float = 60.0,
                env: Optional[Dict[str, str]] = None,
                log_dir: Optional[str] = None) -> CapacityAnswer:
    """Ground-truth probe: spawn ``world`` trivial workers through
    `runtime.WorkerGroup` and report how many answered. A clean start +
    run means the runtime can schedule that world RIGHT NOW; any spawn
    failure reads as capacity 0 with the failure in ``detail`` (the
    caller's ladder then stays put rather than paying a doomed
    relaunch)."""
    from ray_lightning_tpu.runtime.group import WorkerGroup

    if log_dir is None:
        log_dir = os.path.join(tempfile.gettempdir(),
                               "rlt_capacity_probe")
    group = WorkerGroup(num_workers=world, env=dict(env or {}),
                        log_dir=log_dir, start_timeout=timeout_s)
    try:
        group.start()
        results = group.run(_probe_main, timeout=timeout_s)
        return CapacityAnswer(len(results), "spawn_probe",
                              f"{len(results)}/{world} workers answered")
    except Exception as exc:  # noqa: BLE001 — a failed probe IS the answer
        return CapacityAnswer(
            0, "spawn_probe",
            f"probe of {world} worlds failed: "
            f"{type(exc).__name__}: {str(exc)[:200]}")
    finally:
        group.shutdown()


def _read_probe_file(path: str) -> Optional[int]:
    """Bare int or JSON {"capacity": n}; None when absent/garbled (a
    missing file means the external agent has nothing to say — fall
    through, don't fail)."""
    try:
        with open(path) as f:
            text = f.read().strip()
    except OSError:
        return None
    if not text:
        return None
    try:
        return int(text)
    except ValueError:
        pass
    try:
        doc = json.loads(text)
        return int(doc["capacity"])
    except (json.JSONDecodeError, KeyError, TypeError, ValueError):
        log.warning("capacity probe file %s is neither an int nor "
                    "{\"capacity\": n} — ignoring it", path)
        return None


@dataclasses.dataclass
class CapacityOracle:
    """The configured source chain. See the module docstring for the
    resolution order; every field narrows or extends it."""

    #: explicit probe file (beats ENV RLT_CAPACITY_FILE when set)
    probe_file: Optional[str] = None
    #: world size the spawn-probe fallback proves (None = probe off —
    #: spawning a worker group as a policy-query side effect is an
    #: explicit opt-in)
    spawn_probe_world: Optional[int] = None
    spawn_timeout_s: float = 60.0
    spawn_env: Optional[Dict[str, str]] = None
    #: spawn-probe verdict cache (the env/file sources are cheap and
    #: always re-read)
    cache_ttl_s: float = 30.0
    _cached: Optional[CapacityAnswer] = dataclasses.field(
        default=None, repr=False)
    _cached_until: float = dataclasses.field(default=0.0, repr=False)

    def query(self, assume: Optional[int] = None) -> CapacityAnswer:
        """Resolve the chain. ``assume`` is the caller's labeled
        fallback (e.g. the elastic budget's resolved max) — returned
        with ``source="assumed"`` only when every real source passed."""
        raw = os.environ.get(ENV_CAPACITY)
        if raw is not None:
            try:
                return CapacityAnswer(max(0, int(raw)), "env",
                                      f"{ENV_CAPACITY}={raw}")
            except ValueError:
                log.warning("%s=%r is not an integer — ignoring the "
                            "override", ENV_CAPACITY, raw)
        path = self.probe_file or os.environ.get(ENV_CAPACITY_FILE)
        if path:
            worlds = _read_probe_file(path)
            if worlds is not None:
                return CapacityAnswer(max(0, worlds), "file", path)
        if self.spawn_probe_world:
            now = time.monotonic()
            if self._cached is None or now >= self._cached_until:
                self._cached = spawn_probe(
                    self.spawn_probe_world,
                    timeout_s=self.spawn_timeout_s, env=self.spawn_env)
                self._cached_until = now + self.cache_ttl_s
            return self._cached
        if assume is not None:
            return CapacityAnswer(
                assume, "assumed",
                "no capacity source answered; assuming the resolved "
                "max — configure RLT_CAPACITY / a probe file / the "
                "spawn probe for a measured answer")
        return CapacityAnswer(None, "none", "no capacity source configured")

    def capacity(self, assume: Optional[int] = None) -> Optional[int]:
        return self.query(assume=assume).worlds

    def capacity_fn(self, assume: Optional[int] = None):
        """A `() -> int` adapter for `ElasticBudget.capacity_fn`-shaped
        consumers that cannot carry the answer metadata."""
        def fn() -> int:
            worlds = self.capacity(assume=assume)
            return worlds if worlds is not None else 0
        return fn


_DEFAULT: Optional[CapacityOracle] = None


def default_oracle() -> CapacityOracle:
    """The process-wide shared oracle (env + probe-file sources, spawn
    probe off): the one capacity truth `ElasticBudget` and the serving
    controller consult unless handed a configured instance."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = CapacityOracle()
    return _DEFAULT
