"""The autoscale actuator: poll the load signal, run the policy core,
execute the decision through the `ServeDriver` scaling seams, and
write every decision — acted or held — to an append-only ledger.

The split is deliberate (docs/AUTOSCALE.md): `policy.decide` is pure
and clockless; THIS module owns every side effect — reading
`serve.driver.load_signal`, querying the capacity oracle, calling
`driver.add_replica()` / `driver.remove_replica(graceful=True)`,
classifying a failed spawn via `resilience.policy.classify_failure`
and retrying within ``max_spawn_retries``, and appending to
``<run_dir>/autoscale.jsonl``.

Ledger contract (one JSON object per line, append-only):

    {"decision_index": k, "now": t, "signal": {...}, "capacity": {...},
     "decision": {"action", "target", "delta", "reason", "clamps"},
     "outcome": {"ok", "added"/"removed", "retries", "failures"},
     "replicas": live-after, "duration_s": actuation wall}

``signal`` is the snapshot the decision was made FROM (so a verdict is
auditable against its input), ``capacity`` the oracle's answer with
its source. Scale events additionally land as driver flight-recorder
events and driver metrics counters, and `report`/`monitor --serve`
render the ledger (docs/OBSERVABILITY.md).

A failed scale-up never drops the target: `PolicyState.applied` is
only called after the seam succeeded, so the sustained-pressure streak
survives and the next poll re-proposes the same target — the SIGKILL
drill's contract.
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable, List, Optional

from ray_lightning_tpu.autoscale.capacity import CapacityOracle
from ray_lightning_tpu.autoscale.policy import (
    HOLD, SCALE_DOWN, SCALE_UP, Decision, PolicyConfig, PolicyState,
    decide,
)
from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)

__all__ = ["ControllerConfig", "AutoscaleController", "LEDGER_NAME",
           "read_ledger"]

LEDGER_NAME = "autoscale.jsonl"
LEDGER_VERSION = "rlt-autoscale-v1"


@dataclasses.dataclass
class ControllerConfig:
    """The actuator's knobs — the policy's live in `PolicyConfig`."""

    policy: PolicyConfig = dataclasses.field(default_factory=PolicyConfig)
    #: capacity oracle (None = the process-wide default: env + probe
    #: file, spawn probe off). The SAME oracle type the elastic budget
    #: ladder consults — one capacity truth (docs/AUTOSCALE.md).
    oracle: Optional[CapacityOracle] = None
    #: how many recent tick samples per replica the signal summarizes
    #: — small windows react faster, large ones smooth bursts
    signal_window: int = 16
    #: failed spawns retried per scale-up attempt when
    #: `resilience.policy` classifies the death restartable
    max_spawn_retries: int = 2
    #: wall-clock poll cadence for `run_wall` (the scripted harness
    #: ignores this — it polls on virtual ticks)
    poll_every_s: float = 5.0
    #: SLO watch (telemetry/watch.py, docs/OBSERVABILITY.md): True (or
    #: a WatchConfig) evaluates the declarative rules on every poll —
    #: the controller's cadence IS the watch cadence for a serving
    #: session — with breaches landing in <run_dir>/incidents.jsonl
    #: carrying the forced-flight-persist evidence capture. None: off.
    watch: Any = None


def read_ledger(run_dir: str,
                tail_bytes: Optional[int] = None) -> List[dict]:
    """Parse ``<run_dir>/autoscale.jsonl`` (missing file = no
    decisions = []); unparseable lines are skipped, never fatal — a
    killed controller must still leave a readable ledger prefix. The
    clock-alignment header line is NOT an entry (the timeline adapter
    reads it for the wall-axis placement). ``tail_bytes`` bounds the
    read for cadence-polled callers (RLT503)."""
    from ray_lightning_tpu.telemetry.spans import ledger_tail_lines

    path = os.path.join(run_dir, LEDGER_NAME)
    out: List[dict] = []
    try:
        first, body = ledger_tail_lines(path, tail_bytes)
    except OSError:
        return out
    for line in [first] + body:
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(obj, dict):
            continue
        if "version" in obj and "decision" not in obj:
            continue  # the clock-alignment header
        out.append(obj)
    return out


def _signal_snapshot(signal: dict) -> dict:
    """The compact per-decision signal record — the fields the policy
    read, not the full per-replica breakdown."""
    keys = ("available", "reason", "queue_depth_now", "queue_depth_p50",
            "queue_depth_max", "occupancy", "pressure", "total_slots",
            "blocks_free_fraction", "replicas_reporting",
            "replicas_retired", "window_ticks")
    snap = {k: signal[k] for k in keys if k in signal}
    # per-traffic-class fields (pressure_<class> / queue_depth_now_<cls>
    # / sheds_<class>) are flat and policy-readable — keep them in the
    # ledger so a class-targeted decision stays auditable
    snap.update({k: v for k, v in signal.items()
                 if k.startswith(("pressure_", "queue_depth_now_",
                                  "sheds_"))})
    return snap


class AutoscaleController:
    """One closed control loop over one `ServeDriver` session.

    ``signal_fn`` defaults to `serve.driver.load_signal(run_dir,
    window)` — the scripted-load harness and unit tests may inject
    their own. ``clock`` only feeds the policy's cooldown arithmetic;
    pass ``now=`` to `step()` for a fully virtual clock (the smoke
    drives it with the driver's tick counter: deterministic, no
    wall-clock flakiness).
    """

    def __init__(self, driver, cfg: Optional[ControllerConfig] = None,
                 run_dir: Optional[str] = None,
                 signal_fn: Optional[Callable[[], dict]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.driver = driver
        self.cfg = cfg or ControllerConfig()
        self.run_dir = run_dir if run_dir is not None \
            else driver.cfg.run_dir
        self._clock = clock
        self._signal_fn = signal_fn
        self.state = PolicyState(replicas=driver.n_live)
        self.decisions = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.spawn_retries = 0
        self.scale_up_s: List[float] = []
        self.ledger_path = (os.path.join(self.run_dir, LEDGER_NAME)
                            if self.run_dir else None)
        #: clock-alignment pair stamped into the ledger header: every
        #: entry's "t" is a perf_counter offset from t0_perf, so the
        #: timeline merger places decisions on the shared wall axis
        #: even when the POLICY clock is virtual (the scripted smoke)
        self._t0_wall = time.time()
        self._t0_perf = time.perf_counter()
        self.watch = None
        if self.cfg.watch and self.run_dir is not None:
            from ray_lightning_tpu.telemetry.watch import (
                WatchConfig, WatchEngine,
            )

            self.watch = WatchEngine(
                self.run_dir, WatchConfig.coerce(self.cfg.watch),
                driver=driver)

    # ---- inputs ----------------------------------------------------------

    def _signal(self) -> dict:
        if self._signal_fn is not None:
            return self._signal_fn()
        if self.run_dir is None:
            return {"available": False,
                    "reason": "controller has no run_dir and no "
                              "signal_fn"}
        from ray_lightning_tpu.serve.driver import load_signal

        return load_signal(self.run_dir, window=self.cfg.signal_window)

    def _capacity(self):
        if self.cfg.oracle is None:
            return None
        return self.cfg.oracle.query()

    # ---- the loop --------------------------------------------------------

    def step(self, now: Optional[float] = None) -> dict:
        """One control iteration: signal -> oracle -> decide -> actuate
        -> ledger. Returns the ledger entry."""
        if now is None:
            now = self._clock()
        t0 = time.perf_counter()
        signal = self._signal()
        answer = self._capacity()
        # resync to the ACTUAL replica count: a spawn that failed last
        # poll, or an operator's manual remove, must not leave the
        # policy reasoning about replicas that do not exist
        self.state.replicas = self.driver.n_live
        decision = decide(
            self.cfg.policy, self.state, signal, now,
            capacity=answer.worlds if answer is not None else None)
        outcome = self._actuate(decision, now)
        entry = {
            "decision_index": self.decisions,
            "now": now,
            # "t" is the REAL monotonic offset from the ledger header's
            # t0_perf — "now" may be a virtual policy clock, and the
            # timeline merge must never have to guess this ledger's
            # epoch from it
            "t": round(time.perf_counter() - self._t0_perf, 6),
            "signal": _signal_snapshot(signal or {}),
            "decision": decision.to_dict(),
            "outcome": outcome,
            "replicas": self.driver.n_live,
            "duration_s": round(time.perf_counter() - t0, 6),
        }
        if answer is not None:
            entry["capacity"] = answer.to_dict()
        self.decisions += 1
        self._append_ledger(entry)
        dm = self.driver.driver_metrics
        if dm is not None and dm.enabled:
            dm.count("autoscale_decisions")
            if decision.action == SCALE_UP and outcome.get("ok"):
                dm.count("autoscale_scale_ups")
            elif decision.action == SCALE_DOWN and outcome.get("ok"):
                dm.count("autoscale_scale_downs")
        fl = self.driver.driver_flight
        if fl is not None and fl.enabled and decision.action != HOLD:
            fl.record("autoscale", action=decision.action,
                      target=decision.target, ok=outcome.get("ok"),
                      reason=decision.reason[:120])
        if self.watch is not None:
            # the controller's poll cadence doubles as the watch
            # cadence: pure tail-bounded reads over already-persisted
            # ledgers, breaches land in <run_dir>/incidents.jsonl
            self.watch.poll(driver=self.driver)
        return entry

    def run_wall(self, max_duration_s: float,
                 stop_when_idle: bool = True) -> List[dict]:
        """Wall-clock mode: poll every ``cfg.poll_every_s`` while the
        driver session serves (production shape; the smoke uses the
        scripted virtual-tick harness instead)."""
        entries = []
        t_end = time.monotonic() + max_duration_s
        while time.monotonic() < t_end:
            entries.append(self.step())
            if stop_when_idle and not self.driver.busy():
                break
            time.sleep(self.cfg.poll_every_s)
        return entries

    # ---- actuation -------------------------------------------------------

    def _actuate(self, decision: Decision, now: float) -> dict:
        if decision.action == HOLD:
            return {"ok": True, "action": HOLD}
        if decision.action == SCALE_UP:
            return self._scale_up(decision, now)
        return self._scale_down(decision, now)

    def _scale_up(self, decision: Decision, now: float) -> dict:
        from ray_lightning_tpu.resilience.policy import classify_failure

        added: List[int] = []
        failures: List[dict] = []
        retries = 0
        aborted = False
        t0 = time.perf_counter()
        for _ in range(decision.delta):
            if aborted:
                # a FATAL classification or an exhausted retry budget
                # ends the WHOLE scale-up: the next replica would walk
                # the same broken spawn path (e.g. a corrupt params
                # npz fails identically every time — review finding)
                break
            while True:
                try:
                    added.append(self.driver.add_replica())
                    break
                except Exception as exc:  # noqa: BLE001 — classified below
                    fc = classify_failure(exc)
                    failures.append({"kind": fc.kind, "cause": fc.cause,
                                     "detail": fc.detail[:200]})
                    log.warning(
                        "autoscale: replica spawn died (%s/%s): %s",
                        fc.kind, fc.cause, fc.detail)
                    if not fc.restartable or \
                            retries >= self.cfg.max_spawn_retries:
                        aborted = True
                        break
                    retries += 1
                    self.spawn_retries += 1
        dur = time.perf_counter() - t0
        ok = len(added) == decision.delta
        if added:
            self.scale_up_s.append(dur)
        if ok:
            self.state.applied(decision, now)
            self.scale_ups += 1
        # partial success (some replicas spawned, the last one's budget
        # ran out): commit what exists, cooldown included — capacity
        # DID arrive, and the next judgment should wait for the signal
        # to absorb it. Under still-sustained pressure the remaining
        # delta is re-proposed once the cooldown expires (only a
        # ZERO-progress scale-up skips applied() and re-proposes at
        # the very next poll).
        elif added:
            self.state.applied(
                dataclasses.replace(decision,
                                    target=self.driver.n_live,
                                    delta=len(added)), now)
            self.scale_ups += 1
        out = {"ok": ok, "action": SCALE_UP, "added": added,
               "retries": retries, "duration_s": round(dur, 4)}
        if failures:
            out["failures"] = failures
        return out

    def _scale_down(self, decision: Decision, now: float) -> dict:
        removed: List[int] = []
        errors: List[str] = []
        t0 = time.perf_counter()
        for _ in range(-decision.delta):
            try:
                removed.append(self.driver.remove_replica(graceful=True))
            except Exception as exc:  # noqa: BLE001 — surfaced in ledger
                errors.append(f"{type(exc).__name__}: {str(exc)[:200]}")
                break
        ok = len(removed) == -decision.delta
        if removed:
            self.state.applied(
                decision if ok else dataclasses.replace(
                    decision, target=self.driver.n_live,
                    delta=-len(removed)), now)
            self.scale_downs += 1
        out = {"ok": ok, "action": SCALE_DOWN, "removed": removed,
               "duration_s": round(time.perf_counter() - t0, 4)}
        if errors:
            out["errors"] = errors
        return out

    # ---- ledger ----------------------------------------------------------

    def _append_ledger(self, entry: dict) -> None:
        if self.ledger_path is None:
            return
        os.makedirs(os.path.dirname(self.ledger_path), exist_ok=True)
        with open(self.ledger_path, "a") as f:
            if f.tell() == 0:
                # clock-alignment header (docs/OBSERVABILITY.md
                # "unified timeline"): the same t0_wall/monotonic pair
                # spans/metrics files carry, so the timeline merger
                # never guesses this ledger's epoch
                f.write(json.dumps({
                    "version": LEDGER_VERSION,
                    "t0_wall": self._t0_wall,
                    "t0_perf": self._t0_perf,
                    "pid": os.getpid(),
                }) + "\n")
            f.write(json.dumps(entry) + "\n")
