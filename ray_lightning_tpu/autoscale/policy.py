"""The autoscale decision core: pure, deterministic, I/O-free.

One function — `decide()` — turns a load-signal snapshot
(`serve.driver.load_signal`, docs/OBSERVABILITY.md "load signal") plus
the controller's own memory (`PolicyState`) into a `Decision`. No file
reads, no clock reads, no jax: ``now`` is an argument, so the whole
decision table is unit-testable tick for tick (the scripted-load smoke
drives it with a virtual clock — tests/test_autoscale.py).

The policy is a **target-pressure band with hysteresis**:

  * ``pressure`` (queue_depth_p50 / total_slots) at or above
    ``high_pressure`` for ``sustain_polls`` CONSECUTIVE polls asks for
    ``+max_step`` replicas — one blip never scales;
  * pressure at or below ``low_pressure`` with an EMPTY queue and idle
    occupancy for ``sustain_polls`` polls asks for ``-max_step``;
  * anything in between holds and RESETS both streaks (the hysteresis:
    flapping load keeps resetting the counters and never flaps the
    replica count — test-pinned).

Every proposal then passes the clamps, in order: the scale-direction
**cooldown** (a fresh scale event suppresses the next one in either
direction — the signal lags actuation by a flush cadence, so acting on
the pre-scale signal would double-apply), the ``min_replicas`` /
``max_replicas`` bounds, and the **capacity clamp** (the oracle's
schedulable-world count, `autoscale/capacity.py` — wanting a replica
the runtime cannot schedule is a ledger entry, not a spawn loop). A
clamp that nullifies the step returns a ``hold`` naming the clamp, so
the ledger always says WHY nothing happened.

Streaks survive a cooldown/clamp hold (the moment the cooldown
expires, the sustained signal acts); they reset only on an in-band
signal, a missing signal, or an applied decision
(`PolicyState.applied`).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

__all__ = ["PolicyConfig", "PolicyState", "Decision", "decide",
           "HOLD", "SCALE_UP", "SCALE_DOWN"]

HOLD = "hold"
SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"


@dataclasses.dataclass(frozen=True)
class PolicyConfig:
    """The band, the hysteresis, and the clamps. All thresholds are
    dimensionless or in the controller's clock units (wall seconds in
    production, virtual ticks under the scripted-load harness)."""

    min_replicas: int = 1
    max_replicas: int = 4
    #: pressure >= this, sustained, scales up. pressure is
    #: queue_depth_p50 / total_slots: 0.5 means half a slot-set's worth
    #: of requests is queuing behind capacity at the median tick.
    high_pressure: float = 0.5
    #: pressure <= this (AND queue empty AND idle occupancy),
    #: sustained, scales down
    low_pressure: float = 0.05
    #: scale-down additionally requires mean occupancy at or below
    #: this — a deep queue can drain to zero while every slot still
    #: decodes; reclaiming a replica then would immediately re-queue
    idle_occupancy: float = 0.5
    #: consecutive polls a signal must sustain before acting
    sustain_polls: int = 2
    #: clock units a scale-UP suppresses further scaling
    up_cooldown_s: float = 30.0
    #: clock units a scale-DOWN suppresses further scaling (longer by
    #: default: spawning is cheap to undo, draining is not)
    down_cooldown_s: float = 60.0
    #: replicas added/removed per decision
    max_step: int = 1
    #: traffic class whose flat per-class signal fields
    #: (``pressure_<class>`` / ``queue_depth_now_<class>``, emitted
    #: when the scheduler runs with an `SLOConfig`) drive the band
    #: instead of the pooled signal — e.g. "latency_critical" reacts
    #: to paying-class pressure while a shed best_effort backlog
    #: queues. Falls back to the pooled fields when the signal carries
    #: no per-class data (priority-off run). None = pooled (historical)
    pressure_class: Optional[str] = None

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas ({self.min_replicas}) <= "
                f"max_replicas ({self.max_replicas})")
        if self.low_pressure > self.high_pressure:
            raise ValueError(
                f"low_pressure {self.low_pressure} above high_pressure "
                f"{self.high_pressure} — the band is inverted")
        if self.sustain_polls < 1:
            raise ValueError("sustain_polls must be >= 1")
        if self.max_step < 1:
            raise ValueError("max_step must be >= 1")


@dataclasses.dataclass
class PolicyState:
    """What the policy remembers between polls. The controller owns
    one; tests build them directly for the decision-table matrix."""

    replicas: int
    high_streak: int = 0
    low_streak: int = 0
    last_scale_up_t: Optional[float] = None
    last_scale_down_t: Optional[float] = None

    def applied(self, decision: "Decision", now: float) -> None:
        """Commit an ACTUATED decision: stamp the cooldown, adopt the
        target, reset the streaks. The controller calls this only after
        the driver seam succeeded — a failed spawn leaves the streaks
        high, so the sustained demand re-proposes the same target at
        the next poll instead of being forgotten (the SIGKILL drill's
        'never drops the scale target' contract)."""
        if decision.action == SCALE_UP:
            self.last_scale_up_t = now
        elif decision.action == SCALE_DOWN:
            self.last_scale_down_t = now
        if decision.action != HOLD:
            self.replicas = decision.target
            self.high_streak = 0
            self.low_streak = 0

    def last_scale_t(self) -> Optional[float]:
        stamps = [t for t in (self.last_scale_up_t,
                              self.last_scale_down_t) if t is not None]
        return max(stamps) if stamps else None


@dataclasses.dataclass(frozen=True)
class Decision:
    """One poll's verdict — exactly what lands in the ledger."""

    action: str                  # "scale_up" | "scale_down" | "hold"
    target: int                  # replica count after the action
    delta: int                   # target - current (0 for hold)
    reason: str                  # human-readable why
    clamps: Tuple[str, ...] = () # which clamps shaped/nullified it

    def to_dict(self) -> dict:
        return {"action": self.action, "target": self.target,
                "delta": self.delta, "reason": self.reason,
                "clamps": list(self.clamps)}


def _pressure(signal: dict,
              pressure_class: Optional[str] = None
              ) -> Tuple[float, float, float]:
    """(pressure, queue_depth_now, occupancy) with honest fallbacks: a
    None pressure means no slots reported — queued demand with zero
    slots is INFINITE pressure, an empty queue with zero slots is
    zero. ``pressure_class`` narrows pressure/queue-depth to that
    traffic class's flat fields when the signal carries them."""
    p_key, qd_key = "pressure", "queue_depth_now"
    if (pressure_class is not None
            and f"pressure_{pressure_class}" in signal):
        p_key = f"pressure_{pressure_class}"
        qd_key = f"queue_depth_now_{pressure_class}"
    qd_now = float(signal.get(qd_key) or 0.0)
    occ = float(signal.get("occupancy") or 0.0)
    p = signal.get(p_key)
    if p is None:
        p = math.inf if qd_now > 0 else 0.0
    return float(p), qd_now, occ


def decide(cfg: PolicyConfig, state: PolicyState, signal: Optional[dict],
           now: float, capacity: Optional[int] = None) -> Decision:
    """One poll of the decision core. Mutates ``state``'s streaks (that
    IS the hysteresis memory); cooldown stamps and the replica count
    are only committed by `PolicyState.applied` after actuation.

    ``capacity`` is the oracle's schedulable-world count (None = no
    oracle answer = no clamp). Deterministic: same (state, signal, now,
    capacity) -> same decision.
    """
    n = state.replicas
    if n < cfg.min_replicas:
        # the floor is correctness, not a demand response: a replica
        # set driven below min (operator removal, an aborted scale-up
        # after deaths) must be restored regardless of signal — with
        # 0 live replicas every metrics stream is retired, the signal
        # reads unavailable, and no demand branch could ever fire
        # (review finding, test-pinned). No cooldown either: waiting
        # out a cooldown to reach the configured minimum serves no
        # one. Only the capacity clamp still applies.
        target = cfg.min_replicas
        clamps = ["min_replicas"]
        if capacity is not None and target > capacity:
            target = max(capacity, n)
            clamps.append("capacity")
        if target <= n:
            return Decision(
                HOLD, n, 0,
                f"below the min_replicas floor ({n} < "
                f"{cfg.min_replicas}) but capacity {capacity} holds "
                "the target", tuple(clamps))
        return Decision(
            SCALE_UP, target, target - n,
            f"below the min_replicas floor ({n} < "
            f"{cfg.min_replicas}) — restoring it regardless of "
            "signal", tuple(clamps))
    if not signal or not signal.get("available"):
        # no signal is NOT zero load (load_signal's documented
        # contract) — never scale on ignorance
        state.high_streak = 0
        state.low_streak = 0
        return Decision(HOLD, n, 0,
                        "no load signal (metrics not flushed yet, or "
                        "nothing served)", ("no_signal",))
    p, qd_now, occ = _pressure(signal, cfg.pressure_class)

    if p >= cfg.high_pressure:
        state.high_streak += 1
        state.low_streak = 0
        if state.high_streak < cfg.sustain_polls:
            return Decision(
                HOLD, n, 0,
                f"pressure {p:.3f} >= {cfg.high_pressure} sustained "
                f"{state.high_streak}/{cfg.sustain_polls} polls",
                ("hysteresis",))
        up_stamp = state.last_scale_t()
        if (up_stamp is not None
                and now - up_stamp < cfg.up_cooldown_s):
            return Decision(
                HOLD, n, 0,
                f"pressure {p:.3f} sustained but scale event at "
                f"t={up_stamp:g} is within the {cfg.up_cooldown_s:g} "
                f"up-cooldown (now {now:g})", ("up_cooldown",))
        clamps = []
        target = n + cfg.max_step
        if target > cfg.max_replicas:
            target = cfg.max_replicas
            clamps.append("max_replicas")
        if capacity is not None and target > capacity:
            target = max(capacity, cfg.min_replicas)
            clamps.append("capacity")
        if target <= n:
            return Decision(
                HOLD, n, 0,
                f"pressure {p:.3f} sustained but "
                f"{' + '.join(clamps) or 'clamps'} hold the target at "
                f"{n}", tuple(clamps) or ("max_replicas",))
        return Decision(
            SCALE_UP, target, target - n,
            f"pressure {p:.3f} >= {cfg.high_pressure} for "
            f"{state.high_streak} polls (queue_now {qd_now:g}, "
            f"occupancy {occ:.2f})", tuple(clamps))

    if p <= cfg.low_pressure and qd_now <= 0 and occ <= cfg.idle_occupancy:
        state.low_streak += 1
        state.high_streak = 0
        if state.low_streak < cfg.sustain_polls:
            return Decision(
                HOLD, n, 0,
                f"idle (pressure {p:.3f}, occupancy {occ:.2f}) "
                f"sustained {state.low_streak}/{cfg.sustain_polls} "
                "polls", ("hysteresis",))
        down_stamp = state.last_scale_t()
        if (down_stamp is not None
                and now - down_stamp < cfg.down_cooldown_s):
            return Decision(
                HOLD, n, 0,
                f"idle sustained but scale event at t={down_stamp:g} "
                f"is within the {cfg.down_cooldown_s:g} down-cooldown "
                f"(now {now:g})", ("down_cooldown",))
        target = max(n - cfg.max_step, cfg.min_replicas)
        if target >= n:
            return Decision(
                HOLD, n, 0,
                f"idle sustained but already at min_replicas "
                f"{cfg.min_replicas}", ("min_replicas",))
        return Decision(
            SCALE_DOWN, target, target - n,
            f"pressure {p:.3f} <= {cfg.low_pressure}, queue empty, "
            f"occupancy {occ:.2f} <= {cfg.idle_occupancy} for "
            f"{state.low_streak} polls",
            ("min_replicas",) if target == cfg.min_replicas
            and n - cfg.max_step < cfg.min_replicas else ())

    # in-band: the hysteresis reset — flapping load lands here between
    # excursions and never accumulates a streak
    state.high_streak = 0
    state.low_streak = 0
    return Decision(
        HOLD, n, 0,
        f"pressure {p:.3f} within band ({cfg.low_pressure}, "
        f"{cfg.high_pressure}) — or busy slots hold the floor", ())
