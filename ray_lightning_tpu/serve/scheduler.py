"""Host-side slot lifecycle: admission, prefill interleaving, block
reservation/growth, retirement, preemption.

The scheduler owns every mutable serving decision and keeps it in plain
numpy — the compiled step only ever sees fixed-shape arrays built here.
One `tick()` = admit what fits, pick the next prefill chunk, run the
engine once, account emissions. Determinism: given the same request
stream (ids, seeds, arrival order) the schedule — and therefore every
emitted token — is a pure function of the inputs, which is what lets a
respawned replica REPLAY lost requests to bitwise-identical streams
(driver.py).
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import jax
import numpy as np

from ray_lightning_tpu.serve.engine import DecodeEngine, idle_prefill
from ray_lightning_tpu.serve.kv_cache import (
    BlockAllocator,
    PrefixCache,
    new_block_table,
    prefix_block_hashes,
)
from ray_lightning_tpu.telemetry.metrics import NULL_FLIGHT, NULL_METRICS


#: traffic classes, best first — the index is the preemption rank
#: (lower outranks higher; docs/SERVING.md "traffic & SLO classes")
PRIORITIES = ("latency_critical", "standard", "best_effort")
_PRIORITY_RANK = {p: i for i, p in enumerate(PRIORITIES)}


@dataclasses.dataclass
class Request:
    """One generation request. ``seed`` drives the slot's private RNG —
    sampling is per-request reproducible and batch-order invariant
    (test-pinned), and `generate(prompt, max_new_tokens, temperature,
    top_k, seed)` with the same values is the bitwise reference."""

    rid: str
    prompt: np.ndarray              # [l] int32 token ids
    max_new_tokens: int
    temperature: float = 0.0
    top_k: Optional[int] = None
    seed: int = 0
    eos_id: Optional[int] = None
    #: host wall time the request entered the queue (queue_wait span)
    arrival: float = 0.0
    #: traffic class (PRIORITIES). Inert unless the scheduler is built
    #: with an SLOConfig — priority-off runs the historical FIFO/age
    #: policy no matter what the label says (test-pinned)
    priority: str = "standard"

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.rid}: max_new_tokens < 1")
        if self.priority not in _PRIORITY_RANK:
            raise ValueError(
                f"request {self.rid}: priority {self.priority!r} not in "
                f"{PRIORITIES}")


@dataclasses.dataclass
class Completion:
    rid: str
    tokens: List[int]
    finish_reason: str              # "eos" | "length"
    queue_wait_s: float
    ttft_s: float                   # admission -> first token (host wall)
    decode_s: float                 # first token -> completion
    preempted: int = 0              # times this request was re-queued
    priority: str = "standard"      # the request's traffic class

    @property
    def tpot_s(self) -> float:
        """Mean time per output token after the first."""
        n = max(1, len(self.tokens) - 1)
        return self.decode_s / n


@dataclasses.dataclass(frozen=True)
class ClassSLO:
    """Per-class service targets + admission budget.

    ``queue_budget`` is the class's admission budget: with an SLOConfig
    armed, a new arrival in a SHED class whose class queue already
    holds this many requests is rejected with a typed shed record
    instead of queueing unboundedly behind traffic it can never
    outrank. ``None`` = unlimited."""

    ttft_p95_s: float = 2.0
    tpot_p95_s: float = 0.5
    queue_budget: Optional[int] = None


def _default_classes() -> Dict[str, ClassSLO]:
    return {
        "latency_critical": ClassSLO(ttft_p95_s=0.5, tpot_p95_s=0.2),
        "standard": ClassSLO(ttft_p95_s=2.0, tpot_p95_s=0.5),
        "best_effort": ClassSLO(ttft_p95_s=30.0, tpot_p95_s=2.0),
    }


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Arms traffic-aware scheduling (docs/SERVING.md "traffic & SLO
    classes"). With ``slo=None`` (the default everywhere) the scheduler
    runs the byte-identical historical policy: FIFO admission,
    oldest-preempts-youngest growth, no shedding, no class-keyed
    metrics — the priority label on a Request is inert.

    Armed, three seams change, all host-side (the compiled step never
    sees a priority):

    * admission order becomes (class rank, FIFO) — stable within a
      class, so the anti-livelock age ordering survives;
    * the growth-stall seam preempts by (class rank, age): a grower may
      evict strictly-lower-class slots of ANY age, same-class slots
      only if strictly younger — never peers-or-better; a blocked
      higher-class ARRIVAL may preempt a strictly-lower-class slot
      (`preempt_on_admit`);
    * overload sheds ``shed_classes`` load explicitly: a breached
      class ``queue_budget`` or a dry pool blocking a higher class
      produces a typed shed record with a capped-exponential
      ``retry_after_s`` hint — never silence.
    """

    classes: Dict[str, ClassSLO] = dataclasses.field(
        default_factory=_default_classes)
    #: classes eligible for load shedding under overload
    shed_classes: Tuple[str, ...] = ("best_effort",)
    #: shed queued shed-class work when a dry pool blocks the
    #: admission of a strictly higher class
    shed_on_dry_pool: bool = True
    #: a blocked higher-class arrival may preempt a strictly-lower-
    #: class slot to take its blocks (never a peer)
    preempt_on_admit: bool = True
    #: capped-exponential retry-after hint: base * 2^(sheds-1), capped
    retry_after_base_s: float = 0.5
    retry_after_cap_s: float = 30.0

    def __post_init__(self):
        for name in self.classes:
            if name not in _PRIORITY_RANK:
                raise ValueError(f"SLOConfig: unknown class {name!r}")
        for name in self.shed_classes:
            if name not in _PRIORITY_RANK:
                raise ValueError(
                    f"SLOConfig: unknown shed class {name!r}")

    def slo_for(self, priority: str) -> ClassSLO:
        return self.classes.get(priority, ClassSLO())

    def retry_after(self, n_sheds: int) -> float:
        """Capped-exponential backoff hint for the n-th shed of one
        request (n_sheds >= 1)."""
        return min(self.retry_after_cap_s,
                   self.retry_after_base_s * (2.0 ** max(0, n_sheds - 1)))

    def to_wire(self) -> dict:
        """JSON-safe payload (process-backend worker spawn)."""
        return {
            "classes": {k: dataclasses.asdict(v)
                        for k, v in self.classes.items()},
            "shed_classes": list(self.shed_classes),
            "shed_on_dry_pool": self.shed_on_dry_pool,
            "preempt_on_admit": self.preempt_on_admit,
            "retry_after_base_s": self.retry_after_base_s,
            "retry_after_cap_s": self.retry_after_cap_s,
        }

    @staticmethod
    def from_wire(d: Optional[dict]) -> Optional["SLOConfig"]:
        if d is None:
            return None
        return SLOConfig(
            classes={k: ClassSLO(**v)
                     for k, v in d.get("classes", {}).items()},
            shed_classes=tuple(d.get("shed_classes", ("best_effort",))),
            shed_on_dry_pool=d.get("shed_on_dry_pool", True),
            preempt_on_admit=d.get("preempt_on_admit", True),
            retry_after_base_s=d.get("retry_after_base_s", 0.5),
            retry_after_cap_s=d.get("retry_after_cap_s", 30.0),
        )


class _Slot:
    __slots__ = ("req", "blocks", "emitted", "prefill_next",
                 "admitted_at", "first_token_at", "preempted", "seq",
                 "shared_blocks", "hashes")

    def __init__(self, req: Request, blocks: List[int], preempted: int,
                 seq: int):
        self.req = req
        self.blocks = blocks            # allocated pool block ids
        self.emitted: List[int] = []
        self.prefill_next = 0           # prompt tokens already chunked
        self.admitted_at = time.perf_counter()
        self.first_token_at: Optional[float] = None
        self.preempted = preempted
        #: admission order — the preemption policy's age (monotonic,
        #: tie-free where wall clocks are not)
        self.seq = seq
        #: leading blocks mapped from the prefix cache at admission
        #: (their prefill was skipped); shrinks if a fork copies one
        self.shared_blocks = 0
        #: cumulative prompt-block digests (prefix_block_hashes) —
        #: kept for registration when prefill completes
        self.hashes: List[bytes] = []


@dataclasses.dataclass
class _PrefillGroup:
    """One FIFO prefill unit. Single-slot engines (prefill_batch == 1)
    run groups of one with ``width`` = the raw prompt length (the
    historical slide-back chunk discipline). Batched engines admit up
    to ``prefill_batch`` requests into one group, every row RIGHT-
    ALIGNED to the shared chunk-multiple ``width`` (the model's
    left-pad cache path — `generate(prompt_lengths=...)`): rows advance
    in lockstep at the shared write offset ``next`` and all finish on
    the same chunk, where the last real token of every row sits in the
    same in-chunk column."""

    slots: List[int]
    width: int
    next: int = 0


def validate_request(cfg, spec, req: Request) -> None:
    """The admission-time span checks EVERY submission path must pass
    — `Scheduler.submit` and the driver's dynamic-session `submit()`
    (which may have to defer a request before any scheduler sees it;
    an unvalidated oversize request would sit at a FIFO head forever,
    head-of-line-blocking the replica — review finding, test-pinned).
    ``cfg`` is the `EngineConfig`, ``spec`` its pool spec."""
    total = req.prompt.size + req.max_new_tokens
    if cfg.draft is not None:
        if req.temperature != 0.0:
            raise ValueError(
                f"request {req.rid}: speculative decoding is "
                f"greedy-only (temperature 0), got "
                f"{req.temperature}")
        # the verify chunk writes k positions from the LAST decode pos
        # — k-1 headroom keeps the window inside the slot
        total += cfg.draft.k - 1
    padded = ""
    if cfg.prefill_batch > 1:
        # batched prefill right-aligns the prompt to a chunk multiple
        # even when the request is admitted alone — the admission-time
        # span must cover that pad
        ch = cfg.prefill_chunk
        total = -(-req.prompt.size // ch) * ch + req.max_new_tokens
        padded = " (chunk-padded)"
    if total > cfg.max_slot_len:
        raise ValueError(
            f"request {req.rid}: prompt {req.prompt.size}{padded} + "
            f"max_new_tokens {req.max_new_tokens} exceeds the "
            f"engine's max_slot_len {cfg.max_slot_len}")
    if -(-total // spec.block_size) > spec.n_blocks - 1:
        # even with the pool to itself this request cannot finish —
        # admitting it would preempt-loop forever in on_demand mode
        raise ValueError(
            f"request {req.rid}: span {total} needs more blocks "
            f"than the whole pool holds "
            f"({spec.n_blocks - 1} usable)")


def _key_data(seed: int) -> np.ndarray:
    return np.array(jax.random.key_data(jax.random.key(seed)),
                    np.uint32)


class Scheduler:
    """Continuous-batching policy over one `DecodeEngine`.

    ``reserve="worst_case"`` (default) allocates every block a request
    could ever need at admission — no mid-stream surprises, admission
    defers while the pool is short. ``reserve="on_demand"`` allocates
    for the prompt only and grows per block boundary during decode;
    when the pool runs dry at a growth point the OLDEST slot preempts
    the YOUNGEST one back to the queue and takes its blocks —
    oldest-first progress guarantees the system drains, and replay is
    deterministic (same seed, same tokens), so a preempted stream is
    delayed, never corrupted.
    """

    def __init__(self, engine: DecodeEngine, reserve: str = "worst_case",
                 metrics=None, flight=None, prefix_cache: bool = False,
                 slo: Optional[SLOConfig] = None):
        if reserve not in ("worst_case", "on_demand"):
            raise ValueError(f"reserve={reserve!r}")
        if prefix_cache and engine.cfg.prefill_batch != 1:
            raise ValueError(
                "prefix_cache=True requires prefill_batch == 1 — the "
                "batched lane's left-pad alignment shifts block "
                "boundaries per group, so chains never line up")
        if prefix_cache and engine.mesh is not None:
            raise ValueError(
                "prefix_cache=True requires an unsharded replica "
                "(mesh=None) — the fork copy is a single-device "
                "primitive")
        #: live metrics (telemetry/metrics.py): per-tick gauges + event
        #: counters + completion latency histograms — every recorded
        #: value is a plain host scalar the tick computed anyway, so
        #: metrics on/off never changes the engine program or adds a
        #: host sync (test-pinned)
        self.metrics = metrics if metrics is not None else NULL_METRICS
        #: flight recorder: bounded ring of recent ticks + scheduler
        #: events, cadence-persisted — the postmortem a dead replica
        #: leaves behind (docs/OBSERVABILITY.md "flight recorder")
        self.flight = flight if flight is not None else NULL_FLIGHT
        self.engine = engine
        self.cfg = engine.cfg
        self.spec = engine.spec
        self.reserve = reserve
        self.alloc = BlockAllocator(self.spec)
        #: prompt-prefix -> block-chain cache (docs/SERVING.md "prefix
        #: sharing"): admission maps a matched chain into the slot's
        #: table by incref and prefills only the divergent tail
        self.prefix: Optional[PrefixCache] = (
            PrefixCache(self.alloc) if prefix_cache else None)
        #: tokens the verify chunk advances per tick (1 = base engine)
        self._spec_k = (self.cfg.draft.k
                        if self.cfg.draft is not None else 1)
        #: REAL prompt positions advanced through the prefill lane —
        #: the prefill-once assertion's counter (shared prefixes are
        #: admitted at pos > 0 and never re-issued)
        self.prefill_tokens_issued = 0
        self._emitted_total = 0
        self._decode_slot_steps = 0
        C = self.cfg.capacity
        self.tables = new_block_table(self.spec, C)
        self.pos = np.zeros(C, np.int32)
        self.decoding = np.zeros(C, bool)
        self.temp = np.zeros(C, np.float32)
        self.top_k = np.zeros(C, np.int32)
        self.rngs = np.zeros((C, 2), np.uint32)
        #: per-slot left pad (batched prefill admits left-padded rows;
        #: 0 everywhere on single-slot engines) — the decode lanes mask
        #: pad columns exactly like generate(prompt_lengths=...)
        self.pad = np.zeros(C, np.int32)
        self.slots: Dict[int, _Slot] = {}
        self.free_slots: List[int] = list(range(C))
        self.queue: Deque[Tuple[Request, int]] = deque()  # (req, preempts)
        self.prefill_groups: Deque[_PrefillGroup] = deque()  # FIFO
        self.completions: List[Completion] = []
        #: (rid, token) pairs emitted by the MOST RECENT tick — the
        #: driver's streaming hook
        self.last_emissions: List[Tuple[str, int]] = []
        #: rids preempted by the MOST RECENT tick: a streaming consumer
        #: must DISCARD its partial stream for these (the replay
        #: regenerates it bitwise; keeping the prefix would duplicate
        #: tokens — review finding, regression-pinned)
        self.last_preemptions: List[str] = []
        #: partial-progress timing for the MOST RECENT tick's
        #: preemptions — the driver records these as REPLAYED-tagged
        #: spans so a preempt-heavy run stops under-reporting
        #: queue_wait without double-counting the replayed prefix
        self.last_preemption_details: List[dict] = []
        #: traffic-aware policy (None = the byte-identical historical
        #: scheduler: FIFO + oldest-preempts-youngest, no shedding, no
        #: class-keyed metrics — test-pinned)
        self.slo = slo
        #: typed shed records since the last `take_sheds()` — every
        #: rejected/deferred request leaves one; a consumer that drops
        #: them ships silent request loss (lint rule RLT505)
        self.last_sheds: List[dict] = []
        #: per-rid shed count (drives the capped-exponential
        #: retry_after_s hint across resubmissions)
        self._shed_counts: Dict[str, int] = {}
        self._seq = 0
        self._queue_wait: Dict[str, float] = {}
        #: running occupancy: decoding-slot fraction summed over ticks
        self._occupancy_sum = 0.0
        self._ticks = 0
        #: drain mode (autoscale scale-down, docs/AUTOSCALE.md):
        #: admissions stop, already-slotted work decodes to retirement,
        #: and the driver evicts whatever lands back in the queue
        self.draining = False

    # ---- submission ------------------------------------------------------

    def submit(self, req: Request) -> None:
        validate_request(self.cfg, self.spec, req)
        if req.arrival == 0.0:
            req.arrival = time.perf_counter()
        self.enqueue(req, 0)

    def enqueue(self, req: Request, preempts: int) -> None:
        """Queue a validated request carrying its prior preemption
        count — the requeue path a scale-down/eviction uses so a
        request bounced between replicas keeps honest `preempted`
        accounting. External submissions go through `submit()` (which
        validates the span against THIS engine's pool first)."""
        if self.draining:
            raise RuntimeError(
                f"scheduler is draining — request {req.rid} must route "
                "to a live replica (driver bug: admissions are closed "
                "here)")
        if self.slo is None:
            self.queue.append((req, preempts))
            return
        budget = self.slo.slo_for(req.priority).queue_budget
        if (req.priority in self.slo.shed_classes
                and budget is not None
                and self._queued_in_class(req.priority) >= budget):
            self._shed(req, preempts, "queue_budget")
            return
        self._insert_by_class(req, preempts, front_of_class=False)

    def take_sheds(self) -> List[dict]:
        """Drain the typed shed records (explicit rejection/deferral —
        each carries rid, priority, reason, retry_after_s). The driver
        turns every record into a terminal status on the stream; a
        consumer that drops them ships silent request loss (RLT505)."""
        out, self.last_sheds = self.last_sheds, []
        return out

    # ---- traffic-aware policy helpers (no-ops with slo=None) -------------

    def _queued_in_class(self, priority: str) -> int:
        return sum(1 for q, _ in self.queue if q.priority == priority)

    def _insert_by_class(self, req: Request, preempts: int,
                         front_of_class: bool) -> None:
        """Class-ordered queue insert, FIFO-stable within a class. A
        new arrival goes BEHIND its class peers (front_of_class=False);
        a preempted requeue goes AHEAD of them (it is the oldest of its
        class — the anti-livelock age ordering the historical
        appendleft encoded, scoped to the class)."""
        r = _PRIORITY_RANK[req.priority]
        i = len(self.queue)
        for j, (q, _) in enumerate(self.queue):
            rq = _PRIORITY_RANK[q.priority]
            if rq > r or (front_of_class and rq == r):
                i = j
                break
        self.queue.insert(i, (req, preempts))

    def _shed(self, req: Request, preempts: int, reason: str) -> None:
        """Reject/defer one request with a typed record — the explicit
        overload paper trail (never silence). retry_after_s is
        capped-exponential in this rid's shed count."""
        n = self._shed_counts.get(req.rid, 0) + 1
        self._shed_counts[req.rid] = n
        rec = {
            "rid": req.rid,
            "priority": req.priority,
            "reason": reason,
            "retry_after_s": self.slo.retry_after(n),
            "sheds": n,
            "preempted": preempts,
        }
        self.last_sheds.append(rec)
        self._queue_wait.pop(req.rid, None)
        self.metrics.count("sheds")
        self.metrics.count(f"sheds_{req.priority}")
        self.flight.record("shed", rid=req.rid, priority=req.priority,
                           reason=reason,
                           retry_after_s=rec["retry_after_s"])

    def _shed_starved(self) -> None:
        """Dry pool blocking the queue head: queued shed-class work of
        STRICTLY lower class than the blocked head is shed with
        explicit records — it sits behind traffic it can never outrank,
        so leaving it queued is silent starvation."""
        if self.slo is None or not self.slo.shed_on_dry_pool:
            return
        head, _ = self.queue[0]
        r = _PRIORITY_RANK[head.priority]
        keep: Deque[Tuple[Request, int]] = deque()
        for req, preempts in self.queue:
            if (req.priority in self.slo.shed_classes
                    and _PRIORITY_RANK[req.priority] > r):
                self._shed(req, preempts, "dry_pool")
            else:
                keep.append((req, preempts))
        self.queue = keep

    def _admit_preempt(self) -> bool:
        """A blocked higher-class ARRIVAL preempts ONE strictly-lower-
        class slot (lowest class first, youngest within it) to take its
        slot + blocks — never a peer, so within-class age ordering (and
        with it the drain guarantee) is untouched. False when the
        policy is off or no strictly-lower-class victim exists."""
        if self.slo is None or not self.slo.preempt_on_admit:
            return False
        if not self.queue:
            return False
        head, _ = self.queue[0]
        r = _PRIORITY_RANK[head.priority]
        victims = [s for s in self.slots
                   if _PRIORITY_RANK[self.slots[s].req.priority] > r]
        if not victims:
            return False
        victim = max(victims, key=lambda s: (
            _PRIORITY_RANK[self.slots[s].req.priority],
            self.slots[s].seq))
        self.metrics.count("admit_preemptions")
        self._preempt(victim)
        return True

    def busy(self) -> bool:
        return bool(self.queue or self.slots)

    # ---- drain / eviction (the scale-down seams, docs/AUTOSCALE.md) ------

    def begin_drain(self) -> None:
        """Stop admissions for good: queued work must be evicted onto
        survivors (`evict_queued`), slotted work decodes to retirement
        under further `tick()`s. Idempotent."""
        if not self.draining:
            self.draining = True
            self.flight.record("drain_begin", queued=len(self.queue),
                               slotted=len(self.slots))

    def evict_queued(self) -> List[Tuple[Request, int]]:
        """Pop every still-queued (never admitted, or preempted-back)
        request for requeue on another replica. No partial state exists
        for these — replay elsewhere is bitwise by construction (same
        seed, same stream)."""
        out = list(self.queue)
        self.queue.clear()
        for req, preempts in out:
            self.flight.record("evict", rid=req.rid, state="queued",
                               preempted=preempts)
        return out

    def evict_slotted(self) -> List[Tuple[Request, int]]:
        """Forced (non-graceful) drain: tear every slot down, free its
        blocks, and return the requests with their preemption count
        bumped — the existing bitwise replay seam: a consumer discards
        the partial stream and the re-decode regenerates it identically
        from the seed (exactly what replica-death replay does)."""
        out: List[Tuple[Request, int]] = []
        for s in sorted(self.slots):
            slot = self.slots.pop(s)
            self.alloc.free(slot.blocks)
            self.tables[s, :] = 0
            self.decoding[s] = False
            self.pos[s] = 0
            self.pad[s] = 0
            self.free_slots.append(s)
            self.flight.record("evict", rid=slot.req.rid,
                               state="slotted",
                               emitted=len(slot.emitted),
                               preempted=slot.preempted + 1)
            out.append((slot.req, slot.preempted + 1))
        self.prefill_groups.clear()
        return out

    # ---- internals -------------------------------------------------------

    def _blocks_needed_at_admit(self, req: Request,
                                width: Optional[int] = None) -> int:
        """``width`` is the (padded) prefill width the slot will hold —
        the raw prompt length on single-slot engines."""
        if width is None:
            width = req.prompt.size
        if self.reserve == "worst_case":
            span = width + req.max_new_tokens
        elif self.cfg.prefill_batch > 1:
            # batched prefill writes exactly [0, width) — width is
            # already a chunk multiple; growth per decode boundary
            span = width
        else:
            # prefill writes full chunks: cover the prompt rounded up
            # to the chunk width (tail-chunk garbage lands in owned
            # blocks), growth happens per decode block boundary
            ch = self.cfg.prefill_chunk
            span = min(-(-width // ch) * ch, self.cfg.max_slot_len)
        return -(-span // self.spec.block_size)

    def _alloc_or_evict(self, n: int) -> Optional[List[int]]:
        """`BlockAllocator.alloc` with the prefix cache as the relief
        valve: when the free list is short, LRU cache entries whose
        block nothing else holds (refcount 1) are evicted to cover the
        shortfall before the caller defers or preempts."""
        if n <= 0:
            return []
        got = self.alloc.alloc(n)
        if got is None and self.prefix is not None:
            self.prefix.evict(n - self.alloc.free_blocks)
            got = self.alloc.alloc(n)
        return got

    def _admit_one(self, width: int) -> Optional[int]:
        """Admit the queue head into a free slot with blocks reserved
        for ``width`` prefill positions. Returns the slot id, or None
        when the pool is short (FIFO holds).

        With the prefix cache armed, the prompt's cumulative block
        digests are matched against cached chains first: matched FULL
        blocks map into the slot's table by incref (their prefill is
        skipped — ``pos`` starts past them), capped one block short of
        the prompt end so the slot's OWN final chunk always runs and
        computes ``last_logits``. A failed owned-tail allocation
        decrefs the held match exactly — a deferred admission leaks
        nothing."""
        req, preempts = self.queue[0]
        matched: List[int] = []
        hashes: List[bytes] = []
        if self.prefix is not None:
            P = self.spec.block_size
            hashes = prefix_block_hashes(req.prompt, P)
            cap = (req.prompt.size - 1) // P
            matched = self.prefix.match(hashes, max_blocks=cap)
        n_need = self._blocks_needed_at_admit(req, width) - len(matched)
        # hold the matched chain (incref) BEFORE the tail allocation:
        # the allocation may evict LRU cache entries, and an unheld
        # match at refcount 1 would be evictable out from under us
        if matched:
            self.alloc.incref(matched)
        blocks = self._alloc_or_evict(n_need)
        if blocks is None:
            if matched:
                self.alloc.decref(matched)
            return None  # pool short: keep FIFO order, retry next tick
        n_shared = len(matched) * self.spec.block_size
        blocks = matched + blocks
        self.queue.popleft()
        s = self.free_slots.pop(0)
        self._seq += 1
        slot = _Slot(req, blocks, preempts, self._seq)
        slot.shared_blocks = len(matched)
        slot.hashes = hashes
        slot.prefill_next = n_shared
        self.slots[s] = slot
        self.tables[s, :] = 0
        self.tables[s, :len(blocks)] = blocks
        self.pos[s] = n_shared
        self.decoding[s] = False
        self.pad[s] = width - req.prompt.size
        self.temp[s] = req.temperature
        self.top_k[s] = req.top_k or 0
        self.rngs[s] = _key_data(req.seed)
        self._queue_wait[req.rid] = (
            slot.admitted_at - req.arrival if req.arrival else 0.0)
        if self.prefix is not None:
            self.prefix.prompt_tokens += int(req.prompt.size)
            self.prefix.shared_tokens += n_shared
            if n_shared:
                self.metrics.count("prefix_hits")
                self.metrics.count("shared_prompt_tokens", n_shared)
        self.metrics.count("admissions")
        self.flight.record("admit", rid=req.rid, slot=s,
                           blocks=len(blocks), preempted=preempts,
                           shared=len(matched))
        return s

    def _admit(self) -> None:
        if self.draining:
            # admissions are closed: anything in the queue (including a
            # request a growth stall just preempted back) waits for the
            # driver's eviction pass, never re-admits here
            return
        if self.cfg.prefill_batch == 1:
            # slo=None: `_admit_preempt()` is a constant False, so this
            # is exactly the historical free-slot FIFO loop
            while self.queue and (self.free_slots
                                  or self._admit_preempt()):
                s = self._admit_one(self.queue[0][0].prompt.size)
                if s is None:
                    # pool short: try taking a strictly-lower-class
                    # slot's blocks; otherwise shed starved shed-class
                    # work behind the blocked head and defer
                    if self._admit_preempt():
                        continue
                    self._shed_starved()
                    self.metrics.count("admission_deferrals")
                    return
                self.prefill_groups.append(
                    _PrefillGroup([s], self.slots[s].req.prompt.size))
            return
        # batched admission: FIFO groups of up to prefill_batch
        # requests, every member right-aligned to the group width W =
        # the HEAD request's chunk-rounded prompt length. A longer
        # prompt at the queue head ends the group and heads the next
        # one (W never grows after member 1, so earlier members' block
        # reservations stay valid) — no request is ever skipped past.
        ch = self.cfg.prefill_chunk
        while self.queue and self.free_slots:
            group: List[int] = []
            width = 0
            while (self.queue and self.free_slots
                   and len(group) < self.cfg.prefill_batch):
                req, _ = self.queue[0]
                solo_w = -(-req.prompt.size // ch) * ch
                if not group:
                    width = solo_w
                elif (solo_w > width
                      or width + req.max_new_tokens
                      > self.cfg.max_slot_len):
                    break  # heads the next group instead
                s = self._admit_one(width)
                if s is None:
                    self.metrics.count("admission_deferrals")
                    break  # pool short
                group.append(s)
            if not group:
                return
            self.prefill_groups.append(_PrefillGroup(group, width))

    def _policy_key(self, slot: _Slot) -> Tuple[int, int]:
        """Preemption/growth policy order: (class rank, admission age).
        With slo=None every rank is 0, so the order — and every
        decision derived from it — is the historical seq-only age
        ordering (test-pinned)."""
        if self.slo is None:
            return (0, slot.seq)
        return (_PRIORITY_RANK[slot.req.priority], slot.seq)

    def _grow(self, s: int, slot: _Slot) -> bool:
        """Ensure every block a decode write can touch this tick
        exists: positions ``pos .. pos + spec_k - 1`` (k == 1 on the
        base engine — the historical one-block growth). True = ok,
        False = pool empty (caller preempts)."""
        idx = (int(self.pos[s]) + self._spec_k - 1) \
            // self.spec.block_size
        while len(slot.blocks) <= idx:
            got = self._alloc_or_evict(1)
            if got is None:
                return False
            self.tables[s, len(slot.blocks)] = got[0]
            slot.blocks.extend(got)
        return True

    def _fork_for_window(self, s: int, slot: _Slot, start: int) -> bool:
        """Copy-on-write: before the prefill chunk's FULL ``ch``-wide
        window ``[start, start + ch)`` is written, any block in the
        window with refcount > 1 (shared with the prefix cache or a
        sibling slot) is forked — copied into a fresh block the slot
        repoints its table at — so a non-exclusive block is never
        written. Reached only when the window slides back across the
        shared prefix (prompt near the slot end); the rewrite is
        value-identical on the reference path, but forking keeps the
        invariant robust on every path. True = ok, False = pool dry
        (caller preempts the prefilling slot)."""
        P = self.spec.block_size
        lo = start // P
        hi = min((start + self.cfg.prefill_chunk - 1) // P,
                 len(slot.blocks) - 1)
        for bi in range(lo, hi + 1):
            b = slot.blocks[bi]
            if self.alloc.refcount(b) <= 1:
                continue
            got = self._alloc_or_evict(1)
            if got is None:
                return False
            self.engine.copy_block(b, got[0])
            slot.blocks[bi] = got[0]
            self.tables[s, bi] = got[0]
            self.alloc.decref([b])
            if bi < slot.shared_blocks:
                slot.shared_blocks = bi
            self.metrics.count("block_forks")
            self.flight.record("fork", rid=slot.req.rid, slot=s,
                               block=int(b), copy=int(got[0]))
        return True

    def _preempt(self, s: int) -> None:
        """Return a slot's request to the queue head for deterministic
        replay from scratch (same seed -> same tokens; emitted-so-far
        is discarded, the stream restarts delayed but identical)."""
        slot = self.slots.pop(s)
        self.last_preemptions.append(slot.req.rid)
        self.last_preemption_details.append(self._partial_timing(
            slot, time.perf_counter(), preempted=slot.preempted + 1))
        self.metrics.count("preemptions")
        self.flight.record("preempt", rid=slot.req.rid, slot=s,
                           emitted=len(slot.emitted),
                           preempted=slot.preempted + 1)
        self.alloc.free(slot.blocks)
        self.tables[s, :] = 0
        self.decoding[s] = False
        self.pos[s] = 0
        self.pad[s] = 0
        for g in list(self.prefill_groups):
            if s in g.slots:
                g.slots.remove(s)
                if not g.slots:  # group emptied mid-prefill
                    self.prefill_groups.remove(g)
                break
        self.free_slots.append(s)
        if self.slo is None:
            self.queue.appendleft((slot.req, slot.preempted + 1))
        else:
            # front of its CLASS, not of the whole queue — a preempted
            # best-effort request must not jump a latency-critical one
            self._insert_by_class(slot.req, slot.preempted + 1,
                                  front_of_class=True)

    def _retire(self, s: int, reason: str) -> Completion:
        slot = self.slots.pop(s)
        now = time.perf_counter()
        first = slot.first_token_at or now
        comp = Completion(
            rid=slot.req.rid,
            tokens=list(slot.emitted),
            finish_reason=reason,
            queue_wait_s=self._queue_wait.pop(slot.req.rid, 0.0),
            ttft_s=first - slot.admitted_at,
            decode_s=now - first,
            preempted=slot.preempted,
            priority=slot.req.priority,
        )
        self.alloc.free(slot.blocks)
        self.tables[s, :] = 0
        self.decoding[s] = False
        self.pos[s] = 0
        self.pad[s] = 0
        self.free_slots.append(s)
        self.completions.append(comp)
        m = self.metrics
        if m.enabled:
            m.count("completions")
            m.observe("queue_wait_s", comp.queue_wait_s)
            m.observe("ttft_s", comp.ttft_s)
            m.observe("tpot_s", comp.tpot_s)
            m.observe("decode_s", comp.decode_s)
            if self.slo is not None:
                # class-keyed twins: `observe()` auto-creates the
                # histogram, so `serving.ttft_<class>_p95_s` watch
                # selectors resolve with zero grammar change
                p = comp.priority
                m.count(f"completions_{p}")
                m.observe(f"ttft_{p}_s", comp.ttft_s)
                m.observe(f"tpot_{p}_s", comp.tpot_s)
                m.observe(f"queue_wait_{p}_s", comp.queue_wait_s)
        self.flight.record("retire", rid=comp.rid, slot=s, reason=reason,
                           tokens=len(comp.tokens),
                           preempted=comp.preempted)
        return comp

    # ---- the tick --------------------------------------------------------

    def tick(self) -> List[Completion]:
        """Admit -> prefill-chunk pick -> engine step -> account.
        Returns the requests that COMPLETED this tick."""
        self.last_preemptions = []
        self.last_preemption_details = []
        self._admit()
        # growth check before the step: every decoding slot must own
        # the block its write lands in. On a dry pool a grower may only
        # evict slots STRICTLY AFTER itself in policy order (decoding
        # or prefilling — a re-admitted request is always the
        # youngest); with no victim it preempts ITSELF. Policy order is
        # (class rank, admission seq): with slo=None every rank is 0
        # and this is the byte-identical historical age ordering; armed,
        # a grower may evict strictly-lower-class slots of ANY age and
        # same-class slots only if strictly younger — never peers. The
        # policy-minimal slot is therefore never evicted and strictly
        # progresses every tick, so the system drains — any policy that
        # lets a later grower evict an earlier slot (or the grower
        # evict itself while holding victims) lets two oversubscribed
        # requests cycle forever (observed livelock, test-pinned
        # against).
        for s in sorted([s for s in self.slots if self.decoding[s]],
                        key=lambda s: self._policy_key(self.slots[s])):
            if s not in self.slots:
                continue  # preempted as a victim earlier this tick
            me = self.slots[s]
            me_key = self._policy_key(me)
            while not self._grow(s, me):
                # a dry pool at a growth boundary: the signal item 1(c)
                # autoscale watches — every stall is one eviction (or a
                # self-preempt) the pool's size forced
                self.metrics.count("growth_stalls")
                victims = [v for v in self.slots
                           if self._policy_key(self.slots[v]) > me_key]
                if victims:
                    self._preempt(max(
                        victims,
                        key=lambda v: self._policy_key(self.slots[v])))
                elif len(self.slots) > 1:
                    # s is the youngest: yield its blocks to its elders
                    self._preempt(s)
                    break
                else:
                    # alone and still dry — unreachable when submit()
                    # holds its pool-size invariant (a lone slot's span
                    # fits the pool); requeueing would re-admit into
                    # the same state forever, so fail loudly instead
                    raise RuntimeError(
                        f"request {me.req.rid} cannot grow with the "
                        "pool to itself — engine pool is smaller than "
                        "one request's span")
        # one prefill chunk, FIFO over admitted-but-not-decoding groups
        prefill = idle_prefill(self.cfg)
        pf_group = self.prefill_groups[0] if self.prefill_groups else None
        ch = self.cfg.prefill_chunk
        if pf_group is not None and self.cfg.prefill_batch == 1:
            pf_slot = pf_group.slots[0]
            slot = self.slots[pf_slot]
            ptoks = slot.req.prompt
            ppos = slot.prefill_next
            chunk_len = min(ch, ptoks.size - ppos)
            # the engine writes the FULL ch-wide window: slide the
            # window start back so it never crosses the slot end —
            # otherwise the model's in-cache update and the pool
            # scatter both clamp and scribble real prompt entries
            # (review finding, regression-pinned). Re-sent rows
            # recompute bitwise-identical K/V: each row's causal mask
            # restricts it to the same context as its original pass.
            start = min(ppos, self.cfg.max_slot_len - ch)
            if self.prefix is not None and not self._fork_for_window(
                    pf_slot, slot, start):
                # pool dry under a copy-on-write fork: bounce the
                # prefilling request back to the queue (deterministic
                # replay) and run this tick without a prefill chunk
                self._preempt(pf_slot)
                pf_group = None
            else:
                n_win = min(ch, ptoks.size - start)
                chunk = np.zeros(ch, np.int32)
                chunk[:n_win] = ptoks[start:start + n_win]
                finished = ppos + chunk_len >= ptoks.size
                last_row = (ptoks.size - 1 - start) if finished else -1
                prefill = (np.int32(pf_slot), chunk, np.int32(start),
                           np.int32(last_row))
        elif pf_group is not None:
            # batched lane: the head group advances one shared chunk;
            # every row's LEFT-padded prompt is right-aligned to the
            # group width, so the final chunk's last real token sits in
            # the same column for every row (no window sliding: the
            # width is a chunk multiple by construction)
            B = self.cfg.prefill_batch
            start = pf_group.next
            toks = np.zeros((B, ch), np.int32)
            slots_arr = np.full(B, -1, np.int32)
            pads = np.zeros(B, np.int32)
            for r, s in enumerate(pf_group.slots):
                req = self.slots[s].req
                pad = int(self.pad[s])
                slots_arr[r] = s
                pads[r] = pad
                # padded row: pad zeros then the prompt; this chunk is
                # padded_row[start : start + ch]
                p = start - pad + np.arange(ch)
                valid = (p >= 0) & (p < req.prompt.size)
                toks[r, valid] = req.prompt[p[valid]]
            finished = start + ch >= pf_group.width
            last_row = (pf_group.width - 1 - start) if finished else -1
            prefill = (slots_arr, toks, np.int32(start),
                       np.int32(last_row), pads)
        was_decoding = self.decoding.copy()
        emitted, n_emit, self.rngs = self.engine.tick(
            self.tables, self.pos, self.decoding, self.temp, self.top_k,
            self.rngs, prefill,
            pad=self.pad if self.cfg.prefill_batch > 1 else None)
        self._occupancy_sum += float(was_decoding.mean())
        self._ticks += 1
        # prefill accounting
        if pf_group is not None and self.cfg.prefill_batch == 1:
            pf_slot = pf_group.slots[0]
            slot = self.slots[pf_slot]
            chunk_len = min(ch, slot.req.prompt.size - slot.prefill_next)
            slot.prefill_next += chunk_len
            self.pos[pf_slot] += chunk_len
            self.prefill_tokens_issued += chunk_len
            if slot.prefill_next >= slot.req.prompt.size:
                self.prefill_groups.popleft()
                self.decoding[pf_slot] = True
                if self.prefix is not None:
                    # publish the fully prefilled chain: every FULL
                    # prompt block becomes matchable for later admits
                    n_full = (slot.req.prompt.size
                              // self.spec.block_size)
                    self.prefix.register(slot.hashes[:n_full],
                                         slot.blocks[:n_full])
        elif pf_group is not None:
            pf_group.next += ch
            for s in pf_group.slots:
                self.pos[s] += ch  # cache positions incl. pad columns
            self.prefill_tokens_issued += ch * len(pf_group.slots)
            if pf_group.next >= pf_group.width:
                self.prefill_groups.popleft()
                for s in pf_group.slots:
                    self.decoding[s] = True
        # decode accounting — the engine hands back up to W tokens per
        # slot (W == 1 on the base step): append in order, truncating
        # at eos / max_new exactly where plain greedy decode stops
        done: List[Completion] = []
        self.last_emissions = []
        n_active = int(was_decoding.sum())
        if n_active:
            self._decode_slot_steps += n_active
            self._emitted_total += int(n_emit[was_decoding].sum())
        for s in list(self.slots):
            if not was_decoding[s]:
                continue
            slot = self.slots[s]
            if slot.first_token_at is None:
                slot.first_token_at = time.perf_counter()
            req = slot.req
            for _j in range(int(n_emit[s])):
                tok = int(emitted[s, _j])
                slot.emitted.append(tok)
                self.last_emissions.append((req.rid, tok))
                self.pos[s] += 1
                if req.eos_id is not None and tok == req.eos_id:
                    done.append(self._retire(s, "eos"))
                    break
                if len(slot.emitted) >= req.max_new_tokens:
                    done.append(self._retire(s, "length"))
                    break
        m = self.metrics
        if m.enabled or self.flight.enabled:
            # every value below is host bookkeeping the tick already
            # holds in plain python/numpy — no device array is touched
            queue_depth = len(self.queue)
            decoding = int(self.decoding.sum())
            prefilling = sum(len(g.slots) for g in self.prefill_groups)
            free = self.alloc.free_blocks
            total = self.spec.n_blocks - 1  # block 0 is scratch
            if m.enabled:
                m.gauge("queue_depth", queue_depth)
                m.gauge("decoding_slots", decoding)
                m.gauge("prefilling_slots", prefilling)
                m.gauge("free_slots", len(self.free_slots))
                m.gauge("blocks_free", free)
                m.gauge("blocks_in_use", total - free)
                m.gauge("slot_occupancy", float(was_decoding.mean()))
                if self.slo is not None:
                    # per-class pressure feeds `load_signal()`'s
                    # pressure_<class> fields (autoscale + watch);
                    # emitted only when the policy is armed so a
                    # priority-off run's metrics stream is unchanged
                    for p in PRIORITIES:
                        m.gauge(f"queue_depth_{p}",
                                self._queued_in_class(p))
            self.flight.record("tick", tick=self._ticks,
                               queue_depth=queue_depth,
                               decoding=decoding, prefilling=prefilling,
                               blocks_free=free,
                               completed=len(done))
            m.tick_end()
        return done

    # ---- metrics ---------------------------------------------------------

    @property
    def slot_occupancy(self) -> float:
        """Mean decoding-slot fraction over all ticks so far."""
        return self._occupancy_sum / max(1, self._ticks)

    @property
    def shared_block_fraction(self) -> float:
        """Fraction of admitted prompt tokens served from the prefix
        cache instead of the prefill lane (0.0 with the cache off or
        when no prompts shared a prefix)."""
        return (self.prefix.shared_block_fraction
                if self.prefix is not None else 0.0)

    @property
    def accepted_tokens_per_step(self) -> float:
        """Mean tokens emitted per decoding slot per engine tick —
        exactly 1.0 on the base engine, ``1 + mean accepted
        proposals`` under speculative decoding (the throughput
        multiplier the draft buys)."""
        if not self._decode_slot_steps:
            return 1.0
        return self._emitted_total / self._decode_slot_steps

    def _partial_timing(self, slot: _Slot, now: float,
                        preempted: int) -> dict:
        """One request's partial-progress timing — the shared shape
        behind `last_preemption_details` and `inflight_snapshot` (the
        driver back-dates spans from exactly these fields, so the two
        accountings can never drift apart)."""
        first = slot.first_token_at
        return {
            "rid": slot.req.rid,
            "queue_wait_s": self._queue_wait.get(slot.req.rid, 0.0),
            "prefill_s": (first if first is not None else now)
            - slot.admitted_at,
            "decode_s": (now - first) if first is not None else 0.0,
            "emitted": len(slot.emitted),
            "preempted": preempted,
        }

    def inflight_snapshot(self) -> List[dict]:
        """Partial-progress timing for every request the scheduler
        still holds — slotted (prefilling/decoding) and queued. The
        driver records these as INFLIGHT-tagged serving spans at drain
        time, so a run that stops mid-flight (replica death, shutdown)
        accounts the wall its unfinished requests already spent instead
        of dropping it (docs/OBSERVABILITY.md "serving spans")."""
        now = time.perf_counter()
        out: List[dict] = []
        for s, slot in self.slots.items():
            out.append({
                **self._partial_timing(slot, now,
                                       preempted=slot.preempted),
                "state": "decoding" if self.decoding[s]
                else "prefilling",
            })
        for req, preempts in self.queue:
            out.append({
                "rid": req.rid, "state": "queued",
                "queue_wait_s": (now - req.arrival) if req.arrival
                else 0.0,
                "prefill_s": 0.0, "decode_s": 0.0, "emitted": 0,
                "preempted": preempts,
            })
        return out
