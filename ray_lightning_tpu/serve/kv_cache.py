"""Block-paged KV cache: a shared block pool + per-slot block tables.

The training-side cache (`models.llama.init_cache`) is dense: one
``[L, B, S_max, Hkv, hd]`` buffer per request batch, sized for the
worst case. Serving cannot afford that shape — requests are ragged,
arrive and retire continuously, and the cache is the dominant HBM
consumer — so the serving engine stores KV in fixed-size **blocks**
drawn from one shared pool:

    pool_k, pool_v : [L, n_blocks, block_size, Hkv, hd]
    block_table    : [capacity, blocks_per_slot] int32  (host-owned)

A slot's logical cache position ``p`` lives at pool block
``table[slot, p // block_size]``, offset ``p % block_size``. The device
step receives the table as a plain int32 input each call: admission and
retirement only rewrite table rows and host-side scalars, so the
compiled step never changes shape (the no-recompile-under-churn
guarantee the engine pins).

Block 0 is the **scratch block**: never allocated, and every index the
step must not really write (idle slots, the prefill lane when nothing
is prefilling) is redirected to it. Scratch contents are garbage by
design; every read of the gathered view is masked by position
(``kv_pos <= q_pos``) before it can influence attention, and masked
scores contribute *exactly* zero through the softmax — the bitwise
parity with single-stream `generate` rests on this (docs/SERVING.md
"numerics").
"""
from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class PagedPoolSpec:
    """Shape of the paged pool for one model config.

    ``gathered_len = blocks_per_slot * block_size`` is the dense view
    the step materializes per slot — the per-slot maximum of
    ``prompt_len + max_new_tokens`` the scheduler can admit.
    """

    n_blocks: int
    block_size: int
    blocks_per_slot: int

    def __post_init__(self):
        if self.block_size < 1 or self.blocks_per_slot < 1:
            raise ValueError("block_size and blocks_per_slot must be >= 1")
        if self.n_blocks < 2:
            # block 0 is reserved scratch — a pool of 1 block can hold
            # no request at all
            raise ValueError("n_blocks must be >= 2 (block 0 is scratch)")

    @property
    def gathered_len(self) -> int:
        return self.blocks_per_slot * self.block_size

    @classmethod
    def for_capacity(cls, capacity: int, max_len: int,
                     block_size: int = 16,
                     oversubscribe: float = 1.0) -> "PagedPoolSpec":
        """A spec sized so ``capacity`` slots of up to ``max_len`` tokens
        fit. ``oversubscribe < 1`` shrinks the pool below the dense
        worst case — the paged bet that real lengths are ragged; the
        scheduler's on-demand mode defers admissions (or preempts) when
        the bet loses."""
        bps = -(-max_len // block_size)
        blocks = max(2, 1 + int(round(capacity * bps * oversubscribe)))
        return cls(n_blocks=blocks, block_size=block_size,
                   blocks_per_slot=bps)


def init_pool(cfg, spec: PagedPoolSpec):
    """Zeroed (pool_k, pool_v), leaves
    ``[n_layers, n_blocks, block_size, n_kv_heads, head_dim]`` in the
    model's activation dtype — the same per-position layout as
    `models.llama.init_cache`, block-chunked over the sequence axis."""
    shape = (cfg.n_layers, spec.n_blocks, spec.block_size,
             cfg.n_kv_heads, cfg.head_dim)
    return jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype)


def validate_pool_tp(cfg, tp: int) -> None:
    """A tensor-parallel replica shards the pool over the KV-head axis
    (the one axis every pool consumer — gather, scatter, both fused
    kernels — treats as embarrassingly parallel), so the head count
    must divide evenly: an uneven split would give ranks different
    pool shapes and the one-compile step different programs per rank."""
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    if cfg.n_kv_heads % tp:
        raise ValueError(
            f"tensor-parallel degree {tp} must divide n_kv_heads "
            f"{cfg.n_kv_heads}: the paged pool shards over the KV-head "
            "axis (docs/SERVING.md 'sharded replicas')")


def pool_partition_spec(tp: int = 1):
    """PartitionSpec of one pool leaf ``[L, n_blocks, block_size, Hkv,
    hd]`` on a replica's own mesh: KV heads over the ``tensor`` axis,
    every other axis replicated. Block identity is untouched — the SAME
    host-side block table drives every shard, so the allocator and the
    scheduler stay tp-oblivious."""
    from jax.sharding import PartitionSpec as P

    if tp <= 1:
        return P()
    return P(None, None, None, "tensor", None)


def pool_shard_bytes(cfg, spec: PagedPoolSpec, tp: int = 1) -> int:
    """Per-device HBM of one rank's pool shard (k + v): the head axis
    divides by ``tp``, everything else is carried whole."""
    validate_pool_tp(cfg, tp)
    return int(pool_bytes(cfg, spec)) // tp


def pool_bytes(cfg, spec: PagedPoolSpec) -> int:
    """HBM held by the pool itself (k + v)."""
    per = (cfg.n_layers * spec.n_blocks * spec.block_size
           * cfg.n_kv_heads * cfg.head_dim)
    return 2 * per * jnp.dtype(cfg.dtype).itemsize


def gathered_view_bytes(cfg, spec: PagedPoolSpec, capacity: int) -> int:
    """HBM of the dense per-slot gathered view the REFERENCE decode
    lane materializes (k + v): ``[L, capacity, gathered_len, Hkv, hd]``.
    The reference engine pays this copy for correctness-first paged
    semantics; the fused paged-attention kernel
    (ops/pallas/paged_attention.py) consumes the pool through the block
    tables and this term vanishes (docs/SERVING.md "paged-attention
    kernel") — the planner charges whichever path the engine would
    select (`serve_kv_plan_bytes(fused=...)`)."""
    per = (cfg.n_layers * capacity * spec.gathered_len
           * cfg.n_kv_heads * cfg.head_dim)
    return 2 * per * jnp.dtype(cfg.dtype).itemsize


def serve_kv_plan_bytes(cfg, spec: PagedPoolSpec, capacity: int,
                        fused: bool = False,
                        prefill_batch: int = 1,
                        fused_prefill: bool = False,
                        tp: int = 1) -> dict:
    """The serving cache's HBM story for the ``plan --serve`` leg:
    itemized pool + gathered view + the per-slot logits buffer the
    engine keeps device-resident between steps.

    ``fused`` selects the DECODE attention path being priced;
    ``fused_prefill`` the PREFILL path (the two kernels gate shapes
    independently). On the fused decode path the capacity-wide dense
    view is RETIRED — what survives is the prefill lane's per-group
    gather (``[L, prefill_batch, gathered_len, Hkv, hd]``), itemized
    separately as ``prefill_gather_bytes``; with the fused PREFILL
    kernel that last copy vanishes too and the view term reaches
    zero. The retired bytes are itemized so `plan --serve` can state
    the per-replica HBM the kernels bought back.

    ``tp > 1`` prices ONE RANK of a tensor-parallel replica: the pool
    and every gathered view carry the KV-head axis and divide by
    ``tp``; ``last_logits`` is replicated per rank (docs/SERVING.md
    "sharded replicas") and does not."""
    validate_pool_tp(cfg, tp)
    logits = capacity * cfg.vocab_size * 4  # f32 last_logits, replicated
    dense = int(gathered_view_bytes(cfg, spec, capacity)) // tp
    prefill_gather = int(gathered_view_bytes(
        cfg, spec, min(prefill_batch, capacity))) // tp
    if fused_prefill:
        prefill_gather = 0
    if fused:
        view = prefill_gather
    else:
        # the reference decode lane's capacity-wide copy dominates; the
        # group-sized prefill gather is a slice of the same story (it
        # is only itemized separately once the decode view is retired)
        view = dense
        prefill_gather = min(prefill_gather, view)
    return {
        "pool_bytes": int(pool_bytes(cfg, spec)) // tp,
        "gathered_view_bytes": view,
        "gathered_view_retired_bytes": dense - view,
        "prefill_gather_bytes": prefill_gather,
        "last_logits_bytes": int(logits),
    }


class BlockAllocator:
    """Host-side free-list over the pool's blocks, with per-block
    REFCOUNTS so prefix sharing can map one physical block into many
    slot tables (docs/SERVING.md "prefix sharing"). Block 0 (scratch)
    is never handed out. Pure bookkeeping — the device never sees this
    object, only the int32 tables the scheduler builds from it.

    ``alloc`` grants blocks at refcount 1; ``incref`` adds a sharer;
    ``decref`` (and its alias ``free``) drops one reference and returns
    the block to the free list only when the LAST reference dies. A
    decref of a block that is already free refuses with the same
    "double free" error the unref'd allocator raised — releasing a
    reference you do not hold is the bookkeeping bug that silently
    corrupts a *different* request's cache."""

    def __init__(self, spec: PagedPoolSpec):
        self.spec = spec
        self._free: List[int] = list(range(1, spec.n_blocks))
        #: block id -> live reference count (allocated blocks only)
        self._refs: Dict[int, int] = {}

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def refcount(self, b: int) -> int:
        """Live references on block ``b`` (0 when free)."""
        return self._refs.get(int(b), 0)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` block ids at refcount 1, or None when the pool cannot
        satisfy the request (the caller defers admission / preempts —
        never a partial grant, which would strand blocks on a failed
        admit)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        ids, self._free = self._free[:n], self._free[n:]
        for b in ids:
            self._refs[b] = 1
        return ids

    def incref(self, ids) -> None:
        """Add one reference per id — mapping an already-resident block
        into another slot's table (prefix sharing)."""
        for b in ids:
            b = int(b)
            if self._refs.get(b, 0) < 1:
                raise ValueError(f"incref of unallocated block {b}")
            self._refs[b] += 1

    def decref(self, ids) -> List[int]:
        """Drop one reference per id; returns the ids whose LAST
        reference died (now back on the free list)."""
        freed: List[int] = []
        for b in ids:
            b = int(b)
            if b <= 0 or b >= self.spec.n_blocks:
                raise ValueError(f"freeing invalid block {b}")
            rc = self._refs.get(b, 0)
            if rc < 1:
                raise ValueError(f"double free of block {b}")
            if rc == 1:
                del self._refs[b]
                self._free.append(b)
                freed.append(b)
            else:
                self._refs[b] = rc - 1
        return freed

    def free(self, ids) -> None:
        """Alias for :meth:`decref` — every historical release site
        (retirement, preemption, drain-eviction) is one dropped
        reference, which only *frees* when nothing shares the block."""
        self.decref(ids)


def prefix_block_hashes(tokens, block_size: int) -> List[bytes]:
    """Cumulative digest per FULL block of ``tokens``: digest ``i``
    identifies tokens ``0 .. (i+1)*block_size`` as a chain, so equal
    digests imply equal prefixes (not merely equal blocks — K/V at
    position ``p`` depends on every earlier token, so a block is only
    shareable together with its whole prefix). hashlib keeps the key
    deterministic across processes, unlike Python's seeded ``hash``."""
    toks = np.asarray(tokens, dtype=np.int32).reshape(-1)
    out: List[bytes] = []
    h = b""
    for i in range(toks.size // block_size):
        chunk = toks[i * block_size:(i + 1) * block_size].tobytes()
        h = hashlib.sha1(h + chunk).digest()
        out.append(h)
    return out


class PrefixCache:
    """Prompt-prefix → block-chain cache over one :class:`BlockAllocator`
    (docs/SERVING.md "prefix sharing").

    Maps the cumulative token-hash of each FULL prompt block to the
    pool block holding its K/V. The cache holds exactly ONE reference
    per cached block, so a cached chain outlives the request that
    prefilled it and a later request with the same prefix re-attaches
    by ``incref`` instead of re-prefilling. Entries are LRU-ordered;
    eviction frees only blocks at refcount 1 (the cache is the sole
    holder — a block some live slot still maps is never yanked)."""

    def __init__(self, alloc: BlockAllocator):
        self.alloc = alloc
        #: digest -> block id, oldest-touched first (LRU order)
        self._chain: "OrderedDict[bytes, int]" = OrderedDict()
        #: counters for shared_block_fraction / the smoke's
        #: prefill-once assertion (host bookkeeping only)
        self.shared_tokens = 0
        self.prompt_tokens = 0

    def __len__(self) -> int:
        return len(self._chain)

    def match(self, hashes: Sequence[bytes],
              max_blocks: Optional[int] = None) -> List[int]:
        """Longest cached chain prefix of ``hashes`` (block ids, in
        chain order), capped at ``max_blocks``. Touches hits for LRU."""
        blocks: List[int] = []
        limit = len(hashes) if max_blocks is None else min(
            max_blocks, len(hashes))
        for h in hashes[:limit]:
            b = self._chain.get(h)
            if b is None:
                break
            self._chain.move_to_end(h)
            blocks.append(b)
        return blocks

    def register(self, hashes: Sequence[bytes], blocks: Sequence[int]
                 ) -> None:
        """Publish a prefilled chain: cache each (digest, block) pair
        not yet present, taking one reference per newly cached block. A
        digest already cached under a DIFFERENT block (two requests
        racing the same prefix through separate slots) keeps the first
        publication — the duplicate's blocks stay owned by its slot."""
        for h, b in zip(hashes, blocks):
            if h in self._chain:
                self._chain.move_to_end(h)
                continue
            self.alloc.incref([b])
            self._chain[h] = int(b)

    def evict(self, n_blocks: int) -> int:
        """Free up to ``n_blocks`` pool blocks by dropping LRU entries
        whose block the cache alone holds (refcount 1). Entries whose
        block is still shared by a live slot are skipped — their chain
        suffix may become unreachable until they age out, which is
        bounded by the same LRU walk. Returns blocks actually freed."""
        freed = 0
        for h in list(self._chain):
            if freed >= n_blocks:
                break
            b = self._chain[h]
            if self.alloc.refcount(b) == 1:
                del self._chain[h]
                self.alloc.decref([b])
                freed += 1
        return freed

    @property
    def shared_block_fraction(self) -> float:
        """Fraction of admitted prompt tokens served from cached
        chains instead of prefill (0.0 when nothing shared)."""
        if not self.prompt_tokens:
            return 0.0
        return self.shared_tokens / self.prompt_tokens


def new_block_table(spec: PagedPoolSpec, capacity: int) -> np.ndarray:
    """All-scratch table: every entry points at block 0 until the
    scheduler assigns real blocks on admission."""
    return np.zeros((capacity, spec.blocks_per_slot), np.int32)
