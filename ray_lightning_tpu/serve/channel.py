"""The driver→worker request channel (the inbound half of a replica's wire).

The process backend always had an OUTBOUND stream — workers push
``("tok", ...)``/``("done", ...)`` items over the WorkerGroup side
channel — but nothing flowed IN after spawn: a process replica's request
list was frozen at ``group.run(...)`` time, which is why dynamic serving
sessions were inline-only (docs/AUTOSCALE.md's old "limits" section).
This module is the missing inbound half: a seekable, append-only
per-replica command log the driver writes and every rank of the replica
group tails.

Design (docs/SERVING.md "the request channel"):

- **One JSONL command log per replica per epoch** at
  ``<run_dir>/channel/replica<r>/epoch<k>.jsonl``. Commands are single
  JSON lines ``{"seq": n, "op": ..., **payload}``. ``seq`` is monotonic
  per replica across epochs — a seq is never reused, so acks are
  unambiguous even across respawns.
- **Seekable, torn-write safe.** The reader remembers its byte offset
  and only consumes lines terminated by ``\\n`` — a half-flushed tail
  line is left for the next poll, never parsed. The writer appends and
  flushes line-atomically (single ``write()`` of the full line).
- **Acked.** Workers ack over the EXISTING result side channel as
  ``("ack", replica, seq)`` — one ack per poll *batch* carrying the
  highest seq consumed, not one per command (and never one per token:
  that is lint rule RLT504's per-token-channel-chatter).
- **Replay-safe across respawn.** A respawned worker must not see a
  log whose mid-file commands it already half-executed: on respawn the
  driver seals the old epoch and writes a FRESH epoch file containing
  re-submits for every assigned-but-unfinished request (original
  arrival order) plus the replica's control state (drain, pause). The
  worker is told its epoch at spawn and reads it from offset 0 —
  scheduler determinism (serve/scheduler.py: the schedule is a pure
  function of the request stream) makes the replayed streams bitwise.
- **Lockstep fan-in for TP groups.** Every rank of a tensor-parallel
  replica group tails the SAME file and applies the SAME commands in
  the SAME order, so all ranks hold identical scheduler state without a
  leader→follower broadcast; only rank 0 (the replica leader) emits
  results and acks. Single-host filesystems make this free; a
  multi-host replica group needs the run_dir on a shared filesystem
  (the standard TPU-pod NFS arrangement) — see docs/SERVING.md.

The channel is deliberately a FILE, not a socket: the worker main loop
is single-threaded and already blocks inside the engine tick, so the
natural cadence is poll-between-ticks; a file gives seekability (replay
is a reader reset, not a protocol negotiation) and survives the writer
— a driver crash leaves a complete, inspectable command history next to
the flight recorder's postmortem.
"""
from __future__ import annotations

import json
import os
import threading
from pathlib import Path
from typing import Any, Dict, List, Optional

from ray_lightning_tpu.utils import get_logger

log = get_logger(__name__)

#: ops a worker session understands (serve/driver.py _replica_session_main)
OPS = ("submit", "drain", "stop", "pause", "resume")


def channel_dir(run_dir: str | Path, replica: int) -> Path:
    return Path(run_dir) / "channel" / f"replica{replica}"


def epoch_path(run_dir: str | Path, replica: int, epoch: int) -> Path:
    return channel_dir(run_dir, replica) / f"epoch{epoch}.jsonl"


class ChannelWriter:
    """Driver-side command log writer for ONE replica.

    ``send`` appends one command line to the current epoch and returns
    its seq. ``begin_epoch`` seals the current file and starts the next
    one pre-populated with replayed commands — the respawn seam. Seqs
    keep counting across epochs (never reused).
    """

    def __init__(self, run_dir: str | Path, replica: int):
        self.replica = replica
        self.dir = channel_dir(run_dir, replica)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.epoch = 0
        self._seq = 0
        self._run_dir = run_dir
        # serializes the log I/O: driver threads append concurrently
        # (submit routing, eviction rerouting) while the respawn thread
        # rolls epochs. The append body stays INLINE in every locked
        # section — this lock exists to serialize exactly that I/O.
        self._lock = threading.Lock()
        self._f = open(epoch_path(run_dir, replica, 0), "a",
                       encoding="utf-8")

    @property
    def last_seq(self) -> int:
        """Highest seq handed out so far (0 = nothing sent)."""
        return self._seq

    def send(self, op: str, **payload: Any) -> int:
        """Append one command; returns its seq."""
        if op not in OPS:
            raise ValueError(f"unknown channel op {op!r} (one of {OPS})")
        with self._lock:
            self._seq += 1
            rec = {"seq": self._seq, "op": op}
            rec.update(payload)
            # one write() of the full line: a reader that races the
            # append either sees the line with its newline or not at all
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
            os.fsync(self._f.fileno())
            return self._seq

    def send_at(self, epoch: int, op: str,
                **payload: Any) -> Optional[int]:
        """Append one command IFF the writer is still on ``epoch``;
        returns its seq, or None when the epoch rolled underneath. The
        deferred-send seam: the driver decides a send under its session
        lock (recording the epoch it decided against) and performs it
        outside — if the replica respawned in between, `begin_epoch`'s
        replay already carries the command (it was computed from the
        same locked state), so appending it again would DUPLICATE the
        stream on the fresh epoch."""
        if op not in OPS:
            raise ValueError(f"unknown channel op {op!r} (one of {OPS})")
        with self._lock:
            if epoch != self.epoch:
                return None
            self._seq += 1
            rec = {"seq": self._seq, "op": op}
            rec.update(payload)
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
            os.fsync(self._f.fileno())
            return self._seq

    def begin_epoch(self, replay: List[Dict[str, Any]]) -> int:
        """Seal the current epoch and open the next, pre-populated with
        ``replay`` commands (each an ``{"op": ..., **payload}`` dict —
        seqs are assigned fresh here). Returns the new epoch number the
        respawned worker must be told to read. Atomic against
        `send`/`send_at`: a send deciding against the old epoch either
        lands before the roll (old file, superseded by the replay) or
        is dropped by its epoch guard."""
        with self._lock:
            self._f.close()
            self.epoch += 1
            self._f = open(
                epoch_path(self._run_dir, self.replica, self.epoch), "a",
                encoding="utf-8")
            for cmd in replay:
                payload = {k: v for k, v in cmd.items() if k != "op"}
                self._seq += 1
                rec = {"seq": self._seq, "op": cmd["op"]}
                rec.update(payload)
                self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
            os.fsync(self._f.fileno())
        log.info("replica %d channel epoch %d: %d replayed command(s)",
                 self.replica, self.epoch, len(replay))
        return self.epoch

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass


def _tail_lines(path: Path, offset: int):
    """Complete new JSONL records past ``offset``; returns
    ``(records, new_offset)``. Missing file or a torn tail line read as
    nothing-new (consume only through the last newline)."""
    try:
        with open(path, "rb") as f:
            f.seek(offset)
            chunk = f.read()
    except FileNotFoundError:
        return [], offset
    if not chunk:
        return [], offset
    end = chunk.rfind(b"\n")
    if end < 0:
        return [], offset
    out = [json.loads(line.decode("utf-8"))
           for line in chunk[:end + 1].splitlines() if line.strip()]
    return out, offset + end + 1


class ChannelReader:
    """Worker-side tail of one replica's command log for ONE epoch.

    ``poll()`` (the LEADER's read) returns every COMPLETE new command
    line since the last call (possibly none). ``take_upto(seq)`` (a
    FOLLOWER's read, driven by the leader's cursor log) returns exactly
    the commands with ``seq <= target``, buffering anything newer — the
    primitive that lets every rank of a TP replica group apply
    bit-identical command batches at bit-identical loop iterations.
    The file may not exist yet when the worker races the driver's
    first send — that reads as an empty poll, not an error.
    """

    def __init__(self, run_dir: str | Path, replica: int, epoch: int):
        self.replica = replica
        self.path = epoch_path(run_dir, replica, epoch)
        self._offset = 0
        self._buf: List[Dict[str, Any]] = []
        #: highest seq consumed — the value the leader acks after each
        #: non-empty poll batch (ONE ack per batch: RLT504 discipline)
        self.last_seq = 0

    def _fill(self) -> None:
        recs, self._offset = _tail_lines(self.path, self._offset)
        self._buf.extend(recs)

    def poll(self) -> List[Dict[str, Any]]:
        self._fill()
        out, self._buf = self._buf, []
        for cmd in out:
            self.last_seq = max(self.last_seq, int(cmd.get("seq", 0)))
        return out

    def take_upto(self, seq: int) -> List[Dict[str, Any]]:
        self._fill()
        out = [c for c in self._buf if int(c.get("seq", 0)) <= seq]
        self._buf = [c for c in self._buf if int(c.get("seq", 0)) > seq]
        for cmd in out:
            self.last_seq = max(self.last_seq, int(cmd.get("seq", 0)))
        return out


# ---- the replica-group cursor log (TP lockstep) ---------------------------
#
# Every rank of a tensor-parallel replica group holds a FULL host-side
# scheduler and must apply the same commands at the same loop iteration
# — otherwise two ranks' admission orders diverge and the SPMD step is
# fed different "replicated" inputs (a silent corruption, then a hang).
# Rather than a device-side broadcast per tick, rank 0 (the leader)
# journals every state-changing iteration to a cursor log next to the
# command log: "consumed commands up to seq N, then ticked (or not)".
# Followers do not evaluate scheduling policy at all — they REPLAY the
# leader's iteration journal, which is deterministic by the scheduler's
# purity guarantee. The journal is per-epoch like the command log, so
# respawn replay resets both together.


def cursor_path(run_dir: str | Path, replica: int, epoch: int) -> Path:
    return channel_dir(run_dir, replica) / f"epoch{epoch}.cursor"


class CursorWriter:
    """Leader-side iteration journal for one epoch (tp > 1 only)."""

    def __init__(self, run_dir: str | Path, replica: int, epoch: int):
        p = cursor_path(run_dir, replica, epoch)
        p.parent.mkdir(parents=True, exist_ok=True)
        self._f = open(p, "a", encoding="utf-8")

    def advance(self, seq: int, ticked: bool) -> None:
        self._f.write(json.dumps({"seq": seq, "tick": ticked}) + "\n")
        self._f.flush()

    def end(self) -> None:
        self._f.write(json.dumps({"end": True}) + "\n")
        self._f.flush()

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass


class CursorReader:
    """Follower-side tail of the leader's iteration journal."""

    def __init__(self, run_dir: str | Path, replica: int, epoch: int):
        self.path = cursor_path(run_dir, replica, epoch)
        self._offset = 0
        self._buf: List[Dict[str, Any]] = []

    def next(self) -> Optional[Dict[str, Any]]:
        """The next journal record, or None when the leader has not
        written one yet (the follower idles and retries)."""
        if not self._buf:
            recs, self._offset = _tail_lines(self.path, self._offset)
            self._buf.extend(recs)
        return self._buf.pop(0) if self._buf else None


def ack_item(replica: int, seq: int) -> tuple:
    """The wire item a replica leader puts on the result side channel
    after consuming a poll batch: highest seq consumed, once per batch."""
    return ("ack", replica, seq)


def request_to_wire(req) -> Dict[str, Any]:
    """serve.scheduler.Request -> JSON-safe payload (prompt as a list)."""
    return {
        "rid": req.rid,
        "prompt": [int(t) for t in req.prompt],
        "max_new_tokens": int(req.max_new_tokens),
        "temperature": float(req.temperature),
        "top_k": None if req.top_k is None else int(req.top_k),
        "seed": int(req.seed),
        "eos_id": None if req.eos_id is None else int(req.eos_id),
        "arrival": float(req.arrival),
        "priority": req.priority,
    }


def request_from_wire(d: Dict[str, Any]):
    """Inverse of ``request_to_wire`` (import deferred: scheduler pulls
    in jax, and the channel itself is host-only)."""
    import numpy as np

    from ray_lightning_tpu.serve.scheduler import Request

    return Request(
        rid=d["rid"],
        prompt=np.asarray(d["prompt"], np.int32),
        max_new_tokens=int(d["max_new_tokens"]),
        temperature=float(d.get("temperature", 0.0)),
        top_k=d.get("top_k"),
        seed=int(d.get("seed", 0)),
        eos_id=d.get("eos_id"),
        arrival=float(d.get("arrival", 0.0)),
        # absent on command logs written before traffic classes
        priority=d.get("priority", "standard"),
    )
