"""Inference/serving subsystem: continuous-batching decode engine on a
block-paged KV cache, multiplexed across replica groups (docs/SERVING.md).

Layers:
  * kv_cache.py   — the paged pool + slot block tables + host allocator
  * engine.py     — ONE jitted continuous-batching step (decode lane for
                    every slot + a cond-gated prefill-chunk lane), fixed
                    shapes so request churn never recompiles
  * scheduler.py  — host-side slot lifecycle: admission queue, block
                    reservation/growth, retirement, preemption
  * driver.py     — request multiplexing over replica groups (inline or
                    runtime.WorkerGroup processes) with supervised
                    respawn + deterministic replay on replica death
  * audit.py      — tracecheck audit of the decode step + the serving
                    HBM plan leg
  * sweep.py      — block-size autotune for BOTH paged kernels
                    (correctness matrix everywhere, wall-clock on TPU,
                    JSON artifact -> ``apply_autotune``)
  * cli.py        — ``python -m ray_lightning_tpu serve``
                    (+ --smoke, --autotune)
"""
from ray_lightning_tpu.serve.engine import DecodeEngine, EngineConfig
from ray_lightning_tpu.serve.kv_cache import (
    BlockAllocator,
    PagedPoolSpec,
    init_pool,
    serve_kv_plan_bytes,
)
from ray_lightning_tpu.serve.scheduler import Request, Scheduler

__all__ = [
    "BlockAllocator",
    "DecodeEngine",
    "EngineConfig",
    "PagedPoolSpec",
    "Request",
    "Scheduler",
    "init_pool",
    "serve_kv_plan_bytes",
]
