"""Block-size autotune for BOTH paged attention kernels
(docs/SERVING.md "block-size autotune").

The paged decode kernel streams one pool block per grid step and the
paged prefill kernel streams one pool block per (row, q-tile) step —
``block_size`` IS the KV tile, so it sets the DMA granularity, the
VMEM working set, and (through ``blocks_per_slot = span / block_size``)
the grid depth. The right value is a hardware question the planner
cannot answer from byte math, so this module measures it:

  * **correctness matrix** — every candidate geometry runs BOTH
    kernels in interpret mode (`dispatch.force_pallas` off-TPU)
    against their XLA reference twins on a deterministic random case,
    plus a ``shared_spec`` cell replaying the decode gather through a
    FORKED table (slots aliasing a shared prefix chain — the prefix
    cache's copy-on-write geometry) and the speculative verify's
    k-wide chunk where the prefill kernel tiles it. This works on any
    host, including CPU CI, and is the part the tier-1 tests pin
    (`tests/test_paged_prefill.py`).
  * **wall-clock timing** — on a real TPU backend each correct
    candidate's kernels are jitted, warmed, and timed best-of-N;
    without one the timing leg degrades to a structured
    ``{"skipped": "backend unavailable"}`` (the bench.py discipline —
    a skip is recorded, never invented numbers).

The result is a JSON **artifact** keyed by (model fingerprint,
topology) that the engine can consume: `apply_autotune(engine_cfg,
artifact)` returns an `EngineConfig` re-geometried to the winning
candidate (same per-slot span — the sweep never changes capacity
semantics, only the tiling), refusing a model-fingerprint mismatch.
`python -m ray_lightning_tpu serve <preset> --autotune out.json`
writes one from the CLI.
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional, Sequence

__all__ = [
    "DEFAULT_BLOCK_SIZES", "SweepCandidate", "candidate_grid",
    "model_fingerprint", "sweep_paged_kernels", "save_artifact",
    "load_artifact", "apply_autotune",
]

#: candidate KV-tile widths. 8 is the TPU sublane floor
#: (`paged_shapes_supported` rejects smaller); 256 tokens is past the
#: point where a bigger tile stops amortizing anything and only grows
#: the VMEM working set.
DEFAULT_BLOCK_SIZES = (8, 16, 32, 64, 128, 256)


@dataclasses.dataclass(frozen=True)
class SweepCandidate:
    """One pool geometry under test. The per-slot token span
    (``block_size * blocks_per_slot``) is held CONSTANT across the
    grid — the sweep tunes tiling, never capacity."""

    block_size: int
    blocks_per_slot: int

    @property
    def span(self) -> int:
        return self.block_size * self.blocks_per_slot


def candidate_grid(engine_cfg,
                   block_sizes: Optional[Sequence[int]] = None
                   ) -> list:
    """Candidate geometries preserving ``engine_cfg``'s per-slot span.

    A block size qualifies when it divides the span and meets the
    kernels' sublane floor (% 8); span-constancy keeps the prefill
    chunk inside the slot for every candidate (the EngineConfig
    contract already holds for the incumbent). The incumbent geometry
    is always in the grid (so the sweep can only confirm or beat
    it)."""
    span = engine_cfg.block_size * engine_cfg.blocks_per_slot
    sizes = sorted(set(block_sizes or DEFAULT_BLOCK_SIZES)
                   | {engine_cfg.block_size})
    return [SweepCandidate(block_size=bs, blocks_per_slot=span // bs)
            for bs in sizes
            if bs >= 8 and bs % 8 == 0 and bs <= span
            and span % bs == 0]


def model_fingerprint(model_cfg) -> str:
    """The attention-shape identity an artifact is valid for — the
    fields BOTH kernels tile on. Everything else (vocab, hidden dim,
    weights) is irrelevant to the tiling decision."""
    import numpy as np

    return (f"L{model_cfg.n_layers}-H{model_cfg.n_heads}"
            f"-KV{model_cfg.n_kv_heads}-hd{model_cfg.head_dim}"
            f"-{np.dtype(model_cfg.dtype).name}")


def _correctness_case(model_cfg, engine_cfg, cand: SweepCandidate,
                      seed: int = 0) -> dict:
    """Interpret-mode parity of BOTH kernels vs their XLA reference
    twins on this candidate geometry — deterministic random K/V/q,
    ragged pads, a table tail past the written length. Returns
    per-kernel ``{"ok", "max_err"}`` (or ``{"ok": False, "error"}``
    when a kernel refuses the shape or dies)."""
    import numpy as np

    import jax.numpy as jnp

    from ray_lightning_tpu.ops import dispatch
    from ray_lightning_tpu.ops.attention import (
        paged_attention_reference, paged_prefill_reference,
    )
    from ray_lightning_tpu.ops.pallas.paged_attention import (
        paged_attention_pallas, paged_shapes_supported,
    )
    from ray_lightning_tpu.ops.pallas.paged_prefill import (
        paged_prefill_pallas, paged_prefill_shapes_supported,
    )

    rng = np.random.default_rng(seed)
    H, HKV, HD = (model_cfg.n_heads, model_cfg.n_kv_heads,
                  model_cfg.head_dim)
    P, M = cand.block_size, cand.blocks_per_slot
    C = min(engine_cfg.capacity, 4)
    B = min(engine_cfg.prefill_batch, C)
    CH = min(engine_cfg.prefill_chunk, cand.span)
    n_blocks = 1 + C * M
    pool_k = jnp.asarray(rng.normal(size=(n_blocks, P, HKV, HD)),
                         jnp.float32)
    pool_v = jnp.asarray(rng.normal(size=(n_blocks, P, HKV, HD)),
                         jnp.float32)
    tables = jnp.asarray(
        1 + (np.arange(C * M) % (n_blocks - 1)).reshape(C, M),
        jnp.int32)
    out: dict = {}

    # decode lane: one query token per slot, ragged lengths
    q1 = jnp.asarray(rng.normal(size=(C, H, HD)), jnp.float32)
    lengths = jnp.asarray(
        rng.integers(1, cand.span + 1, size=(C,)), jnp.int32)
    pads = jnp.zeros((C,), jnp.int32)
    if not paged_shapes_supported((C, H, HD), (n_blocks, P, HKV, HD)):
        out["decode"] = {"ok": False,
                         "error": "shape not supported by the kernel"}
    else:
        try:
            ref = paged_attention_reference(q1, pool_k, pool_v, tables,
                                            lengths, pads)
            with dispatch.force_pallas():
                got = paged_attention_pallas(q1, pool_k, pool_v,
                                             tables, lengths, pads)
            err = float(jnp.max(jnp.abs(got - ref)))
            out["decode"] = {"ok": bool(err < 2e-5), "max_err": err}
        except Exception as exc:  # noqa: BLE001 — recorded, not raised
            out["decode"] = {"ok": False,
                             "error": f"{type(exc).__name__}: "
                                      f"{str(exc)[:160]}"}

    # prefill lane: a CH-wide chunk mid-prompt, ragged left pads
    qc = jnp.asarray(rng.normal(size=(B, CH, H, HD)), jnp.float32)
    pos = max(0, min(cand.span - CH, cand.span // 2))
    pad = jnp.asarray([min(i * 2, max(pos - 1, 0))
                       for i in range(B)], jnp.int32)
    if not paged_prefill_shapes_supported((B, CH, H, HD),
                                          (n_blocks, P, HKV, HD)):
        out["prefill"] = {"ok": False,
                          "error": "shape not supported by the kernel"}
    else:
        try:
            ref = paged_prefill_reference(qc, pool_k, pool_v,
                                          tables[:B], pos, pad=pad)
            with dispatch.force_pallas():
                got = paged_prefill_pallas(qc, pool_k, pool_v,
                                           tables[:B], pos, pad=pad)
            err = float(jnp.max(jnp.abs(got - ref)))
            out["prefill"] = {"ok": bool(err < 2e-5), "max_err": err}
        except Exception as exc:  # noqa: BLE001 — recorded, not raised
            out["prefill"] = {"ok": False,
                              "error": f"{type(exc).__name__}: "
                                       f"{str(exc)[:160]}"}

    # shared-prefix + speculative cell: the prefix cache makes slots
    # ALIAS each other's prefix blocks (fork-on-write tables), so the
    # decode kernel must gather correctly through an aliased table —
    # every slot's first half points at slot 0's chain, tails stay
    # owned. Piggybacked: the speculative verify is a NARROW k-wide
    # chunk mid-slot; where the prefill kernel tiles that width the
    # pair must agree there too (where it does not, the engine runs
    # the verify on the reference lane — recorded as a skip, not a
    # failure).
    forked = np.asarray(tables).copy()
    half = max(1, M // 2)
    forked[:, :half] = forked[0, :half]
    forked = jnp.asarray(forked, jnp.int32)
    if not paged_shapes_supported((C, H, HD), (n_blocks, P, HKV, HD)):
        out["shared_spec"] = {
            "ok": False, "error": "shape not supported by the kernel"}
    else:
        try:
            ref = paged_attention_reference(q1, pool_k, pool_v, forked,
                                            lengths, pads)
            with dispatch.force_pallas():
                got = paged_attention_pallas(q1, pool_k, pool_v,
                                             forked, lengths, pads)
            err = float(jnp.max(jnp.abs(got - ref)))
            cell = {"ok": bool(err < 2e-5), "max_err": err}
            K = 4                       # DraftConfig's default k
            qk = jnp.asarray(rng.normal(size=(B, K, H, HD)),
                             jnp.float32)
            vpos = max(0, min(cand.span - K, cand.span // 2))
            vpad = jnp.zeros((B,), jnp.int32)
            if paged_prefill_shapes_supported(
                    (B, K, H, HD), (n_blocks, P, HKV, HD)):
                refv = paged_prefill_reference(qk, pool_k, pool_v,
                                               forked[:B], vpos,
                                               pad=vpad)
                with dispatch.force_pallas():
                    gotv = paged_prefill_pallas(qk, pool_k, pool_v,
                                                forked[:B], vpos,
                                                pad=vpad)
                verr = float(jnp.max(jnp.abs(gotv - refv)))
                cell["verify_chunk"] = {"ok": bool(verr < 2e-5),
                                        "max_err": verr}
                cell["ok"] = bool(cell["ok"]
                                  and cell["verify_chunk"]["ok"])
            else:
                cell["verify_chunk"] = {
                    "skipped": "k-wide chunk not tiled — the "
                               "speculative verify runs the "
                               "reference lane"}
            out["shared_spec"] = cell
        except Exception as exc:  # noqa: BLE001 — recorded, not raised
            out["shared_spec"] = {"ok": False,
                                  "error": f"{type(exc).__name__}: "
                                           f"{str(exc)[:160]}"}
    return out


def _time_candidate(model_cfg, engine_cfg, cand: SweepCandidate,
                    repeats: int = 5) -> dict:
    """Best-of-N wall clock for both kernels on a REAL accelerator
    backend — compiled once, warmed once, `block_until_ready` fenced.
    Callers gate on the backend; this function assumes one."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ray_lightning_tpu.ops import dispatch
    from ray_lightning_tpu.ops.pallas.paged_attention import (
        paged_attention_pallas,
    )
    from ray_lightning_tpu.ops.pallas.paged_prefill import (
        paged_prefill_pallas,
    )

    rng = np.random.default_rng(1)
    H, HKV, HD = (model_cfg.n_heads, model_cfg.n_kv_heads,
                  model_cfg.head_dim)
    P, M = cand.block_size, cand.blocks_per_slot
    C, B = engine_cfg.capacity, engine_cfg.prefill_batch
    CH = min(engine_cfg.prefill_chunk, cand.span)
    n_blocks = 1 + C * M
    dtype = jnp.bfloat16 if "bfloat16" in str(model_cfg.dtype) \
        else jnp.float32
    pool_k = jnp.asarray(rng.normal(size=(n_blocks, P, HKV, HD)),
                         dtype)
    pool_v = jnp.asarray(rng.normal(size=(n_blocks, P, HKV, HD)),
                         dtype)
    tables = jnp.asarray(
        1 + (np.arange(C * M) % (n_blocks - 1)).reshape(C, M),
        jnp.int32)
    q1 = jnp.asarray(rng.normal(size=(C, H, HD)), dtype)
    lengths = jnp.full((C,), cand.span, jnp.int32)
    pads = jnp.zeros((C,), jnp.int32)
    qc = jnp.asarray(rng.normal(size=(B, CH, H, HD)), dtype)
    pos = max(0, cand.span - CH)
    pad = jnp.zeros((B,), jnp.int32)

    def best_of(fn, *args) -> float:
        with dispatch.force_pallas():
            jfn = jax.jit(fn)
            jfn(*args).block_until_ready()       # compile + warm
            best = float("inf")
            for _ in range(repeats):
                t0 = time.perf_counter()
                jfn(*args).block_until_ready()
                best = min(best, time.perf_counter() - t0)
        return best

    return {
        "decode_wall_s": best_of(
            lambda q, k, v: paged_attention_pallas(
                q, k, v, tables, lengths, pads), q1, pool_k, pool_v),
        "prefill_wall_s": best_of(
            lambda q, k, v: paged_prefill_pallas(
                q, k, v, tables[:B], pos, pad=pad), qc, pool_k, pool_v),
    }


def sweep_paged_kernels(model_cfg, engine_cfg, *,
                        block_sizes: Optional[Sequence[int]] = None,
                        topology: str = "v5p-8",
                        repeats: int = 5) -> dict:
    """Run the sweep and return the artifact dict.

    Correctness runs everywhere (interpret mode); timing runs only on
    a real non-CPU backend and otherwise records the structured skip.
    The winner is the fastest candidate whose BOTH kernels passed
    correctness (combined decode+prefill wall); without timing the
    incumbent geometry wins by default, labeled
    ``winner_source: "default-untimed"`` so a consumer can tell a
    measured answer from a fallback."""
    import jax

    grid = candidate_grid(engine_cfg, block_sizes)
    backend = jax.default_backend()
    # timing is meaningful ONLY on a real TPU: everywhere else the
    # pallas kernels run in interpret mode (`dispatch.interpret_mode`),
    # and interpreter wall-clock would crown a winner by interpreter
    # overhead — a GPU host degrades to the structured skip like CPU
    timed = backend == "tpu"
    results = []
    for cand in grid:
        entry = {
            "block_size": cand.block_size,
            "blocks_per_slot": cand.blocks_per_slot,
            **_correctness_case(model_cfg, engine_cfg, cand),
        }
        ok = (entry["decode"].get("ok")
              and entry["prefill"].get("ok")
              and entry["shared_spec"].get("ok"))
        if timed and ok:
            try:
                entry["timing"] = _time_candidate(
                    model_cfg, engine_cfg, cand, repeats=repeats)
            except Exception as exc:  # noqa: BLE001 — recorded
                entry["timing"] = {
                    "error": f"{type(exc).__name__}: {str(exc)[:160]}"}
        elif not timed:
            entry["timing"] = {
                "skipped": f"backend unavailable ({backend})"}
        results.append(entry)

    passing = [r for r in results
               if r["decode"].get("ok") and r["prefill"].get("ok")
               and r["shared_spec"].get("ok")]
    winner, source = None, None
    measured = [r for r in passing
                if "decode_wall_s" in (r.get("timing") or {})]
    if measured:
        best = min(measured,
                   key=lambda r: (r["timing"]["decode_wall_s"]
                                  + r["timing"]["prefill_wall_s"]))
        winner = {"block_size": best["block_size"],
                  "blocks_per_slot": best["blocks_per_slot"]}
        source = "measured"
    elif passing:
        incumbent = [r for r in passing
                     if r["block_size"] == engine_cfg.block_size]
        best = incumbent[0] if incumbent else passing[0]
        winner = {"block_size": best["block_size"],
                  "blocks_per_slot": best["blocks_per_slot"]}
        source = "default-untimed"
    return {
        "kind": "rlt-paged-kernel-autotune",
        "model": model_fingerprint(model_cfg),
        "topology": topology,
        "backend": backend,
        "span": engine_cfg.block_size * engine_cfg.blocks_per_slot,
        "capacity": engine_cfg.capacity,
        "prefill_chunk": engine_cfg.prefill_chunk,
        "prefill_batch": engine_cfg.prefill_batch,
        "results": results,
        "winner": winner,
        "winner_source": source,
    }


def save_artifact(artifact: dict, path: str) -> None:
    """Atomic JSON write (tmp + replace — the checkpoint meta
    discipline: a killed sweep never leaves a torn artifact)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


def load_artifact(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("kind") != "rlt-paged-kernel-autotune":
        raise ValueError(
            f"{path} is not a paged-kernel autotune artifact "
            f"(kind={doc.get('kind')!r})")
    return doc


def apply_autotune(engine_cfg, artifact: dict, *, model_cfg=None):
    """The engine-consumable seam: re-geometry ``engine_cfg`` to the
    artifact's winning candidate.

    Refuses an artifact with no winner, a per-slot span that differs
    from the config's (the sweep holds span constant — a mismatched
    span means the artifact was swept for a different deployment), or
    — when ``model_cfg`` is given — a model fingerprint mismatch (a
    v5p-swept llama3-8b artifact must not silently re-tile a tiny
    CPU config)."""
    winner = artifact.get("winner")
    if not winner:
        raise ValueError(
            "autotune artifact has no winner (no candidate passed "
            "correctness) — refusing to re-geometry the engine")
    if model_cfg is not None:
        want = model_fingerprint(model_cfg)
        if artifact.get("model") != want:
            raise ValueError(
                f"autotune artifact was swept for model "
                f"{artifact.get('model')!r}, not {want!r}")
    span = engine_cfg.block_size * engine_cfg.blocks_per_slot
    if artifact.get("span") != span:
        raise ValueError(
            f"autotune artifact span {artifact.get('span')} != engine "
            f"span {span} — swept for a different slot geometry")
    return dataclasses.replace(
        engine_cfg, block_size=winner["block_size"],
        blocks_per_slot=winner["blocks_per_slot"])
