"""The continuous-batching decode engine: ONE jitted step for a serving
replica's whole lifetime.

Shape discipline is the design (docs/SERVING.md): the step is compiled
once for a fixed slot ``capacity``, pool geometry, and prefill chunk
width; admission, retirement, and per-request sampling knobs arrive as
*runtime* int/float arrays, so request churn can never retrace — the
engine pins its own compile count (`compile_count`) and the smoke gate
asserts it stays 1 across a full churned workload.

One step does two things, both masked, both fixed-shape:

  * **decode lane** — for every slot: split its RNG, sample the next
    token from the slot's carried ``last_logits`` (greedy /
    temperature / top-k chosen by *runtime* per-slot values), run the
    model's single-token cache path on the sampled token over the
    slot's gathered paged view, and scatter the new K/V into the pool
    at ``pos``. Slots not in the decode phase are redirected to the
    scratch block and their state is `where`-masked through unchanged.
  * **prefill lane** — at most one slot advances its prompt by one
    fixed-width chunk through the model's chunked cache path
    (``lax.cond``-gated: a step with no admission pays no prefill
    compute). The final chunk also projects the last real prompt row
    through the lm_head into ``last_logits`` — the logits the decode
    lane will sample the first generated token from, exactly where
    single-stream `generate`'s prefill leaves it.

Numerics: every lane reuses the model's OWN cache path (`Llama.apply`
vmapped per slot), the sampling math mirrors `generate`'s per step
(same split sequence, same categorical call shape), and every padded /
scratch position is masked to exact-zero influence before softmax —
per-request token streams are **bitwise-identical** to independent
single-stream `generate` runs on the XLA reference path (test-pinned;
the smoke gate re-proves it on every format.sh run).

HBM: the pool is donated through the step along with ``last_logits``,
so steady-state serving holds one pool, not two. On the **reference
attention path** the decode lane additionally materializes one dense
gathered view per step; on the **fused path**
(`ops.pallas.paged_attention`, selected at build time by
`ops.attention.paged_attention_uses_pallas` — the flash dispatch
discipline) the decode lane consumes the pool directly through the
block tables and that view never exists (`serve/audit.py` prices both
stories in the ``plan --serve`` leg).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_lightning_tpu.serve.kv_cache import (
    PagedPoolSpec,
    init_pool,
    pool_partition_spec,
    validate_pool_tp,
)


@dataclasses.dataclass(frozen=True)
class DraftConfig:
    """Speculative-decoding knob (docs/SERVING.md "speculative
    decoding"): a small DRAFT model proposes ``k - 1`` greedy tokens
    per tick and the target verifies all ``k`` (the carried token plus
    the proposals) in ONE k-wide chunk riding the same multi-token
    machinery as chunked prefill. Greedy accept/reject keeps the
    emitted stream token-identical to plain greedy decode; ``k = 1``
    degenerates to the base engine (no proposals, one verify row)."""

    #: tokens verified per tick (1 carried + k-1 draft proposals)
    k: int = 4

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"draft k must be >= 1, got {self.k}")


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static shape of one serving replica's compiled step."""

    #: concurrent request slots (the decode lane's fixed batch)
    capacity: int = 8
    #: tokens per pool block
    block_size: int = 16
    #: per-slot block-table width — caps prompt + generation length at
    #: ``blocks_per_slot * block_size``
    blocks_per_slot: int = 8
    #: pool blocks (None = dense worst case: capacity * blocks_per_slot
    #: + scratch). Smaller oversubscribes — the paged bet.
    n_blocks: Optional[int] = None
    #: prefill chunk width: one admitting slot advances this many prompt
    #: tokens per step (TTFT = ceil(prompt / chunk) steps + one sample)
    prefill_chunk: int = 32
    #: prefill lane batch (ROADMAP 1d): up to this many queued prompts
    #: advance TOGETHER each tick through the model's left-padded
    #: ragged-batch cache path (`generate(prompt_lengths=...)`'s pad
    #: mechanism): the scheduler admits FIFO groups right-aligned to a
    #: shared chunk-multiple width, each row's pad columns masked out
    #: of attention forever. 1 (default) lowers the identical
    #: historical single-slot program — no pad inputs anywhere.
    prefill_batch: int = 1
    #: speculative decoding (None = the base single-token step). Set,
    #: the engine requires a draft model/params at construction, runs
    #: `build_spec_step`'s k-token verify tick, and the scheduler
    #: enforces greedy-only sampling plus the k-1 slot-overflow
    #: headroom in `validate_request`.
    draft: Optional[DraftConfig] = None

    def __post_init__(self):
        if isinstance(self.draft, dict):
            # survive the dataclasses.asdict round trip the process
            # replica backend ships configs through
            object.__setattr__(self, "draft", DraftConfig(**self.draft))
        if self.capacity < 1:
            raise ValueError("capacity must be >= 1")
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if not 1 <= self.prefill_batch <= self.capacity:
            raise ValueError(
                f"prefill_batch {self.prefill_batch} must be within "
                f"[1, capacity={self.capacity}]")
        if self.prefill_chunk > self.blocks_per_slot * self.block_size:
            # the scheduler slides the chunk window back to keep the
            # full width inside the slot; a chunk wider than the slot
            # itself has no valid window at all
            raise ValueError(
                f"prefill_chunk {self.prefill_chunk} exceeds "
                f"max_slot_len "
                f"{self.blocks_per_slot * self.block_size}")
        if self.draft is not None and self.prefill_batch != 1:
            raise ValueError(
                "speculative decoding (draft=...) requires "
                "prefill_batch == 1 — the verify chunk rides the "
                "single-slot program")
        if self.draft is not None and \
                self.draft.k > self.blocks_per_slot * self.block_size:
            raise ValueError(
                f"draft k {self.draft.k} exceeds max_slot_len "
                f"{self.blocks_per_slot * self.block_size}")

    @property
    def pool_spec(self) -> PagedPoolSpec:
        n = self.n_blocks
        if n is None:
            n = 1 + self.capacity * self.blocks_per_slot
        return PagedPoolSpec(n_blocks=n, block_size=self.block_size,
                             blocks_per_slot=self.blocks_per_slot)

    @property
    def max_slot_len(self) -> int:
        return self.pool_spec.gathered_len


def _sample_one(logits, key, temp, top_k):
    """Per-slot sampling, runtime-switched, mirroring `generate`'s
    static-python `sample` bit for bit per mode:

      * temp == 0      -> argmax (the categorical draw is computed and
                          discarded — fixed shapes beat a branch)
      * top_k > 0      -> k-th-largest threshold filter; the threshold
                          VALUE from a descending sort equals
                          ``lax.top_k(x, k)[0][:, -1]`` for runtime k
      * else           -> plain temperature sampling

    The categorical call takes ``[1, V]`` exactly like `generate`'s
    B=1 call so the drawn bits match under vmap."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temp, jnp.finfo(logits.dtype).tiny)
    srt = jnp.sort(scaled)[::-1]
    kth = srt[jnp.clip(top_k, 1, scaled.shape[0]) - 1]
    filtered = jnp.where(scaled >= kth, scaled, -jnp.inf)
    sampled_from = jnp.where(top_k > 0, filtered, scaled)
    drawn = jax.random.categorical(
        key, sampled_from[None, :])[0].astype(jnp.int32)
    return jnp.where(temp == 0.0, greedy, drawn)


def build_step(model, cfg: EngineConfig, fused: bool = False,
               fused_prefill: bool = False):
    """The jitted continuous-batching step for ``model`` (a
    `models.llama.Llama` instance) under ``cfg``. Returned uncompiled —
    `DecodeEngine` jits it with the pool/logits donated; `serve.audit`
    traces it abstractly.

    ``fused`` selects the decode lane at BUILD time (the dispatch
    decision is static, like a kernel choice — it can never retrace):

      * False — the reference lane: the model's single-token cache path
        vmapped per slot over a dense gathered view of each slot's
        blocks. The bitwise anchor against single-stream `generate()`.
      * True — the fused lane: ONE batched model call whose cache is
        the pool itself (`models.llama` paged branch +
        `ops.attention.paged_attention`); the per-slot dense view is
        never materialized. Pinned to the reference lane within the
        flash kernel's tolerance discipline (tests/test_paged_attention).

    ``fused_prefill`` selects the PREFILL lane the same way
    (independently — the two kernels have separate shape gates):

      * False — the reference lane: gather the group's blocks into a
        dense ``[L, B, G, Hkv, hd]`` view and run the model's chunked
        cache path over it (the historical program).
      * True — the fused lane: the model's paged-prefill branch
        scatters the chunk's K/V straight into owned pool blocks
        (scratch-redirected for vacant rows) and
        `ops.attention.paged_prefill` attends causally through the
        block tables — the per-group gather never exists
        (tests/test_paged_prefill).
    """
    mcfg = model.cfg
    spec = cfg.pool_spec
    L, HKV, HD = mcfg.n_layers, mcfg.n_kv_heads, mcfg.head_dim
    C, P, G, CH = cfg.capacity, spec.block_size, spec.gathered_len, \
        cfg.prefill_chunk
    B = cfg.prefill_batch

    def _decode_one(params, tok, kc, vc, pos):
        # the model's OWN single-token cache path ([1, 1] batch), new
        # K/V extracted at the write position for the pool scatter
        logits, (nk, nv) = model.apply(
            {"params": params}, tok[None, None],
            cache=(kc[:, None], vc[:, None]), pos=pos)
        k_tok = jax.lax.dynamic_slice_in_dim(nk[:, 0], pos, 1,
                                             axis=1)[:, 0]
        v_tok = jax.lax.dynamic_slice_in_dim(nv[:, 0], pos, 1,
                                             axis=1)[:, 0]
        return logits[0, 0], k_tok, v_tok

    def _decode_one_padded(params, tok, kc, vc, pos, pad):
        # the left-pad-aware twin (prefill_batch > 1): same program
        # with the model's pad mask/RoPE shift live (pad == 0 rows
        # compute bitwise-identically to `_decode_one`)
        logits, (nk, nv) = model.apply(
            {"params": params}, tok[None, None],
            cache=(kc[:, None], vc[:, None]), pos=pos, pad=pad[None])
        k_tok = jax.lax.dynamic_slice_in_dim(nk[:, 0], pos, 1,
                                             axis=1)[:, 0]
        v_tok = jax.lax.dynamic_slice_in_dim(nv[:, 0], pos, 1,
                                             axis=1)[:, 0]
        return logits[0, 0], k_tok, v_tok

    def _write_index(tables, pos, decoding):
        # where this tick's K/V token lands; slots not in the decode
        # phase are redirected to the scratch block
        bi = jnp.where(
            decoding,
            jnp.take_along_axis(tables, (pos // P)[:, None],
                                axis=1)[:, 0],
            0)
        off = jnp.where(decoding, pos % P, 0)
        return bi, off

    def _decode_reference(params, pool_k, pool_v, tables, pos, decoding,
                          emitted, slot_pad):
        # one dense gathered view per step — the copy the fused lane
        # retires (charged by serve_memory_summary on this path only)
        gk = pool_k[:, tables].reshape(L, C, G, HKV, HD)
        gv = pool_v[:, tables].reshape(L, C, G, HKV, HD)
        if slot_pad is None:
            logits2, k_tok, v_tok = jax.vmap(
                _decode_one, in_axes=(None, 0, 1, 1, 0),
                out_axes=(0, 1, 1),
            )(params, emitted, gk, gv, pos)
        else:
            logits2, k_tok, v_tok = jax.vmap(
                _decode_one_padded, in_axes=(None, 0, 1, 1, 0, 0),
                out_axes=(0, 1, 1),
            )(params, emitted, gk, gv, pos, slot_pad)
        bi, off = _write_index(tables, pos, decoding)
        pool_k = pool_k.at[:, bi, off].set(k_tok)
        pool_v = pool_v.at[:, bi, off].set(v_tok)
        return pool_k, pool_v, logits2

    def _decode_fused(params, pool_k, pool_v, tables, pos, decoding,
                      emitted, slot_pad):
        # the fused lane: the pool IS the cache — the model's paged
        # branch scatters the new K/V at the (scratch-redirected) write
        # index and `paged_attention` streams block-table-named tiles,
        # so no [L, C, G, Hkv, hd] copy exists on this path
        from ray_lightning_tpu.ops.attention import PagedDecodeView

        bi, off = _write_index(tables, pos, decoding)
        # use_pallas=True (static aux) bakes the build-time decision
        # into the program: fused=True MEANS the kernel, wherever and
        # whenever the jit happens to trace (the shape gate already
        # passed at DecodeEngine init)
        view = PagedDecodeView(tables=tables, lengths=pos + 1,
                               write_block=bi, write_offset=off,
                               use_pallas=True)
        logits2, (pool_k, pool_v) = model.apply(
            {"params": params}, emitted[:, None],
            cache=(pool_k, pool_v), pos=pos, pad=slot_pad, paged=view)
        return pool_k, pool_v, logits2[:, 0]

    _decode = _decode_fused if fused else _decode_reference

    def _sample(last_logits, decoding, temp, top_k, rngs):
        keys = jax.random.wrap_key_data(rngs)
        split = jax.vmap(jax.random.split)(keys)
        nxt, sub = split[:, 0], split[:, 1]
        # RNG advances exactly once per EMITTED token (generate's body
        # splits once per loop trip) — idle/prefilling slots hold still
        new_rngs = jnp.where(decoding[:, None],
                             jax.random.key_data(nxt), rngs)
        emitted = jax.vmap(_sample_one)(last_logits, sub, temp, top_k)
        return emitted, new_rngs

    if B == 1:
        def step(params, pool_k, pool_v, last_logits, tables, pos,
                 decoding, temp, top_k, rngs, prefill_slot,
                 prefill_tokens, prefill_pos, prefill_last_row):
            """One engine tick. Donated: pool_k, pool_v, last_logits
            (positions 1-3 of the signature; `DecodeEngine` owns them).

            Host-owned runtime inputs (plain numpy per call):
              tables   [C, M] i32   slot -> pool block ids (0 = scratch)
              pos      [C]    i32   tokens written to each slot's cache
              decoding [C]    bool  slot is in the decode phase
              temp     [C]    f32 / top_k [C] i32 / rngs [C, 2] u32
              prefill_slot  i32     slot taking this step's chunk (-1
                                    none)
              prefill_tokens [CH] i32 / prefill_pos i32
              prefill_last_row i32  row of the last REAL prompt token
                                    within this chunk (-1: prompt
                                    continues)

            Returns (pool_k, pool_v, last_logits, rngs', emitted [C]
            i32). ``emitted[s]`` is meaningful only where
            ``decoding[s]`` — the scheduler masks by its own phase
            bookkeeping.
            """
            # ---- decode lane: sample, then advance every slot --------
            emitted, new_rngs = _sample(last_logits, decoding, temp,
                                        top_k, rngs)
            pool_k, pool_v, logits2 = _decode(
                params, pool_k, pool_v, tables, pos, decoding, emitted,
                None)
            last_logits = jnp.where(decoding[:, None], logits2,
                                    last_logits)

            # ---- prefill lane: one chunk for one admitting slot ------
            def do_prefill(pool_k, pool_v, last_logits):
                slot = jnp.maximum(prefill_slot, 0)
                row = tables[slot]
                if fused_prefill:
                    # the fused lane: the pool IS the cache — the
                    # model's paged-prefill branch scatters the CH-wide
                    # chunk at the table-named write indices and
                    # `paged_prefill` streams block tiles, so the
                    # [L, 1, G, Hkv, hd] gather never exists. The full
                    # CH-wide write stays safe past a partial tail
                    # chunk for the same reason as the reference lane:
                    # tail garbage lands in OWNED blocks and is
                    # overwritten before any mask exposes it.
                    from ray_lightning_tpu.ops.attention import (
                        PagedPrefillView,
                    )

                    wpos = prefill_pos + jnp.arange(CH)
                    view = PagedPrefillView(
                        tables=row[None], write_block=row[wpos // P][None],
                        write_offset=(wpos % P)[None], use_pallas=True)
                    logits, (pool_k, pool_v) = model.apply(
                        {"params": params}, prefill_tokens[None],
                        cache=(pool_k, pool_v), pos=prefill_pos,
                        paged=view)
                else:
                    kc = pool_k[:, row].reshape(L, 1, G, HKV, HD)
                    vc = pool_v[:, row].reshape(L, 1, G, HKV, HD)
                    logits, (nk, nv) = model.apply(
                        {"params": params}, prefill_tokens[None],
                        cache=(kc, vc), pos=prefill_pos)
                    kw = jax.lax.dynamic_slice_in_dim(
                        nk[:, 0], prefill_pos, CH, axis=1)
                    vw = jax.lax.dynamic_slice_in_dim(
                        nv[:, 0], prefill_pos, CH, axis=1)
                    # the full CH-wide write is safe past a partial tail
                    # chunk: positions >= prompt_len hold garbage the
                    # decode lane overwrites before any mask ever
                    # exposes them
                    wpos = prefill_pos + jnp.arange(CH)
                    wbi = row[wpos // P]
                    pool_k = pool_k.at[:, wbi, wpos % P].set(kw)
                    pool_v = pool_v.at[:, wbi, wpos % P].set(vw)
                done_row = logits[0, prefill_last_row]
                finished = prefill_last_row >= 0
                last_logits = jnp.where(
                    (jnp.arange(C) == slot)[:, None] & finished,
                    done_row[None, :], last_logits)
                return pool_k, pool_v, last_logits

            pool_k, pool_v, last_logits = jax.lax.cond(
                prefill_slot >= 0, do_prefill,
                lambda a, b, c: (a, b, c), pool_k, pool_v, last_logits)
            return pool_k, pool_v, last_logits, new_rngs, emitted

        return step

    def step(params, pool_k, pool_v, last_logits, tables, pos,
             decoding, temp, top_k, rngs, slot_pad, prefill_slots,
             prefill_tokens, prefill_pos, prefill_last_row,
             prefill_pad):
        """The batched-prefill twin (prefill_batch > 1). Extra runtime
        inputs over the single-slot step:

          slot_pad [C] i32      per-slot left pad (0 once unpadded) —
                                the decode lanes mask pad columns and
                                shift RoPE exactly like
                                `generate(prompt_lengths=...)`
          prefill_slots [B] i32 the head FIFO group's slots (-1 =
                                vacant row, scratch-redirected)
          prefill_tokens [B, CH] i32  this chunk of the group's
                                LEFT-PADDED prompts (right-aligned to
                                the shared chunk-multiple width)
          prefill_pos i32       the group's shared cache write offset
          prefill_last_row i32  in-chunk column of every row's last
                                real token (-1: prompts continue; the
                                right-alignment makes it shared)
          prefill_pad [B] i32   per-row left pad within the group
        """
        emitted, new_rngs = _sample(last_logits, decoding, temp, top_k,
                                    rngs)
        pool_k, pool_v, logits2 = _decode(
            params, pool_k, pool_v, tables, pos, decoding, emitted,
            slot_pad)
        last_logits = jnp.where(decoding[:, None], logits2, last_logits)

        # ---- prefill lane: one chunk for the head FIFO group ---------
        def do_prefill(pool_k, pool_v, last_logits):
            slots = jnp.maximum(prefill_slots, 0)
            active = prefill_slots >= 0
            rows = jnp.where(active[:, None], tables[slots], 0)
            wpos = prefill_pos + jnp.arange(CH)
            if fused_prefill:
                # the fused lane: the group's left-padded chunk is
                # scattered straight into owned pool blocks (vacant
                # rows carry all-scratch tables — their writes and
                # reads land in masked block 0) and `paged_prefill`
                # attends causally through the tables; the
                # [L, B, G, Hkv, hd] per-group gather never exists on
                # this path. Pad columns land real K/V in owned blocks
                # exactly as on the reference lane — masked out of
                # every attention forever.
                from ray_lightning_tpu.ops.attention import (
                    PagedPrefillView,
                )

                view = PagedPrefillView(
                    tables=rows, write_block=rows[:, wpos // P],
                    write_offset=jnp.broadcast_to(wpos % P, (B, CH)),
                    use_pallas=True)
                logits, (pool_k, pool_v) = model.apply(
                    {"params": params}, prefill_tokens,
                    cache=(pool_k, pool_v), pos=prefill_pos,
                    pad=prefill_pad, paged=view)
            else:
                kc = pool_k[:, rows].reshape(L, B, G, HKV, HD)
                vc = pool_v[:, rows].reshape(L, B, G, HKV, HD)
                logits, (nk, nv) = model.apply(
                    {"params": params}, prefill_tokens,
                    cache=(kc, vc), pos=prefill_pos, pad=prefill_pad)
                kw = jax.lax.dynamic_slice_in_dim(nk, prefill_pos, CH,
                                                  axis=2)
                vw = jax.lax.dynamic_slice_in_dim(nv, prefill_pos, CH,
                                                  axis=2)
                # pad columns land real K/V in owned blocks; they are
                # masked out of every attention forever (the model's
                # pad contract), so like partial-tail garbage they can
                # never reach an unmasked reduction
                wbi = rows[:, wpos // P]
                woff = jnp.broadcast_to(wpos % P, (B, CH))
                pool_k = pool_k.at[:, wbi, woff].set(kw)
                pool_v = pool_v.at[:, wbi, woff].set(vw)
            done = active & (prefill_last_row >= 0)
            done_rows = logits[:, prefill_last_row]      # [B, V]
            # scatter each finished row's logits into its slot via a
            # one-hot contraction: vacant rows map to slot -1 (never
            # matches), and <= 1 row per slot makes the sum exact
            sel = (jnp.arange(C)[:, None]
                   == jnp.where(done, slots, -1)[None, :])
            contrib = sel.astype(done_rows.dtype) @ done_rows
            last_logits = jnp.where(sel.any(axis=1)[:, None], contrib,
                                    last_logits)
            return pool_k, pool_v, last_logits

        pool_k, pool_v, last_logits = jax.lax.cond(
            jnp.any(prefill_slots >= 0), do_prefill,
            lambda a, b, c: (a, b, c), pool_k, pool_v, last_logits)
        return pool_k, pool_v, last_logits, new_rngs, emitted

    return step


def build_spec_step(model, draft_model, cfg: EngineConfig):
    """The speculative-decoding twin of `build_step` (single-slot
    prefill lane only; reference attention lanes only — the verify
    chunk and the draft's gathered view are priced honestly by
    `serve.audit.speculative_plan`).

    Per tick, per decoding slot, with ``k = cfg.draft.k``:

      1. ``t0 = sample(last_logits)`` — the SAME `_sample` trip as the
         base step (greedy when temp == 0; the scheduler enforces
         greedy-only for draft-armed engines).
      2. The DRAFT model runs ``k`` single-token feedback steps over
         its own paged pool (same block tables), feeding
         ``[t0, d1..d_{k-1}]`` and writing draft K/V at positions
         ``pos..pos+k-1`` — so at full acceptance the draft cache is
         complete through the last accepted position. The k-th greedy
         proposal is discarded.
      3. The TARGET verifies the whole chunk ``[t0, d1..d_{k-1}]`` in
         ONE k-wide call through its chunked cache path (the same
         dense mid-sequence branch chunked prefill rides), writing
         target K/V at ``pos..pos+k-1`` and producing logits
         ``l_0..l_{k-1}`` where ``g_{j+1} = argmax(l_j)`` is the token
         plain greedy decode would emit after position ``pos+j``.
      4. Greedy accept: ``m`` = longest prefix with ``d_j == g_j``
         (cumprod of the match mask). The slot emits
         ``[t0, g_1..g_m]`` (``n_emit = 1 + m``) and carries
         ``last_logits = l_m`` so ``g_{m+1}`` becomes the NEXT tick's
         ``t0`` — emitted exactly once. K/V written past ``pos+m`` is
         conditioned on rejected tokens; it is causally masked
         (kv_pos <= q_pos) and overwritten before the stream ever
         reaches it, the same partial-tail-garbage discipline as
         chunked prefill. ``k = 1`` reduces to the base step's math
         exactly (no proposals, one verify row, ``m = 0``).

    Returns ``(pool_k, pool_v, dpool_k, dpool_v, last_logits, rngs',
    toks [C, k] i32, n_emit [C] i32)``.
    """
    assert cfg.prefill_batch == 1 and cfg.draft is not None
    mcfg, dcfg = model.cfg, draft_model.cfg
    spec = cfg.pool_spec
    L, HKV, HD = mcfg.n_layers, mcfg.n_kv_heads, mcfg.head_dim
    DL, DHKV, DHD = dcfg.n_layers, dcfg.n_kv_heads, dcfg.head_dim
    C, P, G, CH = cfg.capacity, spec.block_size, spec.gathered_len, \
        cfg.prefill_chunk
    K = cfg.draft.k

    def _draft_one(dparams, tok, kc, vc, pos):
        logits, (nk, nv) = draft_model.apply(
            {"params": dparams}, tok[None, None],
            cache=(kc[:, None], vc[:, None]), pos=pos)
        k_tok = jax.lax.dynamic_slice_in_dim(nk[:, 0], pos, 1,
                                             axis=1)[:, 0]
        v_tok = jax.lax.dynamic_slice_in_dim(nv[:, 0], pos, 1,
                                             axis=1)[:, 0]
        return logits[0, 0], k_tok, v_tok

    def _verify_one(params, toks, kc, vc, pos):
        # the target's K-wide chunk through its own chunked cache path
        # — the multi-token-advance machinery chunked prefill built
        logits, (nk, nv) = model.apply(
            {"params": params}, toks[None],
            cache=(kc[:, None], vc[:, None]), pos=pos)
        kw = jax.lax.dynamic_slice_in_dim(nk[:, 0], pos, K, axis=1)
        vw = jax.lax.dynamic_slice_in_dim(nv[:, 0], pos, K, axis=1)
        return logits[0], kw, vw

    def step(params, dparams, pool_k, pool_v, dpool_k, dpool_v,
             last_logits, tables, pos, decoding, temp, top_k, rngs,
             prefill_slot, prefill_tokens, prefill_pos,
             prefill_last_row):
        """One speculative tick. Donated: both pools + last_logits
        (positions 2-6). Runtime inputs as in the base step."""
        # ---- t0: the carried token, sampled exactly like the base ---
        keys = jax.random.wrap_key_data(rngs)
        split = jax.vmap(jax.random.split)(keys)
        new_rngs = jnp.where(decoding[:, None],
                             jax.random.key_data(split[:, 0]), rngs)
        t0 = jax.vmap(_sample_one)(last_logits, split[:, 1], temp,
                                   top_k)

        # ---- draft lane: K feedback trips over the draft pool --------
        def propose(carry, _):
            dpk, dpv, tok, off = carry
            gk = dpk[:, tables].reshape(DL, C, G, DHKV, DHD)
            gv = dpv[:, tables].reshape(DL, C, G, DHKV, DHD)
            wp = pos + off
            dlogits, k_tok, v_tok = jax.vmap(
                _draft_one, in_axes=(None, 0, 1, 1, 0),
                out_axes=(0, 1, 1),
            )(dparams, tok, gk, gv, wp)
            bi = jnp.where(
                decoding,
                jnp.take_along_axis(tables, (wp // P)[:, None],
                                    axis=1)[:, 0],
                0)
            woff = jnp.where(decoding, wp % P, 0)
            dpk = dpk.at[:, bi, woff].set(k_tok)
            dpv = dpv.at[:, bi, woff].set(v_tok)
            nxt = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
            return (dpk, dpv, nxt, off + 1), tok

        (dpool_k, dpool_v, _, _), chunk = jax.lax.scan(
            propose, (dpool_k, dpool_v, t0, jnp.int32(0)), None,
            length=K)
        chunk = jnp.moveaxis(chunk, 0, 1)   # [C, K] = [t0, d1..d_{K-1}]

        # ---- verify lane: ONE K-wide target chunk per slot -----------
        gk = pool_k[:, tables].reshape(L, C, G, HKV, HD)
        gv = pool_v[:, tables].reshape(L, C, G, HKV, HD)
        vlogits, kw, vw = jax.vmap(
            _verify_one, in_axes=(None, 0, 1, 1, 0), out_axes=(0, 1, 1),
        )(params, chunk, gk, gv, pos)        # [C, K, V], [L, C, K, ...]
        wp = pos[:, None] + jnp.arange(K)[None, :]          # [C, K]
        bi = jnp.where(decoding[:, None],
                       jnp.take_along_axis(tables, wp // P, axis=1), 0)
        woff = jnp.where(decoding[:, None], wp % P, 0)
        pool_k = pool_k.at[:, bi, woff].set(kw)
        pool_v = pool_v.at[:, bi, woff].set(vw)

        # ---- greedy accept ------------------------------------------
        g = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)  # [C, K]
        ok = jnp.cumprod(
            (chunk[:, 1:] == g[:, :-1]).astype(jnp.int32), axis=1)
        m = ok.sum(axis=1).astype(jnp.int32)                # [C]
        n_emit = jnp.where(decoding, 1 + m, 0).astype(jnp.int32)
        # emitted stream: t0 then g_1..g_m. g_{m+1} is NOT emitted —
        # carrying l_m makes it the next tick's t0, emitted once there.
        toks = jnp.concatenate([t0[:, None], g[:, :-1]], axis=1)
        picked = jnp.take_along_axis(
            vlogits, m[:, None, None], axis=1)[:, 0]        # [C, V]
        last_logits = jnp.where(decoding[:, None], picked, last_logits)

        # ---- prefill lane: reference chunk, target AND draft ---------
        def do_prefill(pool_k, pool_v, dpool_k, dpool_v, last_logits):
            slot = jnp.maximum(prefill_slot, 0)
            row = tables[slot]
            kc = pool_k[:, row].reshape(L, 1, G, HKV, HD)
            vc = pool_v[:, row].reshape(L, 1, G, HKV, HD)
            logits, (nk, nv) = model.apply(
                {"params": params}, prefill_tokens[None],
                cache=(kc, vc), pos=prefill_pos)
            kw = jax.lax.dynamic_slice_in_dim(
                nk[:, 0], prefill_pos, CH, axis=1)
            vw = jax.lax.dynamic_slice_in_dim(
                nv[:, 0], prefill_pos, CH, axis=1)
            wpos = prefill_pos + jnp.arange(CH)
            wbi = row[wpos // P]
            pool_k = pool_k.at[:, wbi, wpos % P].set(kw)
            pool_v = pool_v.at[:, wbi, wpos % P].set(vw)
            # the draft rides the SAME chunk/window so its cache tracks
            # the target position for position — its logits are unused
            # during prefill (the first proposal each tick feeds t0)
            dkc = dpool_k[:, row].reshape(DL, 1, G, DHKV, DHD)
            dvc = dpool_v[:, row].reshape(DL, 1, G, DHKV, DHD)
            _, (dnk, dnv) = draft_model.apply(
                {"params": dparams}, prefill_tokens[None],
                cache=(dkc, dvc), pos=prefill_pos)
            dkw = jax.lax.dynamic_slice_in_dim(
                dnk[:, 0], prefill_pos, CH, axis=1)
            dvw = jax.lax.dynamic_slice_in_dim(
                dnv[:, 0], prefill_pos, CH, axis=1)
            dpool_k = dpool_k.at[:, wbi, wpos % P].set(dkw)
            dpool_v = dpool_v.at[:, wbi, wpos % P].set(dvw)
            done_row = logits[0, prefill_last_row]
            finished = prefill_last_row >= 0
            last_logits = jnp.where(
                (jnp.arange(C) == slot)[:, None] & finished,
                done_row[None, :], last_logits)
            return pool_k, pool_v, dpool_k, dpool_v, last_logits

        pool_k, pool_v, dpool_k, dpool_v, last_logits = jax.lax.cond(
            prefill_slot >= 0, do_prefill,
            lambda *a: a, pool_k, pool_v, dpool_k, dpool_v, last_logits)
        return (pool_k, pool_v, dpool_k, dpool_v, last_logits,
                new_rngs, toks, n_emit)

    return step


def _copy_pool_block(pk, pv, src, dst):
    """Copy one block's K/V within a (donated) pool pair — the
    copy-on-write fork primitive. Jitted separately from the step so
    the engine's `compile_count` pin (== 1) is undisturbed."""
    return (pk.at[:, dst].set(pk[:, src]),
            pv.at[:, dst].set(pv[:, src]))


def idle_prefill(cfg: EngineConfig):
    """The step's no-prefill sentinel: (slot, tokens, pos, last_row)
    for the single-slot lane, (slots, tokens, pos, last_row, pads) for
    the batched lane."""
    if cfg.prefill_batch == 1:
        return (np.int32(-1), np.zeros(cfg.prefill_chunk, np.int32),
                np.int32(0), np.int32(-1))
    B = cfg.prefill_batch
    return (np.full(B, -1, np.int32),
            np.zeros((B, cfg.prefill_chunk), np.int32),
            np.int32(0), np.int32(-1), np.zeros(B, np.int32))


def _global_put(x, sharding):
    """Place a host array as a GLOBAL jax array under ``sharding`` —
    single- or multi-process alike. Every process holds the full value
    (params come off the same npz, runtime inputs off the same
    lockstep scheduler), so each process carves out its addressable
    devices' slices and assembles the global view — the
    `resilience.faults` respawn-placement idiom. `jax.device_put`
    cannot do this cross-process in general (non-addressable devices),
    and `make_array_from_process_local_data` expects per-process
    SHARDS, not the replicated whole."""
    x = np.asarray(x)
    idx_map = sharding.addressable_devices_indices_map(x.shape)
    arrs = [jax.device_put(x[idx], d) for d, idx in idx_map.items()]
    return jax.make_array_from_single_device_arrays(
        x.shape, sharding, arrs)


def serving_param_specs(model, params, axis_names):
    """Per-leaf ``(path, PartitionSpec)`` list (tree_leaves order) for
    a replica's weights: the model's published per-leaf specs
    (`model.param_specs`, e.g. `models.llama.llama_param_specs` —
    wqkv/gate_up column-split, wo/w_down row-split, embeddings
    vocab-split) looked up by exact leaf path, every unknown leaf
    REPLICATED. Specs naming axes outside ``axis_names`` fall back to
    replicated too — serving meshes are tensor-only. Shared by the
    engine's device placement and `serve.audit`'s collective pricing,
    so the audited layout IS the served one."""
    from jax.sharding import PartitionSpec

    from ray_lightning_tpu.utils.pytree import named_leaves

    if hasattr(model, "param_specs"):       # the trainer-side wrapper
        specs = model.param_specs(params)
    elif hasattr(model, "cfg"):
        # the flax module the engine serves: the published llama
        # placement keyed off its config
        from ray_lightning_tpu.models.llama import llama_param_specs

        specs = llama_param_specs(model.cfg)
    else:
        specs = {}
    axes = set(axis_names)
    out = []
    for path, _ in named_leaves(params):
        spec = specs.get(path)
        if spec is None or any(
                ax not in axes
                for entry in tuple(spec) if entry is not None
                for ax in ((entry,) if isinstance(entry, str) else entry)):
            spec = PartitionSpec()
        out.append((path, spec))
    return out


def serving_param_shardings(model, params, mesh):
    """`serving_param_specs` as per-leaf NamedShardings on the
    replica's own mesh (the pytree `DecodeEngine` places weights
    with)."""
    from jax.sharding import NamedSharding

    flat = [NamedSharding(mesh, spec) for _, spec in
            serving_param_specs(model, params, mesh.axis_names)]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params), flat)


class DecodeEngine:
    """One replica's compiled step + its device-resident buffers.

    Owns ``pool_k/pool_v/last_logits`` (donated through every step —
    callers must never hold references to them) and the compile-count
    pin. The host-side request state lives in `serve.scheduler`.
    """

    def __init__(self, model, params, cfg: EngineConfig,
                 max_seq_len_check: bool = True,
                 use_pallas: Optional[bool] = None,
                 metrics=None, mesh=None,
                 draft_model=None, draft_params=None):
        if max_seq_len_check and cfg.max_slot_len > model.cfg.max_seq_len:
            raise ValueError(
                f"engine max_slot_len {cfg.max_slot_len} exceeds the "
                f"model's max_seq_len {model.cfg.max_seq_len} — RoPE "
                "tables would be read out of range")
        self.model = model
        # the attention-path decision is made ONCE, at build time, by
        # the same predicate the op's dispatch uses (flash discipline:
        # ops.attention.paged_attention_uses_pallas) — on TPU (or under
        # force_pallas/RLT_PALLAS with interpret mode) and a tiling
        # shape, the decode lane is the fused paged-attention kernel
        # and the dense gathered view is never built; otherwise the
        # reference lane, the bitwise anchor against generate().
        from ray_lightning_tpu.ops.attention import (
            paged_attention_uses_pallas,
            paged_prefill_uses_pallas,
        )

        spec = cfg.pool_spec
        if use_pallas is None and not model.cfg.use_flash:
            use_pallas = False  # reference-forced model config
        pool_shape = (spec.n_blocks, spec.block_size,
                      model.cfg.n_kv_heads, model.cfg.head_dim)
        self.fused = paged_attention_uses_pallas(
            (cfg.capacity, model.cfg.n_heads, model.cfg.head_dim),
            pool_shape, use_pallas)
        # the PREFILL lane's dispatch is decided the same way, once,
        # here — the two kernels have separate shape gates (the prefill
        # kernel additionally tiles the chunk width), so the decisions
        # are independent but share the use_pallas resolution
        self.fused_prefill = paged_prefill_uses_pallas(
            (cfg.prefill_batch, cfg.prefill_chunk, model.cfg.n_heads,
             model.cfg.head_dim),
            pool_shape, use_pallas)
        self.draft_model = draft_model
        self.dpool_k = self.dpool_v = self.draft_params = None
        if cfg.draft is not None:
            if draft_model is None or draft_params is None:
                raise ValueError(
                    "cfg.draft is set but no draft model/params were "
                    "given — pass draft_model= and draft_params=")
            if mesh is not None:
                raise ValueError(
                    "speculative decoding requires an unsharded "
                    "replica (mesh=None)")
            if draft_model.cfg.vocab_size != model.cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_model.cfg.vocab_size} != "
                    f"target vocab {model.cfg.vocab_size} — greedy "
                    "verify compares token ids across the two models")
            if max_seq_len_check and \
                    cfg.max_slot_len > draft_model.cfg.max_seq_len:
                raise ValueError(
                    f"engine max_slot_len {cfg.max_slot_len} exceeds "
                    f"the DRAFT model's max_seq_len "
                    f"{draft_model.cfg.max_seq_len}")
            # the verify chunk and the draft feedback trips run the
            # reference lanes only — the fused kernels are single-token
            # / prefill shaped. Priced honestly: serve.audit's
            # speculative_plan charges the gathered views.
            self.fused = False
            self.fused_prefill = False
        self.cfg = cfg
        self.spec = cfg.pool_spec
        #: replica-group mesh (docs/SERVING.md "sharded replicas"):
        #: None = the historical single-device replica; a mesh with a
        #: ``tensor`` axis lowers the SAME one-compile step as an SPMD
        #: program — params shard per `models.llama.llama_param_specs`,
        #: the pool shards over KV heads, and every runtime input +
        #: sampled output stays replicated so the host-side scheduler
        #: (which lives on every rank, lockstep) is tp-oblivious.
        self.mesh = mesh
        self.tp = 1 if mesh is None else int(mesh.shape.get("tensor", 1))
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            validate_pool_tp(model.cfg, self.tp)
            self._repl_sh = NamedSharding(mesh, PartitionSpec())
            pool_sh = NamedSharding(mesh,
                                    pool_partition_spec(self.tp))
            param_sh = serving_param_shardings(model, params, mesh)
            self.params = jax.tree_util.tree_map(_global_put, params,
                                                 param_sh)
            # out shardings pin the donated-buffer cycle: pool in/out
            # identical (donation holds), logits + rngs + emitted
            # replicated so every rank reads the same host values
            self._step = jax.jit(
                build_step(model, cfg, fused=self.fused,
                           fused_prefill=self.fused_prefill),
                donate_argnums=(1, 2, 3),
                out_shardings=(pool_sh, pool_sh, self._repl_sh,
                               self._repl_sh, self._repl_sh))
            pool_k, pool_v = init_pool(model.cfg, self.spec)
            self.pool_k = _global_put(pool_k, pool_sh)
            self.pool_v = _global_put(pool_v, pool_sh)
            self.last_logits = _global_put(
                jnp.zeros((cfg.capacity, model.cfg.vocab_size),
                          jnp.float32), self._repl_sh)
        else:
            # canonicalize the weights' placement: trainer-produced
            # params arrive committed to a NamedSharding over the
            # training mesh, and a step closed over those emits
            # NamedSharding outputs — so the donated pool buffers
            # (built SingleDeviceSharding by init_pool) change
            # signature after the first tick and the step compiles a
            # SECOND executable (observed in the fine-tune -> serve
            # flow; test-pinned). Committing the weights to one
            # concrete device keeps every signature
            # SingleDeviceSharding from the first tick on.
            self.params = jax.device_put(params, jax.devices()[0])
            if cfg.draft is not None:
                # donated: both pools + last_logits (positions 2-6 of
                # the spec signature — params/draft params stay)
                self._step = jax.jit(
                    build_spec_step(model, draft_model, cfg),
                    donate_argnums=(2, 3, 4, 5, 6))
            else:
                self._step = jax.jit(
                    build_step(model, cfg, fused=self.fused,
                               fused_prefill=self.fused_prefill),
                    donate_argnums=(1, 2, 3))
            # COMMIT the device-resident buffers to the same device as
            # the weights: a fresh jnp.zeros is uncommitted, but the
            # step's outputs are committed, so an uncommitted
            # first-tick signature would compile a second executable
            # the moment the donated outputs cycle back in (same
            # phantom-recompile class as the params placement above;
            # the churn pin covers both)
            device = jax.devices()[0]
            pool_k, pool_v = init_pool(model.cfg, self.spec)
            self.pool_k = jax.device_put(pool_k, device)
            self.pool_v = jax.device_put(pool_v, device)
            self.last_logits = jax.device_put(
                jnp.zeros((cfg.capacity, model.cfg.vocab_size),
                          jnp.float32),
                device)
            if cfg.draft is not None:
                self.draft_params = jax.device_put(draft_params, device)
                dpk, dpv = init_pool(draft_model.cfg, self.spec)
                self.dpool_k = jax.device_put(dpk, device)
                self.dpool_v = jax.device_put(dpv, device)
        # the copy-on-write fork primitive (scheduler-driven): its own
        # tiny jit so the step's compile_count pin is undisturbed
        self._copy = jax.jit(_copy_pool_block, donate_argnums=(0, 1))
        self.steps = 0
        # live metrics (telemetry/metrics.py): per-tick prefill/decode
        # token counts + the compile counter. The registry NEVER enters
        # build_step — metrics on or off lowers a byte-identical
        # program (test-pinned), and every recorded value is computed
        # from the host-owned numpy inputs the tick already received
        # (no new host syncs). Assignable after construction: the serve
        # loop arms it once the run dir is known.
        from ray_lightning_tpu.telemetry.metrics import NULL_METRICS

        self.metrics = metrics if metrics is not None else NULL_METRICS

    # ---- compile accounting ---------------------------------------------

    @property
    def attention_path(self) -> str:
        """Which decode attention ran for this replica's lifetime —
        surfaced by the bench serving leg and the smoke verdicts."""
        return "paged-pallas" if self.fused else "reference-gather"

    @property
    def prefill_path(self) -> str:
        """Which prefill attention ran — the prefill twin of
        `attention_path` (the fused lane retires the per-group
        gathered view; docs/SERVING.md 'paged prefill kernel')."""
        return "paged-pallas" if self.fused_prefill else \
            "reference-gather"

    @property
    def compile_count(self) -> int:
        """Distinct compiled programs behind the step — the churn gate
        pins this at 1. Falls back to -1 (unknown) on a jax without the
        cache-size introspection rather than failing serving."""
        try:
            return int(self._step._cache_size())
        except Exception:  # noqa: BLE001 — introspection is advisory
            return -1

    def warmup(self) -> None:
        """Compile (or deserialize, when a persistent compile cache is
        armed — `pipeline.compile_cache`) the step before the replica
        is marked live: an idle tick on the zero pool. P99 TTFT is a
        compile-cache metric (ROADMAP item 1)."""
        C = self.cfg.capacity
        self.tick(
            tables=np.zeros((C, self.spec.blocks_per_slot), np.int32),
            pos=np.zeros(C, np.int32),
            decoding=np.zeros(C, bool),
            temp=np.zeros(C, np.float32),
            top_k=np.zeros(C, np.int32),
            rngs=np.zeros((C, 2), np.uint32),
            prefill=idle_prefill(self.cfg),
            pad=np.zeros(C, np.int32),
        )

    # ---- copy-on-write fork ----------------------------------------------

    def copy_block(self, src: int, dst: int) -> None:
        """Copy pool block ``src`` into ``dst`` (K and V; the draft
        pool too when speculative decoding is armed) — the scheduler's
        fork primitive: before a prefill chunk's write window touches a
        block with refcount > 1, the slot's table is repointed at a
        fresh block populated by this copy, so a shared block is never
        written by a non-exclusive owner."""
        s, d = jnp.int32(src), jnp.int32(dst)
        self.pool_k, self.pool_v = self._copy(self.pool_k, self.pool_v,
                                              s, d)
        if self.dpool_k is not None:
            self.dpool_k, self.dpool_v = self._copy(
                self.dpool_k, self.dpool_v, s, d)

    # ---- the tick --------------------------------------------------------

    def tick(self, tables, pos, decoding, temp, top_k, rngs, prefill,
             pad=None):
        """Run one step; returns ``(toks [C, W] i32 np, n_emit [C] i32
        np, rngs' [C, 2] u32 np)`` — ``toks[s, :n_emit[s]]`` are slot
        s's tokens this tick, oldest first. W == 1 on the base step
        (``n_emit`` = the decoding mask); W == cfg.draft.k on a
        speculative engine, where ``n_emit`` counts the carried token
        plus accepted proposals. The donated device buffers are swapped
        internally. ``pad`` ([C] i32 per-slot left pad) exists only on
        the batched-prefill program (prefill_batch > 1) and is ignored
        otherwise — the single-slot program is the historical one, with
        no pad inputs."""
        if self.mesh is None:
            put = jnp.asarray
        else:
            # every runtime input is replicated over the replica's own
            # mesh: each rank computed the SAME host values (lockstep
            # scheduler), so assembling the global view is pure
            # placement, no wire traffic
            def put(x):
                return _global_put(x, self._repl_sh)
        spec_mode = self.cfg.draft is not None
        if spec_mode:
            common = (
                self.params, self.draft_params, self.pool_k,
                self.pool_v, self.dpool_k, self.dpool_v,
                self.last_logits,
                put(tables), put(pos), put(decoding),
                put(temp), put(top_k), put(rngs))
        else:
            common = (
                self.params, self.pool_k, self.pool_v, self.last_logits,
                put(tables), put(pos), put(decoding),
                put(temp), put(top_k), put(rngs))
        if self.cfg.prefill_batch == 1:
            pslot, ptoks, ppos, plast = prefill
            args = common + (put(pslot), put(ptoks),
                             put(ppos), put(plast))
        else:
            if pad is None:
                pad = np.zeros(self.cfg.capacity, np.int32)
            pslot, ptoks, ppos, plast, ppad = prefill
            args = common + (put(pad), put(pslot),
                             put(ptoks), put(ppos),
                             put(plast), put(ppad))
        if spec_mode:
            (self.pool_k, self.pool_v, self.dpool_k, self.dpool_v,
             self.last_logits, new_rngs, toks, n_emit) = \
                self._step(*args)
            toks = np.array(toks)
            n_emit = np.array(n_emit)
        else:
            (self.pool_k, self.pool_v, self.last_logits, new_rngs,
             emitted) = self._step(*args)
        self.steps += 1
        m = self.metrics
        if m.enabled:
            # counted from the HOST-OWNED inputs this call received —
            # the device outputs above stay un-inspected on the base
            # step, so metrics adds zero host syncs (the spec step's
            # n_emit is already a host-fetched output the scheduler
            # needs anyway). prefill_tokens counts chunk positions
            # advanced (incl. pad columns on the batched lane);
            # decode_tokens counts tokens emitted.
            n_dec = int(n_emit.sum()) if spec_mode else \
                int(np.sum(np.asarray(decoding)))
            if self.cfg.prefill_batch == 1:
                n_pf_rows = 1 if int(prefill[0]) >= 0 else 0
            else:
                n_pf_rows = int(np.sum(np.asarray(prefill[0]) >= 0))
            if n_dec:
                m.count("decode_tokens", n_dec)
            if n_pf_rows:
                m.count("prefill_tokens",
                        n_pf_rows * self.cfg.prefill_chunk)
            m.gauge("engine_steps", self.steps)
            m.gauge("compile_count", self.compile_count)
        if spec_mode:
            return toks, n_emit, np.array(new_rngs)
        if self.mesh is not None:
            # replicated outputs: any addressable shard IS the global
            # value — np.array on a multi-process global array would
            # raise (non-addressable devices)
            emitted = np.array(emitted.addressable_data(0))
            new_rngs = np.array(new_rngs.addressable_data(0))
        else:
            emitted = np.array(emitted)
            new_rngs = np.array(new_rngs)
        return (emitted[:, None],
                np.asarray(decoding).astype(np.int32), new_rngs)
